"""Federation controller core.

The orchestration state machine: learner registry, task lifecycle, model
store, aggregation driver, round-metadata lineage. Capability equivalent of
the reference's C++ ``Controller``/``ControllerDefaultImpl``
(reference metisfl/controller/core/controller.cc: AddLearner :98-168,
RemoveLearner :170-199, LearnerCompletedTask :201-259, ScheduleTasks
:428-518, UpdateLearnersTaskTemplates :520-569, ComputeCommunityModel
:795-950), redesigned:

- Models are flat ``{name: np.ndarray}`` dicts controller-side (no byte-blob
  per-variable arithmetic); aggregation is one jit-compiled XLA computation.
- Concurrency: RPC threads only enqueue; a single-worker scheduling executor
  owns all round logic, so a learner's completion ack never blocks on
  aggregation (the reference pushes ScheduleTasks onto a thread pool for the
  same reason, controller.cc:246-255) and state needs one lock, not two.
- Transport is pluggable (:class:`LearnerProxy`): in-process calls for tests
  and pod-mode, gRPC for cross-host federations.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import os
import random
import resource
import threading
import time
import uuid
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from functools import partial
from typing import (Any, Callable, Dict, List, Optional, Protocol, Sequence,
                    Tuple)

import numpy as np

from metisfl_tpu.aggregation import make_aggregation_rule
from metisfl_tpu.aggregation.secure import SecureAgg
from metisfl_tpu.comm.codec import dumps as codec_dumps
from metisfl_tpu.comm.codec import loads as codec_loads
from metisfl_tpu.comm.messages import (
    EvalResult,
    EvalTask,
    JoinReply,
    JoinRequest,
    TaskResult,
    TrainParams,
    TrainTask,
)
from metisfl_tpu.config import FederationConfig
from metisfl_tpu.scaling import (apply_staleness_decay, make_scaler,
                                 raw_weight, staleness_factor)
from metisfl_tpu.scheduling import SemiSynchronousScheduler, make_scheduler
from metisfl_tpu.selection import ChurnTracker, make_selector
from metisfl_tpu.store import EvictionPolicy, make_store
from metisfl_tpu.store import durable as _durable
from metisfl_tpu import telemetry as _tel
from metisfl_tpu.telemetry import events as _tevents
from metisfl_tpu.telemetry import metrics as _tmetrics
from metisfl_tpu.telemetry import prof as _tprof
from metisfl_tpu.telemetry import profile as _tprofile
from metisfl_tpu.telemetry import trace as _ttrace
from metisfl_tpu.telemetry.health import HealthMonitor, finite_metrics
from metisfl_tpu.tensor.pytree import ModelBlob
from metisfl_tpu.tensor.spec import quantify

logger = logging.getLogger("metisfl_tpu.controller")

# Round-lifecycle metrics: scraped live via GetMetrics / the /metrics
# listener while the lineage equivalents (RoundMetadata) stay post-hoc.
# Names come from the shared constants in telemetry/__init__.py — a typo
# fails at import instead of minting a new series (SURVEY.md §5.5).
_REG = _tmetrics.registry()
_M_ROUND_DURATION = _REG.histogram(
    _tel.M_ROUND_DURATION_SECONDS, "Federation round wall-clock")
_M_ROUNDS = _REG.counter(_tel.M_ROUNDS_TOTAL, "Completed federation rounds")
_M_PHASE = _REG.histogram(
    _tel.M_ROUND_PHASE_DURATION_SECONDS,
    "Per-phase round durations (dispatch/wait_uplinks/select/aggregate/"
    "aggregate_block/store_insert/close)", ("phase",))
_M_UPLINK = _REG.counter(
    _tel.M_UPLINK_BYTES_TOTAL, "Model bytes received from learners",
    ("learner",), budget_label="learner")
_M_ACTIVE_LEARNERS = _REG.gauge(
    _tel.M_CONTROLLER_ACTIVE_LEARNERS, "Currently registered learners")
_M_AGG_FAILURES = _REG.counter(
    _tel.M_AGGREGATION_FAILURES_TOTAL, "Aggregation attempts that raised")
_M_STRAGGLER = _REG.gauge(
    _tel.M_LEARNER_STRAGGLER_SCORE,
    "Round-relative straggler score: EWMA train duration over the "
    "cohort median (1.0 = typical, >1 = slower)", ("learner",),
    budget_label="learner")
_M_DIVERGENCE = _REG.gauge(
    _tel.M_LEARNER_DIVERGENCE_SCORE,
    "Learning-health divergence score: EWMA of the cohort-median/MAD "
    "robust z of each update's deviation from the cohort mean "
    "(0 = typical, higher = pulling against the cohort)", ("learner",),
    budget_label="learner")
_M_ROUND_UPDATE_NORM = _REG.gauge(
    _tel.M_ROUND_UPDATE_NORM,
    "L2 norm of the latest community-model update (telemetry/health.py)")
# churn-tolerant scheduling (quorum barriers, dispatch retry, admission)
_M_DROPPED = _REG.counter(
    _tel.M_LEARNER_DROPPED_TOTAL,
    "Learner contributions dropped from rounds, by cause "
    "(deadline straggler, quorum straggler, leave, quarantine)",
    ("reason",))
_M_DISPATCH_RETRIES = _REG.counter(
    _tel.M_DISPATCH_RETRIES_TOTAL,
    "Failed train dispatches retried to replacement learners "
    "(scheduling.dispatch_retries)")
_M_REDISPATCH = _REG.counter(
    _tel.M_ROUNDS_REDISPATCHED_TOTAL,
    "Rounds abandoned and re-dispatched to a fresh cohort (no-reporter "
    "deadline, whole-cohort departure, aggregation-failure retry)")
_M_CHURN = _REG.gauge(
    _tel.M_LEARNER_CHURN_SCORE,
    "Churn/flap score: EWMA of leave, flap-rejoin, and failed-dispatch "
    "events (0 = stable, approaching 1 = flapping; selection.py "
    "ChurnTracker)", ("learner",), budget_label="learner")
_M_WAL_RECORDS = _REG.counter(
    _tel.M_CONTROLLER_WAL_RECORDS_TOTAL,
    "Hot-standby round-state WAL records appended, by kind "
    "(snapshot/join/leave; controller/wal.py)", ("kind",))
# masked partial-fold plane (secure/distributed.py + secure/recovery.py)
_M_SECURE_SETTLEMENT = _REG.histogram(
    _tel.M_SECURE_SETTLEMENT_SECONDS,
    "Mask settlement duration: contributor reconciliation through "
    "residual disclosure and fixed-point decode")
_M_SECURE_RECOVERED = _REG.counter(
    _tel.M_SECURE_RECOVERED_PARTIES_TOTAL,
    "Dropped mask parties recovered via seed-share disclosure")
_M_SECURE_FOLDS = _REG.counter(
    _tel.M_SECURE_MASKED_FOLDS_TOTAL,
    "Masked partial folds performed, by tier", ("tier",))

# EWMA smoothing for per-learner train/eval durations (straggler
# analytics): ~the last 3-4 rounds dominate, so a recovered learner's
# score decays within a few rounds instead of dragging forever
_EWMA_ALPHA = 0.3


def _ewma(prev: float, observation: float) -> float:
    """First observation seeds the average; later ones alpha-blend."""
    if prev <= 0.0:
        return observation
    return _EWMA_ALPHA * observation + (1.0 - _EWMA_ALPHA) * prev


class LearnerProxy(Protocol):
    """Controller → learner transport for one registered learner."""

    def run_task(self, task: TrainTask) -> None:
        """Fire-and-forget local-training dispatch."""
        ...

    def evaluate(self, task: EvalTask, callback: Callable[[EvalResult], None]) -> None:
        """Non-blocking evaluation; ``callback`` runs on completion."""
        ...

    def shutdown(self) -> None:
        ...


@dataclass
class LearnerRecord:
    learner_id: str
    auth_token: str
    hostname: str = "localhost"
    port: int = 0
    num_train_examples: int = 0
    num_val_examples: int = 0
    num_test_examples: int = 0
    # latest task execution metadata (feeds scalers + semi-sync recompute)
    completed_batches: int = 0
    ms_per_step: float = 0.0
    # consecutive failed train dispatches (liveness; reset on completion)
    dispatch_failures: int = 0
    # round the latest accepted contribution was DISPATCHED from (async
    # staleness: a result computed against an old community model)
    last_result_round: int = -1
    # masking secure-agg party index (-1: not a masking party) — maps this
    # learner to its pairwise-mask identity for dropout recovery
    party_index: int = -1
    # per-learner train overrides (semi-sync step budgets)
    local_steps_override: int = 0
    # EWMA dispatch→completion durations (straggler analytics; feeds the
    # DescribeFederation snapshot and learner_straggler_score)
    ewma_train_s: float = 0.0
    ewma_eval_s: float = 0.0
    proxy: Optional[LearnerProxy] = None


@dataclass
class RoundMetadata:
    """Per-round runtime trace — the reference's FederatedTaskRuntimeMetadata
    (metis.proto:342-365) rebuilt as a plain record."""

    global_iteration: int = 0
    started_at: float = 0.0
    completed_at: float = 0.0
    train_submitted_at: Dict[str, float] = field(default_factory=dict)
    train_received_at: Dict[str, float] = field(default_factory=dict)
    eval_submitted_at: Dict[str, float] = field(default_factory=dict)
    eval_received_at: Dict[str, float] = field(default_factory=dict)
    selected_learners: List[str] = field(default_factory=list)
    aggregation_block_sizes: List[int] = field(default_factory=list)
    aggregation_block_duration_ms: List[float] = field(default_factory=list)
    aggregation_duration_ms: float = 0.0
    # phase breakdown sourced from the round's telemetry spans (trace and
    # lineage agree by construction): total train-dispatch time and the
    # dispatch-to-barrier-release wait. Absent in pre-telemetry payloads —
    # stats.py renders those unchanged.
    dispatch_duration_ms: float = 0.0
    wait_duration_ms: float = 0.0
    # the contribution weights actually applied this round (post scaler and
    # staleness damping) — reference lineage has nothing comparable
    scales: Dict[str, float] = field(default_factory=dict)
    # per-uplink dispatch-version lag at aggregation time (rounds the
    # community model advanced between a task's dispatch and its uplink
    # entering this aggregate) — nonzero only under the asynchronous
    # protocols / quorum stragglers; zero entries are omitted so silo
    # runs' lineage is unchanged
    staleness: Dict[str, float] = field(default_factory=dict)
    model_insertion_duration_ms: Dict[str, float] = field(default_factory=dict)
    model_size: Dict[str, int] = field(default_factory=dict)
    # bytes each learner actually sent this round (the wire-compression
    # ladder — ship_dtype bf16/int8q/topk — shows up here as 2-32x
    # smaller uplinks; the reference tracks only decoded tensor sizes)
    uplink_bytes: Dict[str, int] = field(default_factory=dict)
    peak_rss_kb: int = 0
    # per-learner training metrics as shipped in TaskResult: the final
    # train_metrics dict and the per-epoch trajectory. Previously
    # collected on the wire but dropped controller-side — now they land
    # in experiment.json so stats.py can render per-learner convergence
    # (absent in pre-health payloads; readers fall back gracefully).
    train_metrics: Dict[str, Dict[str, float]] = field(default_factory=dict)
    epoch_metrics: Dict[str, List[Dict[str, float]]] = field(
        default_factory=dict)
    # learning-health snapshot for this round (telemetry/health.py):
    # community update norm, effective step, participation entropy,
    # per-learner update norms / cohort cosines / divergence scores.
    # Empty when telemetry.health is off or under secure aggregation.
    health: Dict[str, Any] = field(default_factory=dict)
    # model-lifecycle lineage (registry/registry.py): the candidate
    # version this round's aggregate registered as, and the stable head
    # at round close. 0 when the registry is off — pre-registry payloads
    # lack the keys entirely and stats.py renders them unchanged.
    registered_version: int = 0
    stable_version: int = 0
    # per-round cost profile (telemetry/profile.py RoundProfile): phase
    # waterfall, per-learner wire-byte/codec/device attribution, store
    # timings. Empty when the performance observatory is off — pre-profile
    # payloads lack the key and stats.py renders them unchanged.
    profile: Dict[str, Any] = field(default_factory=dict)
    # cardinality-budget snapshot (telemetry/metrics.py): per collapsed
    # per-learner family, the round-close quantiles / top offenders /
    # distinct-series count. Empty below budget (and with the budget
    # off) — pre-budget payloads lack the key and render unchanged.
    metrics_digest: Dict[str, Any] = field(default_factory=dict)
    # non-fatal round errors (e.g. partial-cohort secure aggregation after a
    # deadline) — surfaced in lineage instead of vanishing into a log line
    errors: List[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class Controller:
    """See module docstring. Lifecycle: ``start()`` → learners ``join()`` →
    rounds run event-driven off ``task_completed()`` → ``shutdown()``."""

    def __init__(self, config: FederationConfig,
                 proxy_factory: Callable[[LearnerRecord], LearnerProxy],
                 secure_backend=None):
        self.config = config
        self._proxy_factory = proxy_factory
        # the registry lock — every uplink, join/leave, and round close
        # serializes here, which makes it THE contention site to watch:
        # instrumented by the continuous profiler (telemetry/prof.py;
        # with telemetry.prof.enabled=false this is a raw RLock)
        self._lock = _tprof.rlock("controller.registry")
        self._learners: Dict[str, LearnerRecord] = {}
        self._tokens: Dict[str, str] = {}
        # Controller incarnation id, minted fresh per process (never
        # restored from a checkpoint — the whole point is that a restart
        # CHANGES it). Rides in JoinReply and every task envelope so
        # learners detect a controller crash+restart and re-attach.
        self.controller_epoch = uuid.uuid4().hex

        agg = config.aggregation
        if config.secure.enabled:
            if secure_backend is None:
                raise ValueError("secure aggregation enabled but no backend given")
            self._aggregator = SecureAgg(secure_backend)
        elif agg.rule.lower() in ("fedavgm", "fedadam", "fedyogi"):
            # normalized like make_aggregation_rule, so a mixed-case rule
            # string cannot silently drop the server hyperparameters
            self._aggregator = make_aggregation_rule(
                agg.rule, learning_rate=agg.server_learning_rate,
                beta1=agg.server_beta1, beta2=agg.server_beta2,
                tau=agg.server_tau)
        elif agg.rule.lower() == "trimmed_mean":
            self._aggregator = make_aggregation_rule(
                agg.rule, trim_ratio=agg.trim_ratio)
        elif agg.rule.lower() in ("krum", "multikrum"):
            self._aggregator = make_aggregation_rule(
                agg.rule, byzantine_f=agg.byzantine_f)
        else:
            self._aggregator = make_aggregation_rule(agg.rule)
        self._scaler = make_scaler(agg.scaler)
        # SCAFFOLD server control variate c (name -> f32 array) and the
        # cohort's latest unconsumed control deltas (learner_id -> blob)
        self._scaffold_c: Optional[Dict[str, np.ndarray]] = None
        self._scaffold_c_blob: Optional[bytes] = None   # pack cache
        self._scaffold_deltas: Dict[str, bytes] = {}
        self._selector = make_selector("scheduled_cardinality")
        sched_cfg = config.scheduling
        if config.protocol == "semi_synchronous":
            self._scheduler = make_scheduler(
                "semi_synchronous", lambda_=config.semi_sync_lambda,
                recompute_every_round=config.semi_sync_recompute_every_round,
                quorum=sched_cfg.quorum)
        elif config.protocol == "asynchronous_buffered":
            self._scheduler = make_scheduler(
                "asynchronous_buffered", buffer_size=sched_cfg.buffer_size)
        elif config.protocol == "synchronous":
            self._scheduler = make_scheduler("synchronous",
                                             quorum=sched_cfg.quorum)
        else:
            self._scheduler = make_scheduler(config.protocol)
        # quorum barrier (scheduling.quorum): 0 = full-cohort barrier —
        # every quorum hot path below is then one attribute check, and
        # round behavior is bit-identical to the plain synchronous path
        self._quorum = (sched_cfg.quorum
                        if config.protocol in ("synchronous",
                                               "semi_synchronous") else 0)
        # churn-aware admission (selection.py ChurnTracker): per-learner
        # churn/flap scores + optional quarantine. None when opted out —
        # every membership path then costs one attribute check.
        self._churn: Optional[ChurnTracker] = None
        if sched_cfg.churn_tracking:
            self._churn = ChurnTracker(
                alpha=sched_cfg.churn_alpha,
                quarantine_score=sched_cfg.quarantine_score,
                quarantine_s=sched_cfg.quarantine_s)

        store_cfg = config.model_store
        lineage = store_cfg.lineage_length or self._aggregator.required_lineage
        lineage = max(lineage, self._aggregator.required_lineage)
        store_kwargs = {"lineage_length": lineage}
        if store_cfg.store in ("disk", "cached_disk"):
            store_kwargs["root"] = store_cfg.root or "/tmp/metisfl_tpu_store"
        if store_cfg.store == "cached_disk":
            store_kwargs["cache_bytes"] = store_cfg.cache_mb << 20
        if store_cfg.store == "remote":
            store_kwargs["host"] = store_cfg.host
            store_kwargs["port"] = store_cfg.port
        self._store = make_store(store_cfg.store, **store_kwargs)

        # Cohort-scale ingest plane (docs/SCALE.md). All three are None
        # when opted out — every hot path then costs one attribute check.
        # (a) parallel store ingest: completions enqueue, a bounded
        # writer pool persists, aggregation fences on drain
        self._ingest = None
        ingest_workers = int(getattr(store_cfg, "ingest_workers", 0) or 0)
        if ingest_workers > 0:
            from metisfl_tpu.store.ingest import IngestPipeline
            # accept: the worker re-checks membership right before the
            # write, so a queued write racing leave() cannot land after
            # the erase and resurrect the pruned lineage
            self._ingest = IngestPipeline(
                self._store, ingest_workers,
                on_insert=self._note_ingest_insert,
                accept=self.is_member)
        # (b) streaming aggregation: fold accepted uplinks on arrival —
        # no store round-trip — for the weighted-sum rules; unsupported
        # rule/protocol/lineage combinations fall back to the store path
        self._streaming = None
        if getattr(agg, "streaming", False):
            from metisfl_tpu.aggregation.streaming import (
                StreamingAggregator,
                streaming_supported,
            )
            if streaming_supported(self._aggregator.name, config.protocol,
                                   config.secure.enabled, lineage,
                                   self._aggregator.required_lineage,
                                   checkpointed=bool(config.checkpoint.dir),
                                   buffer_size=sched_cfg.buffer_size):
                self._streaming = StreamingAggregator(
                    self._aggregator, stride=agg.stride_length)
            else:
                logger.info(
                    "aggregation.streaming requested but rule=%s/"
                    "protocol=%s/lineage=%d/checkpointed=%s does not "
                    "support it; using the store path",
                    self._aggregator.name, config.protocol, lineage,
                    bool(config.checkpoint.dir))
        # (c) tree-aggregation tier: O(branch) fan-in for the store path
        self._tree = None
        tree_cfg = getattr(agg, "tree", None)
        if tree_cfg is not None and getattr(tree_cfg, "enabled", False):
            from metisfl_tpu.aggregation.tree import TreeReducer
            self._tree = TreeReducer(branch=tree_cfg.branch,
                                     workers=tree_cfg.workers)
        # (d) distributed slice-aggregation tier (aggregation/
        # distributed.py): the tree's branches as driver-booted slice
        # aggregator PROCESSES — uplinks forward to their slice over
        # gRPC, the root fans in O(branch) partials, and a dead
        # aggregator's slice re-homes mid-round. None when opted out (or
        # when the rule cannot slice-fold) — every hot path is then one
        # attribute check; with it armed the in-process tree above stays
        # constructed as the fully-degraded fallback.
        self._slices = None
        masked_tier = (config.secure.enabled
                       and config.secure.scheme == "masking")
        if (tree_cfg is not None and getattr(tree_cfg, "distributed", False)
                and getattr(tree_cfg, "slices", None)):
            if (self._aggregator.name in ("fedavg", "scaffold", "fedstride")
                    and not config.secure.enabled) or masked_tier:
                from metisfl_tpu.aggregation.distributed import (
                    DistributedSliceReducer,
                )
                # masked mode (secure/distributed.py): slices fold raw
                # masked blobs as modular uint64 sums — key-free, masks
                # cancel at the root settlement; with streaming they
                # additionally fold on arrival
                self._slices = DistributedSliceReducer(
                    tree_cfg, ssl=config.ssl, comm=config.comm,
                    masked=masked_tier,
                    stream=masked_tier and bool(getattr(agg, "streaming",
                                                        False)))
            else:
                logger.info(
                    "aggregation.tree.distributed requested but rule=%s "
                    "cannot slice-fold; using the in-process path",
                    self._aggregator.name)
        # (e) masked streaming (secure/distributed.py): under scheme:
        # masking with aggregation.streaming and NO slice tier, the
        # controller folds masked uplinks on arrival itself — modular
        # sums are exact and order-free, so the stream accumulates the
        # bits the store path's one-combine would. With slices armed the
        # fold-on-arrival happens slice-side instead (submit streams).
        self._masked_stream = None
        if (masked_tier and getattr(agg, "streaming", False)
                and self._slices is None):
            from metisfl_tpu.secure.distributed import (
                MaskedStreamingAggregator,
            )
            self._masked_stream = MaskedStreamingAggregator()

        # community model state
        self._community_flat: Optional[Dict[str, np.ndarray]] = None
        self._community_blob: Optional[bytes] = None
        self._community_opaque = None      # secure path
        # (full-width blob, narrowed bytes) — see _dispatch_blob
        self._downlink_cache: Optional[Tuple[bytes, bytes]] = None
        self.global_iteration = 0

        # lineage / statistics
        self.round_metadata: List[RoundMetadata] = []
        self.community_evaluations: List[Dict[str, Any]] = []
        self._current_meta = RoundMetadata(global_iteration=0)
        # telemetry: the open round span (root of the round's trace tree;
        # learner train spans parent under it via RPC metadata) and the
        # open dispatch→barrier-release wait span
        self._round_span = None
        self._wait_span = None

        # single-worker pool serializes all scheduling/aggregation work
        self._pool = ThreadPoolExecutor(max_workers=1,
                                        thread_name_prefix="ctrl-sched")
        self._shutdown = threading.Event()
        self._tasks_in_flight: Dict[str, str] = {}  # task_id -> learner_id
        # task_id -> dispatch wall-clock, maintained in lockstep with
        # _tasks_in_flight: DescribeFederation reports in-flight ages and
        # completions feed the per-learner EWMA train durations from it
        self._task_dispatched_at: Dict[str, float] = {}
        # coarse live phase for the status plane ("what is the controller
        # doing RIGHT NOW"): idle | dispatch | wait_uplinks | select |
        # aggregate | halted
        self._phase = "idle"
        # straggler-deadline state: each dispatch bumps the serial so a
        # deadline timer from a completed round never fires on the next one
        self._round_serial = 0
        self._deadline_timer: Optional[threading.Timer] = None
        self._expired_tasks: Dict[str, None] = {}  # ordered set of task_ids
        # consecutive aggregation failures (reset on success): distinguishes
        # transient partial-cohort failures from a deterministically broken
        # federation, which must halt instead of retraining forever
        self._agg_failures = 0
        # consecutive zero-reporter round deadlines (reset whenever a round
        # completes): scheduling.max_empty_redispatch bounds the re-dispatch
        # loop the deadline path would otherwise spin forever. The halt it
        # triggers is recoverable: _halted_no_reporters marks it so a later
        # delivered uplink resumes dispatch (scheduling-executor-only state)
        self._empty_deadlines = 0
        self._halted_no_reporters = False
        # dispatch-retry budget used this round (scheduling.dispatch_retries)
        # and the live backoff timers shutdown() must cancel
        self._dispatch_retries_used = 0
        self._retry_timers: Dict[object, None] = {}
        # round-scoped cache of the fleet's median observed train EWMA
        # (collapsed-straggler-gauge fast path: the median only moves
        # meaningfully at round granularity, so per-uplink O(fleet)
        # median recomputation is wasted work under the controller lock)
        self._straggler_median_cache: Optional[float] = None
        # guards against recursive checkpointing while restore itself
        # replays the community model through set_community_model
        self._in_restore = False
        # coalesces queued async checkpoint saves: N learners joining in
        # a burst (or re-attaching after a failover) must cost one
        # community-blob write on the scheduling executor, not N
        self._ckpt_queued = False

        # Hot-standby round-state WAL (controller/wal.py): registry
        # deltas land synchronously on the join/leave RPC path (before
        # the ack), full snapshots ride the same coalesced executor hook
        # as the checkpoint. None when no standby is configured — every
        # membership path then costs one attribute check.
        self._wal = None
        standby = config.controller.standby
        if standby.enabled and standby.wal_dir:
            from metisfl_tpu.controller.wal import RoundStateLog
            self._wal = RoundStateLog(standby.wal_dir)

        # Learning-health plane (telemetry/health.py): per-uplink update
        # statistics + per-learner divergence scores. None when opted
        # out or under secure aggregation (opaque payloads) — the uplink
        # hot path then costs exactly one attribute check.
        hc = getattr(config.telemetry, "health", None)
        self._health: Optional[HealthMonitor] = None
        if (config.telemetry.enabled and hc is not None
                and getattr(hc, "enabled", False)
                and not config.secure.enabled):
            self._health = HealthMonitor(
                alpha=hc.alpha, anomaly_threshold=hc.anomaly_threshold)
        self._health_advisory = bool(
            self._health is not None and getattr(hc, "advisory", False))

        # Performance observatory (telemetry/profile.py): per-round cost
        # profiles — phase waterfall, per-learner wire bytes + codec
        # attribution, store timings, device stats. None when opted out —
        # every hot-path hook is then one attribute check.
        pc = getattr(config.telemetry, "profile", None)
        self._profile: Optional[_tprofile.ProfileCollector] = None
        if (config.telemetry.enabled and pc is not None
                and getattr(pc, "enabled", False)):
            self._profile = _tprofile.ProfileCollector(
                pc, telemetry_dir=config.telemetry.dir,
                service="controller")
            # the flight recorder snapshots the active collector's tail
            # into crash bundles
            _tprofile.set_collector(self._profile)

        # Telemetry-at-scale plane (docs/OBSERVABILITY.md "Telemetry at
        # scale"): (a) cardinality budget — past it the per-learner
        # metric families serve sketches, DescribeFederation serves
        # digest columns, and the checkpoint persists digests instead of
        # per-learner series. 0 (default) keeps everything exact.
        self._cardinality_budget = 0
        if config.telemetry.enabled:
            self._cardinality_budget = int(
                getattr(config.telemetry, "cardinality_budget", 0) or 0)
            if self._cardinality_budget > 0:
                _REG.set_cardinality_budget(self._cardinality_budget)
        # (b) SLO alert engine (telemetry/alerts.py): None when no rules
        # are configured — the round-close hook is one attribute check.
        self._alerts = None
        alert_specs = getattr(config.telemetry, "alerts", None) or []
        if config.telemetry.enabled and alert_specs:
            from metisfl_tpu.telemetry import alerts as _talerts
            self._alerts = _talerts.AlertEngine(
                _talerts.validate_rules(alert_specs),
                registry=_REG,
                interval_s=getattr(config.telemetry, "alerts_interval_s",
                                   1.0))
            # the flight recorder snapshots the live engine's active
            # alerts into crash bundles ("alerts at death")
            _talerts.set_engine(self._alerts)
            self._alerts.start()

        # Model lifecycle plane (registry/registry.py): versioned
        # community-model lineage with eval-gated promotion. None when
        # opted out — the post-aggregation path then costs exactly one
        # attribute check (same posture as the health monitor above).
        self._registry = None
        rc = getattr(config, "registry", None)
        if rc is not None and getattr(rc, "enabled", False):
            import hashlib

            from metisfl_tpu.registry import ModelRegistry
            self._registry = ModelRegistry(
                rc, config_hash=hashlib.sha256(
                    config.to_wire()).hexdigest()[:16])

    # ------------------------------------------------------------------ #
    # lifecycle
    # ------------------------------------------------------------------ #

    def start(self) -> None:
        pass  # transport servers are owned by the service layer

    def shutdown(self) -> None:
        self._shutdown.set()
        with self._lock:
            if self._deadline_timer is not None:
                self._deadline_timer.cancel()
            # dispatch-retry backoff timers must not fire into the
            # torn-down pool either (their submit is guarded anyway,
            # but cancel keeps shutdown deterministic)
            for timer in list(self._retry_timers):
                timer.cancel()
            self._retry_timers.clear()
        self._pool.shutdown(wait=True)
        # A task that was already draining on the pool when the first
        # cancel ran may have re-armed the timer (complete-round →
        # dispatch → arm); _arm_round_deadline now refuses post-shutdown
        # arming, but cancel again for the window between the first
        # cancel and the shutdown flag propagating — no timer may outlive
        # shutdown() (it would fire into the torn-down pool).
        with self._lock:
            if self._deadline_timer is not None:
                self._deadline_timer.cancel()
        # the alert engine's evaluation daemon must not outlive the
        # controller (and its active-alert gauge series must prune so a
        # later in-process controller starts clean)
        if self._alerts is not None:
            from metisfl_tpu.telemetry import alerts as _talerts
            if _talerts.engine() is self._alerts:
                _talerts.set_engine(None)
            self._alerts.shutdown()
        # ingest workers write INTO the store: stop them (bounded drain)
        # before the store's own shutdown
        if self._ingest is not None:
            self._ingest.shutdown()
        self._store.shutdown()
        if self._tree is not None:
            self._tree.shutdown()
        if self._slices is not None:
            # clients close; the processes themselves are driver-owned
            # (the driver ShutDowns + reaps them like learners)
            self._slices.shutdown()
        if self._registry is not None:
            self._registry.shutdown()
        # Deregister the process-global collector handle if it is still
        # ours: a later controller in the same process (the in-process
        # test/driver pattern) with the profile plane off must see None —
        # otherwise its RPC layer would keep minting per-learner
        # attribution series into this dead collector.
        if self._profile is not None:
            if _tprofile.collector() is self._profile:
                _tprofile.set_collector(None)
            self._profile.close()

    # ------------------------------------------------------------------ #
    # membership (RPC thread)
    # ------------------------------------------------------------------ #

    def join(self, request: JoinRequest) -> JoinReply:
        """Register (or re-register) a learner; schedules its initial task.

        Mirrors AddLearner (controller.cc:98-168) + the rejoin path the
        reference drives through ALREADY_EXISTS (grpc_controller_client.py:96-107).
        """
        with self._lock:
            if (request.previous_id
                    and request.previous_id in self._learners
                    and self._tokens.get(request.previous_id) == request.auth_token):
                record = self._learners[request.previous_id]
                record.hostname, record.port = request.hostname, request.port
                record.proxy = self._proxy_factory(record)
                record.dispatch_failures = 0  # fresh endpoint, assume live
                logger.info("learner %s rejoined", record.learner_id)
                _tevents.emit(_tevents.LearnerJoined,
                              learner_id=record.learner_id,
                              hostname=record.hostname, port=record.port,
                              rejoined=True)
                self._note_churn(record.learner_id, "flap_rejoin")
                # Re-dispatch the current community model so a crash-restarted
                # learner rejoins the in-flight round instead of idling until
                # the next dispatch (the reference leaves the sync round
                # stalled after a crash — SURVEY.md §5.3).
                if not self._shutdown.is_set():
                    self._pool.submit(self._guard, self._schedule_initial,
                                      record.learner_id)
                self._wal_join(record)
                self._checkpoint_async()
                return JoinReply(learner_id=record.learner_id,
                                 auth_token=record.auth_token, rejoined=True,
                                 controller_epoch=self.controller_epoch)
            # Endpoint-keyed rejoin: a credential-less join from a
            # host:port already in the registry is the same learner
            # reincarnated without its token (crash that lost the creds
            # file, or a registry restored from a controller checkpoint
            # that the learner never knew about). One process owns one
            # endpoint, so registering a SECOND id for it would leave a
            # ghost in the barrier and double-dispatch the endpoint; the
            # reference's ALREADY_EXISTS rejoin is endpoint-keyed for the
            # same reason (grpc_controller_client.py:96-107). The token
            # rotates — the stale one stops validating. Trust model: join
            # is open, so endpoint reclamation grants nothing an attacker
            # could not get by registering fresh — admission control is
            # the transport's job (TLS + network ACLs, docs/RESILIENCE.md).
            if request.port:
                match = next(
                    (r for r in self._learners.values()
                     if r.hostname == request.hostname
                     and r.port == request.port), None)
                if match is not None:
                    token = uuid.uuid4().hex
                    match.auth_token = token
                    self._tokens[match.learner_id] = token
                    match.num_train_examples = request.num_train_examples
                    match.num_val_examples = request.num_val_examples
                    match.num_test_examples = request.num_test_examples
                    match.party_index = int(
                        request.capabilities.get("party_index",
                                                 match.party_index))
                    match.proxy = self._proxy_factory(match)
                    match.dispatch_failures = 0
                    logger.info("learner %s re-registered from its endpoint "
                                "%s:%d (token rotated)", match.learner_id,
                                request.hostname, request.port)
                    _tevents.emit(_tevents.LearnerJoined,
                                  learner_id=match.learner_id,
                                  hostname=match.hostname, port=match.port,
                                  rejoined=True)
                    self._note_churn(match.learner_id, "flap_rejoin")
                    if not self._shutdown.is_set():
                        self._pool.submit(self._guard, self._schedule_initial,
                                          match.learner_id)
                    self._wal_join(match)
                    self._checkpoint_async()
                    return JoinReply(learner_id=match.learner_id,
                                     auth_token=token, rejoined=True,
                                     controller_epoch=self.controller_epoch)
            learner_id = f"L{len(self._tokens)}_{request.hostname}_{request.port}"
            token = uuid.uuid4().hex
            record = LearnerRecord(
                learner_id=learner_id, auth_token=token,
                hostname=request.hostname, port=request.port,
                num_train_examples=request.num_train_examples,
                num_val_examples=request.num_val_examples,
                num_test_examples=request.num_test_examples,
                party_index=int(request.capabilities.get("party_index", -1)),
            )
            record.proxy = self._proxy_factory(record)
            self._learners[learner_id] = record
            self._tokens[learner_id] = token
            _M_ACTIVE_LEARNERS.set(len(self._learners))
        logger.info("learner %s joined (%d train examples)",
                    learner_id, request.num_train_examples)
        _tevents.emit(_tevents.LearnerJoined, learner_id=learner_id,
                      hostname=request.hostname, port=request.port)
        # Control handoff exactly like controller.cc:163-164: initial task is
        # scheduled off the join path.
        if not self._shutdown.is_set():
            self._pool.submit(self._guard, self._schedule_initial, learner_id)
        # registry durability: a controller crash between here and the next
        # round checkpoint must not forget this learner's identity/token
        self._wal_join(record)
        self._checkpoint_async()
        return JoinReply(learner_id=learner_id, auth_token=token,
                         controller_epoch=self.controller_epoch)

    def leave(self, learner_id: str, auth_token: str) -> bool:
        """RemoveLearner (controller.cc:170-199): drop registry + models."""
        with self._lock:
            record = self._learners.get(learner_id)
            if record is None or record.auth_token != auth_token:
                return False
            proxy = record.proxy
            del self._learners[learner_id]
            _M_ACTIVE_LEARNERS.set(len(self._learners))
            # a departed learner's tasks can never complete: without this
            # prune (and with no round deadline configured) they would sit
            # in the in-flight map forever, and DescribeFederation would
            # report ghost tasks with ever-growing ages
            for tid in [t for t, lid in self._tasks_in_flight.items()
                        if lid == learner_id]:
                self._tasks_in_flight.pop(tid, None)
                self._task_dispatched_at.pop(tid, None)
        # the standby must forget this learner too, before the ack — a
        # promoted registry resurrecting a departed learner would ghost
        # the barrier exactly like the duplicate-id case join() guards
        self._wal_leave(learner_id)
        # bounded metric cardinality under churn: a departed learner's
        # per-learner series (uplink bytes, straggler AND divergence
        # scores) must not accumulate for the process lifetime. Detach
        # the proxy's peer label FIRST: an in-flight RPC's completion
        # callback firing after the prune would otherwise re-mint the
        # peer wire-byte series for the process lifetime.
        if proxy is not None and hasattr(proxy, "detach_peer"):
            proxy.detach_peer()
        self._prune_learner_series(learner_id)
        # drain the departing learner's queued ingest writes BEFORE the
        # erase — a write landing after the prune would resurrect the
        # lineage (and its attribution series) for the process lifetime
        if self._ingest is not None:
            if not self._ingest.drain(learner_id, timeout=30.0):
                # a wedged writer: proceed with the erase — the queued
                # write cannot resurrect the lineage, the worker's
                # membership gate drops it (store/ingest.py accept)
                logger.error("ingest drain for departing %s timed out; "
                             "its queued writes will be gate-dropped",
                             learner_id)
        self._store.erase([learner_id])
        if self._slices is not None:
            # prune the departed learner's held model from its slice
            # owner + the root residual (best-effort: a dead owner's copy
            # dies with it, and the fold path skips departed ids anyway)
            self._slices.forget(learner_id)
        if self._streaming is not None and not self._shutdown.is_set():
            # subtract the departed learner's streamed contribution on
            # the scheduling executor (fold state is single-threaded)
            self._pool.submit(self._guard, self._streaming.forget,
                              learner_id)
        logger.info("learner %s left", learner_id)
        _tevents.emit(_tevents.LearnerLost, learner_id=learner_id)
        _M_DROPPED.inc(reason="leave")
        # churn memory deliberately SURVIVES the leave (a flapper's
        # history is the signal); only the gauge series is pruned above
        self._note_churn(learner_id, "leave")
        # Re-evaluate the round barrier: if the departed learner was the last
        # pending one, no completion event would ever release the round.
        if not self._shutdown.is_set():
            self._pool.submit(self._guard, self._handle_membership_change)
        return True

    def _prune_learner_series(self, learner_id: str) -> None:
        """Drop every per-learner gauge/counter series and plane state
        for a learner that left or was replaced — long-churn runs must
        not accumulate stale labels in the exposition. The series prune
        itself is ONE central call (telemetry.prune_learner covers every
        family registered with a learner/peer cardinality label, plus
        the codec/RPC attribution state behind them — the drift guard in
        tests/test_scaletel.py keeps future per-learner families from
        escaping it); the planes only drop their own non-series state."""
        _tel.prune_learner(learner_id)
        if self._health is not None:
            self._health.drop(learner_id)
        if self._profile is not None:
            # per-learner byte/insert/device attribution inside the
            # collector (its gauge series are already pruned above)
            self._profile.drop(learner_id)

    def _note_churn(self, learner_id: str, event: str) -> None:
        """Fold one membership event into the learner's churn/flap score
        (selection.py ChurnTracker) and surface it: gauge (membership-
        gated under the registry lock, same prune-race posture as the
        straggler gauge), quarantine event + drop counter when the score
        newly crosses the threshold. One attribute check when the churn
        plane is off."""
        if self._churn is None:
            return
        was_quarantined = self._churn.quarantined(learner_id)
        score = self._churn.note(learner_id, event)
        with self._lock:
            if learner_id in self._learners:
                _M_CHURN.set(round(score, 4), learner=learner_id)
        if not was_quarantined and self._churn.quarantined(learner_id):
            _M_DROPPED.inc(reason="quarantine")
            _tevents.emit(_tevents.LearnerQuarantined,
                          learner_id=learner_id, score=round(score, 4),
                          until_s=self._churn.quarantine_s)
            logger.warning(
                "learner %s quarantined for %.1fs (churn score %.2f >= "
                "%.2f after %s)", learner_id, self._churn.quarantine_s,
                score, self._churn.quarantine_score, event)

    def active_learners(self) -> List[str]:
        with self._lock:
            return list(self._learners.keys())

    def is_member(self, learner_id: str) -> bool:
        """Cheap membership probe (RPC threads gate per-learner metric
        attribution on it so departed learners' series stay pruned)."""
        with self._lock:
            return learner_id in self._learners

    def attribute_decode(self, learner_id: str, seconds: float) -> None:
        """Codec decode attribution under the registry lock: leave()
        deletes the record under this lock and prunes the series only
        afterwards, so an attribution recorded here either precedes the
        prune (erased with it) or sees the learner gone — it can never
        resurrect a pruned series."""
        from metisfl_tpu.comm import codec as _codec

        with self._lock:
            if learner_id in self._learners:
                _codec.attribute(learner_id, "decode", seconds)

    def learner_endpoints(self) -> List[Dict[str, Any]]:
        """Registered endpoints with the ports learners reported on join."""
        with self._lock:
            return [
                {"learner_id": r.learner_id, "hostname": r.hostname,
                 "port": r.port}
                for r in self._learners.values()
            ]

    # ------------------------------------------------------------------ #
    # community model management (RPC thread)
    # ------------------------------------------------------------------ #

    def set_community_model(self, blob_bytes: bytes) -> None:
        """ReplaceCommunityModel (controller.cc:85-96): seed or overwrite.

        Under ship_tensor_regex the controller is subset-resident from the
        seed on: the frozen base never occupies controller memory, store,
        checkpoints, or any wire hop — a full-model seed (the usual driver
        flow) is filtered down immediately and re-encoded, so round-1
        dispatch is already adapter-sized."""
        blob = ModelBlob.from_bytes(blob_bytes)
        ship_regex = self.config.train.ship_tensor_regex
        if ship_regex and blob.tensors:
            import re

            subset = [(n, a) for n, a in blob.tensors
                      if re.search(ship_regex, n)]
            if not subset:
                raise ValueError(
                    f"ship_tensor_regex {ship_regex!r} matches no tensor "
                    "in the seeded model — nothing would ever federate")
            if len(subset) != len(blob.tensors):
                blob = ModelBlob(tensors=subset)
                blob_bytes = blob.to_bytes()
        with self._lock:
            self._community_blob = bytes(blob_bytes)
            if blob.tensors:
                self._community_flat = dict(blob.tensors)
                if hasattr(self._aggregator, "seed_community"):
                    # server-opt rules step FROM the seeded model (a mid-run
                    # replacement intentionally re-anchors the optimizer)
                    self._aggregator.seed_community(self._community_flat)
            if blob.opaque:
                self._community_opaque = dict(blob.opaque)
        if self._health is not None and blob.tensors:
            # anchor the health plane's delta reference at the seeded
            # model (round/effective-step norms measure from here)
            self._health.note_community(dict(blob.tensors))
        # Checkpoint the freshly seeded/replaced model immediately: the
        # per-round auto-checkpoint only starts after round 1 completes,
        # so a controller crash during round 1 would otherwise restore to
        # a model-less state a failover restart cannot train from.
        self._checkpoint_async()

    def _wal_join(self, record: LearnerRecord) -> None:
        """Append the learner's full registry entry to the hot-standby
        WAL on the join path, BEFORE the JoinReply ack returns: a
        learner the primary acked must exist in a promoted standby's
        registry as ITSELF (same id, token, party index). Append
        failures are logged, not raised — a disk hiccup must not reject
        the join (the checkpoint save's best-effort posture)."""
        if self._wal is None:
            return
        from metisfl_tpu.controller import wal as _walmod
        try:
            self._wal.append(_walmod.JOIN, self._learner_entry(record))
            _M_WAL_RECORDS.inc(kind="join")
        except Exception:  # noqa: BLE001 - best-effort durability
            logger.exception("WAL join append for %s failed",
                             record.learner_id)

    def _wal_leave(self, learner_id: str) -> None:
        """Append a leave delta before the leave ack (see _wal_join)."""
        if self._wal is None:
            return
        from metisfl_tpu.controller import wal as _walmod
        try:
            self._wal.append(_walmod.LEAVE, {"learner_id": learner_id})
            _M_WAL_RECORDS.inc(kind="leave")
        except Exception:  # noqa: BLE001 - best-effort durability
            logger.exception("WAL leave append for %s failed", learner_id)

    def _checkpoint_async(self) -> None:
        """Queue a round-state save onto the scheduling executor (off
        the RPC path; serialized with round logic): the on-disk
        checkpoint when checkpoint.dir is set, a WAL snapshot when a
        standby is configured — both from ONE state capture. Coalescing:
        while a save is already queued, further requests are no-ops —
        the queued save snapshots state at RUN time, so it covers them.
        No-op when neither sink is armed, during restore, or at
        shutdown."""
        if ((not self.config.checkpoint.dir and self._wal is None)
                or self._in_restore or self._shutdown.is_set()):
            return
        with self._lock:
            if self._ckpt_queued:
                return
            self._ckpt_queued = True

        def _save():
            with self._lock:
                self._ckpt_queued = False
            try:
                state = self._checkpoint_state()
                if self.config.checkpoint.dir:
                    self.save_checkpoint(state=state)
                if self._wal is not None:
                    self._wal.snapshot(state)
                    _M_WAL_RECORDS.inc(kind="snapshot")
            except Exception:  # noqa: BLE001 - best-effort durability
                logger.exception("round-state save failed")

        try:
            self._pool.submit(self._guard, _save)
        except RuntimeError:  # pool already shut down
            with self._lock:
                self._ckpt_queued = False

    def community_model_bytes(self) -> Optional[bytes]:
        with self._lock:
            return self._community_blob

    # ------------------------------------------------------------------ #
    # task completion (RPC thread → scheduling executor)
    # ------------------------------------------------------------------ #

    def task_completed(self, result: TaskResult) -> bool:
        """MarkTaskCompleted (controller.cc:201-259). Returns ack; all heavy
        work happens on the scheduling executor."""
        if self._shutdown.is_set():
            return False
        with self._lock:
            record = self._learners.get(result.learner_id)
            if record is None:
                logger.warning("completion from unknown learner %s",
                               result.learner_id)
                return False
            # Validate the (learner_id, auth_token) composite key before
            # accepting a model (the reference's ValidateLearner on
            # MarkTaskCompleted, controller.cc:205, controller.proto:146-148)
            # — without it any client could poison the community model.
            if record.auth_token != result.auth_token:
                logger.warning("completion from %s with bad auth token",
                               result.learner_id)
                return False
        self._pool.submit(self._guard, self._handle_completed, result)
        return True

    # ------------------------------------------------------------------ #
    # scheduling executor internals
    # ------------------------------------------------------------------ #

    # consecutive aggregation failures tolerated before halting re-dispatch
    _MAX_AGG_FAILURES = 10

    def _guard(self, fn, *args) -> None:
        try:
            fn(*args)
        except Exception:  # pragma: no cover - logged, never kills the pool
            logger.exception("controller executor task failed")

    def _schedule_initial(self, learner_id: str) -> None:
        if self._shutdown.is_set():
            return
        with self._lock:
            record = self._learners.get(learner_id)
        if record is None:
            return
        self._dispatch_train([learner_id], restart_deadline=False)

    def _handle_completed(self, result: TaskResult) -> None:
        start = time.time()
        with self._lock:
            record = self._learners.get(result.learner_id)
            if record is None:
                return
            record.dispatch_failures = 0  # provably reachable
            if result.control_delta:
                self._scaffold_deltas[result.learner_id] = result.control_delta
            if result.processing_ms_per_step > 0:
                record.ms_per_step = result.processing_ms_per_step
            self._tasks_in_flight.pop(result.task_id, None)
            dispatched_at = self._task_dispatched_at.pop(result.task_id, 0.0)
            if dispatched_at:
                # EWMA dispatch→completion duration (straggler analytics).
                # Expired-task completions count too — a straggler's late
                # arrival is exactly the observation the score needs.
                record.ewma_train_s = _ewma(record.ewma_train_s,
                                            max(0.0, start - dispatched_at))
            # A completion for a task the deadline already expired: keep the
            # model (fresh data for later rounds) but do not advance the
            # current round's barrier — and keep its timings out of the
            # current round's metadata (it belongs to an abandoned round).
            # Same verdict for an uplink dispatched by ANOTHER controller
            # incarnation (hot-standby promotion / --resume relaunch):
            # the restored controller re-dispatched that round itself, so
            # folding the dead incarnation's copy too would double-count
            # it — and shift every later round's bits off the same-seed
            # undisturbed run (the chaos gate's bit-identity pin).
            stale = (result.task_id in self._expired_tasks
                     or bool(result.controller_epoch
                             and result.controller_epoch
                             != self.controller_epoch))
            self._expired_tasks.pop(result.task_id, None)
            if not stale:
                self._current_meta.train_received_at[result.learner_id] = start
                self._current_meta.uplink_bytes[result.learner_id] = \
                    len(result.model)
            # under the lock: leave() deletes the record under this lock
            # and prunes the series after — an unlocked inc here could
            # interleave and resurrect a departed learner's series
            _M_UPLINK.inc(len(result.model), learner=result.learner_id)
            if self._profile is not None and result.device_stats:
                # learner-shipped device utilization (step EWMA, MFU,
                # HBM watermark) → per-learner gauges + the round profile
                self._profile.note_device(result.learner_id,
                                          result.device_stats)
        _tevents.emit(_tevents.TaskCompleted, task_id=result.task_id,
                      learner_id=result.learner_id, round=result.round_id,
                      stale=stale, uplink_bytes=len(result.model))
        self._update_straggler_gauge(completed=result.learner_id)
        # a delivered uplink is the churn score's decay tick: a learner
        # that reports steadily recovers from past flaps within a few
        # rounds (same recovery posture as the straggler EWMA)
        self._note_churn(result.learner_id, "completion")

        if stale and self._topk_uplink():
            # a topk payload is a delta against the community model AT
            # DISPATCH; the deadline path has since advanced it, so the
            # reconstruction reference is gone — storing the densification
            # would poison any later aggregation that selects it
            logger.info("late topk completion from %s for expired task %s "
                        "dropped (reconstruction reference advanced)",
                        result.learner_id, result.task_id)
            return
        try:
            model = self._parse_result_model(result)
        except ValueError as exc:
            # A malformed payload (bad sparse indices, missing companions,
            # codec garbage) must cost its OWN contribution, not the round:
            # the learner already got its ack and the task left
            # _tasks_in_flight, so raising here would stall a sync barrier
            # forever (no deadline by default). Drop the model, keep the
            # barrier moving; aggregation proceeds with whatever lineage
            # exists for this learner.
            logger.warning("dropping malformed result from %s for task %s: "
                           "%s", result.learner_id, result.task_id, exc)
            with self._lock:
                self._current_meta.errors.append(
                    f"malformed result from {result.learner_id}: {exc}")
            model = None
        deferred_meta = False
        if model is not None and self._masked_stream is not None:
            # masked streaming (secure/distributed.py): the raw masked
            # blob folds on arrival as a modular uint64 sum. Stale
            # uplinks carry dead masks (streams are round-keyed) and
            # must NEVER enter a live sum — drop them like the plain
            # streaming path drops round-scoped stragglers.
            folded = False
            if not stale and isinstance(model, (bytes, bytearray)):
                try:
                    opaque = dict(ModelBlob.from_bytes(model).opaque)
                    folded = bool(opaque) and self._masked_stream.fold(
                        result.learner_id, opaque, result.round_id)
                except ValueError as exc:
                    logger.warning("undecodable masked uplink from %s: %s",
                                   result.learner_id, exc)
            if folded:
                _M_SECURE_FOLDS.inc(tier="stream")
            else:
                logger.info("masked uplink from %s dropped (stale or "
                            "malformed; masks are round-keyed)",
                            result.learner_id)
            model = None if not folded else model
        elif model is not None and self._streaming is not None:
            # streaming aggregation (docs/SCALE.md): the accepted uplink
            # folds straight into the community accumulator — the store
            # round-trip is skipped entirely. A dropped fold (stale on a
            # round-scoped rule, opaque payload) contributes nothing,
            # exactly like a malformed payload on the store path.
            if not self._stream_fold(result, model, stale):
                model = None
        elif model is not None and self._slices is not None:
            # distributed slice tier (aggregation/distributed.py): the
            # accepted uplink forwards to its slice aggregator over gRPC
            # — the root never stores it, so controller memory and store
            # traffic stay O(branch). submit() never raises and never
            # drops an accepted uplink: an unreachable owner re-homes
            # (bounded retry/backoff) and the fold-of-last-resort is the
            # root's residual buffer.
            # parent on the uplink's server span when one is active (the
            # causal chain: learner train → uplink RPC → slice submit),
            # falling back to the round root for in-process deliveries
            fwd_sp = _ttrace.span(
                "round.slice_submit",
                parent=_ttrace.current_context() or self._round_span,
                attrs={"learner": result.learner_id})
            with fwd_sp, fwd_sp.activate():
                self._slices.submit(result.learner_id, model,
                                    result.round_id)
            _M_PHASE.observe(fwd_sp.duration_ms / 1e3, phase="slice_submit")
        elif model is not None:
            if self._ingest is not None:
                # parallel ingest: enqueue and return — the writer pool
                # records the ACTUAL write time via _note_ingest_insert
                # (no store_insert sample from this thread: no double
                # count), and aggregation fences on drain before select.
                # The result metadata is applied by on_success ONLY when
                # the write lands: a fail-soft write failure must not
                # pair fresh step counts with the older stored model.
                self._ingest.submit(
                    result.learner_id, model,
                    on_success=partial(self._ingest_landed, result))
                deferred_meta = True
            else:
                insert_sp = _ttrace.span(
                    "round.store_insert",
                    parent=_ttrace.current_context() or self._round_span,
                    attrs={"learner": result.learner_id})
                with insert_sp:
                    self._store.insert(result.learner_id, model)
                _M_PHASE.observe(insert_sp.duration_ms / 1e3,
                                 phase="store_insert")
                if self._profile is not None:
                    self._profile.note_store_insert(result.learner_id,
                                                    insert_sp.duration_ms)
        if model is not None:
            if not deferred_meta:
                with self._lock:
                    # step count and result round pair with the STORED
                    # (or streamed) model: dropped payloads (late topk,
                    # malformed, stale-on-streaming) must not refresh
                    # them, or FedNova's τ / the batches scaler /
                    # staleness decay would weight the older stored model
                    # with metadata from a different task (the ingest
                    # path applies them in _ingest_landed, write-fenced)
                    record.completed_batches = result.completed_batches
                    record.last_result_round = result.round_id
            if self._health is not None and isinstance(model, dict) and model:
                # learning-health statistics for this uplink (host numpy,
                # read-only — the stored model is untouched). Reference is
                # the live community model: under sync/semi-sync exactly
                # what the task trained from; a late/async uplink measures
                # against wherever the federation has moved since, which
                # is the divergence that matters for the NEXT aggregation.
                # The dict is safe to read un-copied: community updates
                # REPLACE _community_flat, they never mutate it in place.
                with self._lock:
                    reference = self._community_flat or {}
                try:
                    self._health.observe_update(
                        result.learner_id, model, reference,
                        train_metrics=result.train_metrics)
                except Exception:  # noqa: BLE001 - telemetry never fatal
                    logger.exception("health statistics failed for %s",
                                     result.learner_id)
        if not stale:
            with self._lock:
                self._current_meta.model_insertion_duration_ms[result.learner_id] = (
                    (time.time() - start) * 1e3)
                # surface the shipped training metrics into the round's
                # lineage (experiment.json) — previously dropped on the
                # controller floor (ISSUE 4 satellite). Values that are
                # not finite floats are skipped: a zero-step task ships
                # loss=NaN (strict JSON parsers reject NaN tokens), and
                # a raising conversion here would swallow schedule_next
                # via _guard and stall the sync barrier forever — the
                # wire never validates these learner-shipped dicts
                if result.train_metrics:
                    finite = finite_metrics(result.train_metrics)
                    if finite:
                        self._current_meta.train_metrics[
                            result.learner_id] = finite
                if result.epoch_metrics and isinstance(
                        result.epoch_metrics, (list, tuple)):
                    self._current_meta.epoch_metrics[result.learner_id] = [
                        finite_metrics(epoch)
                        for epoch in result.epoch_metrics]
        if self._halted_no_reporters:
            # the no-reporter halt is recoverable by evidence of life: a
            # delivered uplink (stale or not — every in-flight task was
            # expired at the halt) proves the federation is reachable
            # again, so resume dispatch with a fresh sample. The model
            # above was already stored/streamed like any other.
            self._halted_no_reporters = False
            self._empty_deadlines = 0
            logger.warning("completion from %s after no-reporter halt; "
                           "resuming dispatch", result.learner_id)
            self._scheduler.reset()
            if self._streaming is not None:
                self._streaming.abandon()
            if self._masked_stream is not None:
                self._masked_stream.abandon()
            self._dispatch_train(self._sample_cohort())
            return
        if stale:
            logger.info("late completion from %s for expired task %s stored "
                        "but not scheduled", result.learner_id, result.task_id)
            return

        to_schedule = self._scheduler.schedule_next(
            result.learner_id, self.active_learners())
        if not to_schedule:
            if getattr(self._scheduler, "redispatch_on_completion", False):
                # buffered async (FedBuff): the reporter never idles on
                # the buffer barrier — it trains against the current
                # community model while the buffer keeps filling
                self._dispatch_train([result.learner_id],
                                     restart_deadline=False)
            return
        if self._quorum > 0:
            # quorum release: tasks still in flight belong to the round
            # that just closed — expire them so their late completions
            # are stored (fresh lineage) but never advance the NEXT
            # round's barrier (exactly the deadline path's semantics)
            self._expire_unreported(to_schedule)
        self._complete_round(to_schedule)

    def _handle_membership_change(self) -> None:
        active = self.active_learners()
        if not active or self._shutdown.is_set():
            return
        cohort = self._scheduler.handle_leave(active)
        if cohort:
            if self._quorum > 0:
                self._expire_unreported(cohort)
            self._complete_round(cohort)
            return
        if self._scheduler.round_stalled(active):
            # every dispatched learner departed before the round could
            # complete: abandon it and dispatch a fresh sample so the
            # surviving learners keep making progress
            logger.info("round abandoned (dispatched cohort left); re-dispatching")
            _M_REDISPATCH.inc()
            self._scheduler.reset()
            if self._streaming is not None:
                self._streaming.abandon()
            if self._masked_stream is not None:
                self._masked_stream.abandon()
            self._dispatch_train(self._sample_cohort())

    def _expire_tasks_locked(self, pending: Dict[str, str]) -> None:
        """Move ``pending`` (task_id -> learner_id) to the bounded expired
        set and prune dispatch stamps down to tasks a completion can
        still reference (in-flight or expired — the EWMA pop needs
        them). ONE definition for the quorum and deadline triggers, so
        their bookkeeping can never diverge. Call with ``self._lock``
        held."""
        for tid in pending:
            self._tasks_in_flight.pop(tid, None)
        self._expired_tasks.update(dict.fromkeys(pending))
        while len(self._expired_tasks) > 512:
            self._expired_tasks.pop(next(iter(self._expired_tasks)))
        keep = set(self._tasks_in_flight) | set(self._expired_tasks)
        self._task_dispatched_at = {
            tid: t for tid, t in self._task_dispatched_at.items()
            if tid in keep}

    def _expire_unreported(self, cohort: Sequence[str]) -> None:
        """Quorum release (scheduling.quorum): the releasing cohort is the
        first K reporters — every task still in flight to a learner
        outside it belongs to the round that just closed. Move those to
        the expired set so a straggler's late completion is stored (fresh
        lineage for later rounds) but never advances the next round's
        barrier — the same bookkeeping `_handle_deadline` does, with the
        quorum instead of the clock as the trigger."""
        cohort_set = set(cohort)
        with self._lock:
            pending = {tid: lid for tid, lid in self._tasks_in_flight.items()
                       if lid not in cohort_set}
            if not pending:
                return
            self._expire_tasks_locked(pending)
        dropped = sorted(set(pending.values()))
        _M_DROPPED.inc(len(dropped), reason="quorum")
        logger.info("quorum reached: expiring %d straggler task(s) from %s",
                    len(pending), dropped)

    # -- straggler deadline ----------------------------------------------

    def _arm_round_deadline(self, restart: bool = True) -> None:
        """Start (or restart) the per-round straggler timer after a dispatch.
        Only sync/semi-sync rounds have a barrier a straggler can stall.

        ``restart=False`` (join/rejoin single-learner dispatches) only arms
        when no timer is live — otherwise a crash-looping learner rejoining
        inside the deadline window would keep postponing it forever, and a
        mid-round join would silently extend the in-flight round's deadline.
        """
        deadline = self.config.round_deadline_secs
        if deadline <= 0 or self._scheduler.name == "asynchronous":
            return
        with self._lock:
            # shutdown() cancels the live timer under this lock; a round
            # task draining on the pool concurrently with shutdown must
            # not arm a replacement after that cancel (the regression
            # tests/test_failover.py pins: no timer outlives shutdown)
            if self._shutdown.is_set():
                return
            if (not restart and self._deadline_timer is not None
                    and self._deadline_timer.is_alive()):
                return
            # the serial advanced in _dispatch_train (every fresh round
            # dispatch, deadline configured or not) — capture, don't bump
            serial = self._round_serial
            if self._deadline_timer is not None:
                self._deadline_timer.cancel()

            def _fire():
                if self._shutdown.is_set():
                    return
                try:
                    self._pool.submit(self._guard, self._handle_deadline, serial)
                except RuntimeError:  # pool already shut down
                    pass

            timer = threading.Timer(deadline, _fire)
            timer.daemon = True
            self._deadline_timer = timer
            timer.start()

    def _handle_deadline(self, serial: int) -> None:
        """Round deadline expired: drop unreported learners from the barrier
        and proceed with whoever reported (or re-dispatch if nobody did)."""
        if self._shutdown.is_set():
            return
        with self._lock:
            if serial != self._round_serial:
                return  # round already completed; stale timer
            pending = dict(self._tasks_in_flight)
            self._expire_tasks_locked(pending)
        cohort = self._scheduler.expire_pending(self.active_learners())
        dropped = sorted(set(pending.values()))
        if dropped:
            _M_DROPPED.inc(len(dropped), reason="deadline")
        if cohort:
            logger.warning(
                "round deadline (%.1fs) expired; aggregating %d reporter(s), "
                "dropping stragglers %s", self.config.round_deadline_secs,
                len(cohort), dropped)
            # masking secure-agg recovers partial cohorts via the dropout
            # correction (_masking_dropout_correction); when recovery is
            # impossible (< min_recovery_parties survivors) aggregation
            # fails and _complete_round re-dispatches a fresh full cohort
            self._complete_round(cohort)
            if (getattr(self._scheduler, "redispatch_on_completion", False)
                    and dropped and not self._shutdown.is_set()):
                # buffered async: the post-aggregation dispatch only
                # covers buffer reporters — the expired (dropped)
                # learners are lost training concurrency and must be
                # re-dispatched or they idle for the rest of the run
                revive = self._idle_reporters(dropped)
                if revive:
                    self._dispatch_train(revive, restart_deadline=False)
        else:
            self._empty_deadlines += 1
            limit = self.config.scheduling.max_empty_redispatch
            if limit > 0 and self._empty_deadlines >= limit:
                # nothing has reported for `limit` consecutive deadline
                # windows: the federation is not making progress and
                # re-dispatching forever would never terminate — halt
                # with a clear lineage error (the driver's wall-clock
                # cutoff or an operator takes it from here; a learner
                # DELIVERING an uplink later resumes dispatch via the
                # _halted_no_reporters check in _handle_completed — all
                # the halted round's tasks were just expired, so the
                # resume trigger must be explicit, not the barrier)
                reason = (f"{self._empty_deadlines} consecutive round "
                          f"deadlines expired with no reporters "
                          f"(last dropped: {dropped})")
                logger.error("halting re-dispatch: %s", reason)
                self._halted_no_reporters = True
                with self._lock:
                    self._current_meta.errors.append(
                        f"round halted: {reason}")
                    round_sp, self._round_span = self._round_span, None
                    # close the wait span WITH its round: left open it
                    # would outlive its ended parent, and the first
                    # post-resume round would inherit it and book the
                    # whole halted idle period as wait_uplinks time
                    wait_sp, self._wait_span = self._wait_span, None
                    self._phase = "halted"
                _tevents.emit(_tevents.RoundHalted,
                              round=self.global_iteration, reason=reason)
                if wait_sp is not None:
                    wait_sp.end()
                    with self._lock:
                        self._current_meta.wait_duration_ms += \
                            wait_sp.duration_ms
                if round_sp is not None:
                    round_sp.set_attr("error", f"halted: {reason}")
                    round_sp.end()
                return
            logger.warning(
                "round deadline (%.1fs) expired with no reporters (%s); "
                "re-dispatching (%d/%s)", self.config.round_deadline_secs,
                dropped, self._empty_deadlines, limit or "unbounded")
            _M_REDISPATCH.inc()
            if self._streaming is not None:
                self._streaming.abandon()
            if self._masked_stream is not None:
                self._masked_stream.abandon()
            self._dispatch_train(self._sample_cohort())

    def _ingest_landed(self, result: TaskResult, ms: float) -> None:
        """Ingest-write success hook (runs on the writer, strictly before
        the drain fence covering the write can return): pair the result's
        step count and round with the NOW-stored model. A fail-soft write
        failure never reaches here, so the older stored model keeps its
        older metadata."""
        with self._lock:
            record = self._learners.get(result.learner_id)
            if record is None:
                return
            record.completed_batches = result.completed_batches
            record.last_result_round = result.round_id

    def _note_ingest_insert(self, learner_id: str, ms: float) -> None:
        """Ingest-worker write attribution: the phase histogram and the
        round profile record the worker's ACTUAL write duration (the
        completion handler only enqueued — it records nothing)."""
        _M_PHASE.observe(ms / 1e3, phase="store_insert")
        if self._profile is not None:
            # membership gate under the registry lock (same posture as
            # _M_UPLINK): leave() prunes the profile series strictly
            # after deleting the record, so a late worker write cannot
            # re-mint a departed learner's series
            with self._lock:
                if learner_id in self._learners:
                    self._profile.note_store_insert(learner_id, ms)

    def _stream_fold(self, result: TaskResult, model, stale: bool) -> bool:
        """Fold one accepted uplink into the streaming accumulator.
        Returns False when the contribution was dropped (stale on a
        round-scoped rule — the streaming path has no store to park a
        late model in; or a non-tree payload)."""
        if stale and self._streaming.rule_name != "fedrec":
            # fedavg/fedstride sums are round-scoped: the expired round
            # this model belongs to was already abandoned. (fedrec's
            # recency semantics WANT the late model — newest wins.)
            logger.info("late completion from %s dropped (streaming "
                        "path keeps no store lineage)", result.learner_id)
            return False
        if not isinstance(model, dict) or not model:
            return False
        with self._lock:
            record = self._learners.get(result.learner_id)
            if record is None:
                return False
            entry = {"num_train_examples": record.num_train_examples,
                     "completed_batches": result.completed_batches}
        # raw (unnormalized) weight — the cohort normalizer is unknown
        # until barrier release; finish() divides by z = Σw (docs/SCALE.md)
        weight = raw_weight(self.config.aggregation.scaler, entry)
        if weight <= 0.0:
            # the batch scalers would give this learner scale 0 (e.g.
            # completed_batches=0): accept the completion — the record
            # update below still pairs metadata with it — but fold
            # nothing, matching a scale-0 contribution on the store path
            return True
        decay = self.config.aggregation.staleness_decay
        if decay > 0.0:
            # dispatch-version lag, damped by the same kernel the batch
            # path applies (scaling.staleness_factor — one definition)
            staleness = max(0, self.global_iteration - result.round_id)
            weight *= staleness_factor(staleness, decay)
        t0 = time.perf_counter()
        self._streaming.fold(result.learner_id, model, weight)
        fold_ms = (time.perf_counter() - t0) * 1e3
        _M_PHASE.observe(fold_ms / 1e3, phase="stream_fold")
        if self._profile is not None:
            self._profile.note_phase("stream_fold", fold_ms)
        return True

    def _topk_uplink(self) -> bool:
        from metisfl_tpu.tensor.sparse import parse_topk

        return (not self.config.secure.enabled
                and parse_topk(self.config.train.ship_dtype) is not None)

    def _parse_result_model(self, result: TaskResult):
        blob = ModelBlob.from_bytes(result.model)
        if self.config.secure.enabled:
            return result.model if blob.opaque else dict(blob.tensors)
        tensors = dict(blob.tensors)
        if self.config.train.ship_dtype.lower() == "int8q":
            # int8q uplink: restore exact f32 before storage/aggregation.
            # Gated on the CONFIG (not payload sniffing) so a model that
            # legitimately owns a '#qscale'-suffixed tensor cannot be
            # silently mangled when quantization is off.
            from metisfl_tpu.tensor.quantize import dequantize_named

            tensors = dequantize_named(tensors)
        else:
            from metisfl_tpu.tensor.sparse import densify_named, parse_topk

            if parse_topk(self.config.train.ship_dtype) is not None:
                # topk uplink: dense weights = dispatched community model
                # + scatter(sparse update). Valid because sync/semi-sync
                # (config-enforced) guarantees the community model has not
                # advanced since this task's dispatch. Same config gating
                # rationale as int8q above.
                with self._lock:
                    community = dict(self._community_flat or {})
                tensors = densify_named(tensors, community)
        return tensors

    def _complete_round(self, cohort: Sequence[str]) -> None:
        """One ScheduleTasks pass (controller.cc:428-518): select, aggregate,
        record metadata, evaluate, re-dispatch.

        Aggregation failure must never strand the federation: the error is
        recorded in round metadata and the round re-dispatches — async
        re-dispatches the reporters (so they are not left idle forever
        waiting for a completion ack that aborted), sync abandons the round
        and re-dispatches a fresh full cohort (mask streams are keyed on the
        round counter, which did not advance, so secure retries are clean).
        """
        # the round barrier just released: close the wait-for-uplinks span
        with self._lock:
            wait_sp, self._wait_span = self._wait_span, None
        if wait_sp is not None:
            wait_sp.end()
            _M_PHASE.observe(wait_sp.duration_ms / 1e3, phase="wait_uplinks")
            with self._lock:
                # accumulate like dispatch_duration_ms: an intra-round
                # aggregation-failure retry opens a second wait barrier
                # and both belong to this round's total
                self._current_meta.wait_duration_ms += wait_sp.duration_ms
        if self._profile is not None:
            self._profile.note_mark("wait_end")
        with self._lock:
            self._phase = "select"
        select_sp = _ttrace.span("round.select", parent=self._round_span,
                                 attrs={"cohort": len(cohort)})
        with select_sp:
            if self._health_advisory:
                # advisory only: the default selector records the scores
                # for operators/tests without changing its selection
                selected = self._selector.select(
                    cohort, self.active_learners(),
                    advisory_scores=self._health.scores())
            else:
                selected = self._selector.select(cohort,
                                                 self.active_learners())
        _M_PHASE.observe(select_sp.duration_ms / 1e3, phase="select")
        if self._profile is not None:
            self._profile.note_phase("select", select_sp.duration_ms)
            self._profile.note_mark("select_end")
        with self._lock:
            self._phase = "aggregate"
        try:
            self._compute_community_model(selected)
        except Exception as exc:
            _M_AGG_FAILURES.inc()
            self._agg_failures += 1
            if self._streaming is not None:
                # drop round-scoped fold state so the retry starts clean
                # (fedrec's cross-round rolling state survives)
                self._streaming.abandon()
            if self._masked_stream is not None:
                self._masked_stream.abandon()
            with self._lock:
                self._current_meta.errors.append(f"aggregation failed: {exc!r}")
            if self._agg_failures >= self._MAX_AGG_FAILURES:
                # deterministic breakage (version skew, corrupt payloads):
                # retraining forever would never terminate — halt dispatch
                # and leave the error trail; the driver's wall-clock cutoff
                # (or an operator) takes it from here
                logger.error(
                    "aggregation failed %d consecutive times (%r); halting "
                    "re-dispatch", self._agg_failures, exc)
                # flush the halted round's trace tree: the round span is
                # the root carrying the round attr, and the operator
                # debugging THIS round needs it in the sink
                with self._lock:
                    round_sp, self._round_span = self._round_span, None
                    self._phase = "halted"
                if round_sp is not None:
                    round_sp.set_attr("error", f"aggregation halted: {exc!r}")
                    round_sp.end()
                return
            logger.warning("aggregation failed (%r); re-dispatching", exc)
            if self._shutdown.is_set():
                return
            _M_REDISPATCH.inc()
            if self._scheduler.name.startswith("asynchronous"):
                self._dispatch_train(self._idle_reporters(cohort))
            else:
                self._scheduler.reset()
                self._dispatch_train(self._sample_cohort())
            return
        self._agg_failures = 0
        self._empty_deadlines = 0
        if self._slices is not None:
            # drop the root's residual fold buffer — its uplinks were
            # just folded (or superseded); the slice processes keep their
            # latest-per-learner models exactly like the store keeps
            # lineage across rounds
            self._slices.round_complete()
        if self._profile is not None:
            self._profile.note_mark("aggregate_end")
        with self._lock:
            agg_ms = self._current_meta.aggregation_duration_ms
        _tevents.emit(_tevents.AggregationDone,
                      round=self.global_iteration,
                      selected=len(selected), duration_ms=round(agg_ms, 3))
        # round close: everything between the aggregate landing and the
        # round counter advancing (health fold, version registration,
        # eval dispatch, lineage bookkeeping) — the last measured phase
        # of the cost-profile waterfall, and a real span in the trace
        close_sp = _ttrace.span("round.close", parent=self._round_span)
        self._fold_round_health()
        self._register_round_version()
        self._note_round_telemetry()
        self._send_eval_tasks()
        close_ms = close_sp.end()
        _M_PHASE.observe(close_ms / 1e3, phase="close")
        profile_record = None
        with self._lock:
            self.global_iteration += 1
            self._current_meta.completed_at = time.time()
            self._current_meta.peak_rss_kb = resource.getrusage(
                resource.RUSAGE_SELF).ru_maxrss
            round_wall_s = max(0.0, self._current_meta.completed_at
                               - self._current_meta.started_at)
            if self._profile is not None:
                # assemble under the lock (cheap dict building; the meta
                # object stays reachable through round_metadata, so a
                # concurrent to_dict must never race the write)
                profile_record = self._profile.assemble_round(
                    self._current_meta, close_ms=close_ms)
                self._current_meta.profile = profile_record
            self.round_metadata.append(self._current_meta)
            self._current_meta = RoundMetadata(
                global_iteration=self.global_iteration)
            # next round's uplinks re-derive the straggler median once
            self._straggler_median_cache = None
            round_sp, self._round_span = self._round_span, None
        if round_sp is not None:
            # end the round root BEFORE the critical-path walk: the walk
            # reads the finished-span ring, and the root must be in it
            round_sp.set_attr("learners", len(selected))
            round_sp.end()
        if profile_record is not None:
            # critical-path walk + JSONL sink write stay off the
            # controller lock
            self._profile.attach_critical_path(profile_record)
            self._profile.persist(profile_record)
        _M_ROUND_DURATION.observe(round_wall_s)
        _M_ROUNDS.inc()
        ckpt = self.config.checkpoint
        if ckpt.dir and self.global_iteration % max(1, ckpt.every_n_rounds) == 0:
            try:
                self.save_checkpoint()
            except Exception:
                logger.exception("checkpoint save failed")
        self._maybe_recompute_semisync()
        if self._shutdown.is_set():
            return
        if self._scheduler.name.startswith("asynchronous"):
            # async: re-dispatch only the reporting learner(s). Buffered
            # async re-dispatched most reporters the moment they uplinked
            # (redispatch_on_completion) — only the fill-triggering
            # reporter is still idle here, so filter out the busy ones
            # (plain async cohorts are never in flight at this point).
            next_ids = self._idle_reporters(cohort)
        else:
            next_ids = self._sample_cohort()
        self._dispatch_train(next_ids)

    def _note_round_telemetry(self) -> None:
        """Round-close hook for the telemetry-at-scale plane: one
        synchronous alert evaluation (round-paced even when the engine
        daemon lags behind a fast federation) and the collapsed metric
        families' digest snapshot into ``RoundMetadata.metrics_digest``.
        Two attribute checks when the plane is off; never raises
        (telemetry must not trip the aggregation-failure retry path)."""
        if self._alerts is not None:
            try:
                self._alerts.poll()
            except Exception:  # noqa: BLE001 - alerting never fails a round
                logger.exception("round-close alert poll failed")
        if self._cardinality_budget <= 0:
            return
        try:
            digest: Dict[str, Any] = {}
            for family in _REG.budget_families():
                summary = family.sketch_summary()
                if summary is not None:
                    digest[family.name] = summary
            if digest:
                with self._lock:
                    self._current_meta.metrics_digest = digest
        except Exception:  # noqa: BLE001 - telemetry never fails a round
            logger.exception("round-close metrics digest failed")

    def _idle_reporters(self, cohort: Sequence[str]) -> List[str]:
        """The cohort members that are active and NOT already carrying an
        in-flight task — the only ones an async-family re-dispatch may
        target (a double dispatch would cancel a training run mid-task)."""
        active = set(self.active_learners())
        with self._lock:
            busy = set(self._tasks_in_flight.values())
        return [lid for lid in cohort if lid in active and lid not in busy]

    def _admission_pool(self) -> List[str]:
        """Dispatchable learners: active, under the consecutive-dispatch-
        failure limit, and not churn-quarantined. Degrades instead of
        emptying — an all-dead / all-quarantined registry keeps trying
        rather than halting."""
        limit = self.config.max_dispatch_failures
        with self._lock:
            pool = [lid for lid, r in self._learners.items()
                    if limit <= 0 or r.dispatch_failures < limit]
            if not pool:
                # every learner looks dead: keep trying rather than halting
                pool = list(self._learners.keys())
        if self._churn is not None:
            quarantined = set(self._churn.quarantined_ids())
            if quarantined:
                healthy = [lid for lid in pool if lid not in quarantined]
                if healthy:  # never quarantine the whole federation
                    pool = healthy
        return pool

    def _sample_cohort(self) -> List[str]:
        """Sample next round's participants from reachable active learners
        (ControllerParams.participation_ratio). The scheduler barriers on the
        dispatched sample, so ratio < 1 cannot stall a synchronous round.

        Learners with ``max_dispatch_failures`` consecutive failed dispatches
        are skipped until they complete a task or rejoin — a dead endpoint
        must not keep re-entering sync barriers (SURVEY.md §5.3) — and
        churn-quarantined learners sit out until their window expires.

        With a quorum configured the dispatch is over-provisioned
        (Oort-style): ``ceil(quorum * (1 + overprovision))`` learners get
        tasks so the expected per-round dropout still leaves K reporters;
        ``participation_ratio`` is ignored in that mode (the quorum gives
        an absolute cohort size, the ratio a relative one)."""
        pool = self._admission_pool()
        if self._quorum > 0:
            k = math.ceil(self._quorum
                          * (1.0 + self.config.scheduling.overprovision))
            k = max(1, min(len(pool), k))
            if k >= len(pool):
                return pool
            return random.sample(pool, k)
        ratio = self.config.aggregation.participation_ratio
        if ratio >= 1.0 or not pool:
            return pool
        k = max(1, int(round(ratio * len(pool))))
        return random.sample(pool, k)

    def _maybe_recompute_semisync(self) -> None:
        if not isinstance(self._scheduler, SemiSynchronousScheduler):
            return
        batch = self.config.train.batch_size
        with self._lock:
            timings = {
                lid: {
                    "ms_per_step": r.ms_per_step,
                    "steps_per_epoch": max(1.0, r.num_train_examples / max(1, batch)),
                }
                for lid, r in self._learners.items()
            }
        overrides = self._scheduler.recompute_steps(timings)
        if not overrides:
            return
        with self._lock:
            for lid, steps in overrides.items():
                if lid in self._learners:
                    self._learners[lid].local_steps_override = steps
        logger.info("semi-sync step budgets: %s", overrides)

    # -- aggregation ------------------------------------------------------

    def _compute_community_model(self, selected: Sequence[str]) -> None:
        """ComputeCommunityModel (controller.cc:795-950), stride-blocked.

        Timing comes from telemetry spans (the aggregate span and one span
        per stride block) which ALSO populate the RoundMetadata fields the
        ad-hoc ``time.time()`` deltas used to fill — ``experiment.json``
        is unchanged."""
        agg_sp = _ttrace.span("round.aggregate", parent=self._round_span,
                              attrs={"rule": self._aggregator.name,
                                     "selected": len(selected)})
        try:
            self._compute_community_model_traced(selected, agg_sp)
        finally:
            agg_sp.end()
            _M_PHASE.observe(agg_sp.duration_ms / 1e3, phase="aggregate")

    def _timed_select(self, block, k):
        """Store lineage select with cost-profile attribution (the select
        share of aggregation time is the 100k-learner ingest wall's
        counterpart on the read side)."""
        if self._profile is None:
            return self._store.select(block, k=k)
        t0 = time.perf_counter()
        picked = self._store.select(block, k=k)
        self._profile.note_store_select((time.perf_counter() - t0) * 1e3)
        return picked

    def _compute_community_model_traced(self, selected: Sequence[str],
                                        agg_sp) -> None:
        if self._ingest is not None:
            # lineage visibility fence: every queued write must land (and
            # the store flush its batched fsyncs) before any select — a
            # torn lineage must never enter an aggregate. A timeout means
            # a wedged writer; raising routes into the aggregation-failure
            # retry instead of silently aggregating a partial cohort.
            t0 = time.perf_counter()
            if not self._ingest.drain(timeout=300.0):
                raise RuntimeError(
                    "ingest drain fence timed out; store lineage would be "
                    "torn")
            drain_ms = (time.perf_counter() - t0) * 1e3
            _M_PHASE.observe(drain_ms / 1e3, phase="ingest_drain")
            if self._profile is not None:
                self._profile.note_phase("ingest_drain", drain_ms)
        lineage_k = self._aggregator.required_lineage
        stride = self.config.aggregation.stride_length or len(selected) or 1
        metadata = self._scaling_metadata(selected)
        scales = self._scaler(metadata)
        decay = self.config.aggregation.staleness_decay
        if decay > 0.0:
            scales = apply_staleness_decay(scales, metadata, decay)
        # FedStride state resets between rounds (federated_stride.cc:52-68);
        # FedRec carries state across rounds; FedAvg resets in its own
        # branch. Under streaming the rolling state HOLDS this round's
        # folds — finish() owns the reset.
        if self._aggregator.name == "fedstride" and self._streaming is None:
            self._aggregator.reset()

        community = None
        meta_blocks: List[int] = []
        meta_durations: List[float] = []
        ids = [lid for lid in selected if lid in scales]

        def block_span(block):
            """One aggregation-block span; ``end()`` feeds both the phase
            metric and the lineage block-duration list."""
            sp = _ttrace.span("round.agg_block", parent=agg_sp,
                              attrs={"size": len(block)})
            return sp

        def end_block(sp, block):
            sp.end()
            _M_PHASE.observe(sp.duration_ms / 1e3, phase="aggregate_block")
            meta_blocks.append(len(block))
            meta_durations.append(sp.duration_ms)

        def collect_all_pairs():
            """Whole-cohort collection (secure + robust rules): stride only
            bounds store-select batching; every selected model enters ONE
            combine call. Returns (pairs, present_ids)."""
            pairs, present_ids = [], []
            for i in range(0, len(ids), stride):
                block = ids[i : i + stride]
                sp = block_span(block)
                picked = self._timed_select(block, k=lineage_k)
                for lid in block:
                    if lid in picked:
                        pairs.append((picked[lid], scales[lid]))
                        present_ids.append(lid)
                end_block(sp, block)
            return pairs, present_ids

        if self.config.secure.enabled and (
                self._masked_stream is not None
                or (self._slices is not None
                    and getattr(self._slices, "masked", False))):
            # Masked partial-fold plane (secure/distributed.py): the
            # round's per-tensor uint64 sums were accumulated where the
            # uplinks landed (controller stream or slice processes);
            # barrier release reconciles contributors against the
            # dispatched cohort and settles the masks
            # (secure/recovery.py) — dropouts recovered via seed-share
            # disclosure, never silently folded in.
            if self._masked_stream is not None:
                folded = self._masked_stream.stats()["folded"]
                sp = block_span(range(folded))
                with sp:
                    snap = self._masked_stream.finish(selected)
                end_block(sp, range(folded))
            else:
                slice_sp = _ttrace.span(
                    "round.slice_reduce", parent=agg_sp,
                    attrs={"cohort": len(ids), "masked": True})
                with slice_sp, slice_sp.activate():
                    reduced = self._slices.reduce_masked(
                        ids, self.global_iteration)
                _M_SECURE_FOLDS.inc(tier="root")
                if reduced is None:
                    snap = None
                else:
                    m_sums, m_specs, m_present, slice_errors = reduced
                    snap = (m_sums, m_specs, m_present)
                    if slice_errors:
                        with self._lock:
                            self._current_meta.errors.extend(slice_errors)
            if snap is None:
                logger.warning("no masked contributions for cohort %s",
                               list(selected))
                return
            community = self._settle_masked(*snap)
        elif self.config.secure.enabled:
            # Secure: masking sums must cancel across ALL parties.
            pairs, present_ids = collect_all_pairs()
            if not pairs:
                logger.warning("no stored models for cohort %s", list(selected))
                return
            parsed = self._parse_secure(pairs)
            correction = None
            if self.config.secure.scheme == "masking":
                correction = self._masking_dropout_correction(
                    present_ids, parsed)
            community = self._aggregator.aggregate(parsed,
                                                   correction=correction)
        elif self._streaming is not None:
            # Streaming: the community model is already accumulated —
            # barrier release just finalizes it. Zero store reads.
            folded = self._streaming.stats()["folded"]
            sp = block_span(range(folded))
            with sp:
                community = self._streaming.finish(selected)
            end_block(sp, range(folded))
            if community is None:
                logger.warning("no streamed contributions for cohort %s",
                               list(selected))
                return
        elif getattr(self._aggregator, "requires_full_cohort", False):
            # Robust rules (median / trimmed_mean / krum): a median cannot
            # fold stride-wise.
            pairs, present_ids = collect_all_pairs()
            if not pairs:
                logger.warning("no stored models for cohort %s", list(selected))
                return
            if self._health_advisory:
                # advisory hook (telemetry.health.advisory): the rule
                # records which flagged learners entered the cohort —
                # the combine itself is bit-identical either way
                community = self._aggregator.aggregate(
                    pairs, learner_ids=present_ids,
                    advisory_scores=self._health.scores())
            else:
                community = self._aggregator.aggregate(pairs)
        elif self._slices is not None:
            # Distributed slice tier (aggregation/distributed.py): fan in
            # O(branch) FoldPartial replies; a slice aggregator dying
            # between submit and fold re-homes inside reduce() and the
            # round completes from its recovered spool. The rule gate ran
            # at construction (fedavg/scaffold/fedstride only).
            if self._aggregator.name == "fedstride":
                self._aggregator.reset()  # round-scoped state unused here
            slice_sp = _ttrace.span(
                "round.slice_reduce", parent=agg_sp,
                attrs={"cohort": len(ids)})
            with slice_sp, slice_sp.activate():
                reduced = self._slices.reduce(
                    ids, scales,
                    stride=self.config.aggregation.stride_length,
                    round_id=self.global_iteration)
            if reduced is None:
                logger.warning("no held slice models for cohort %s",
                               list(selected))
                return
            community, partials, slice_errors = reduced
            for partial in partials:
                meta_blocks.append(partial.count)
                meta_durations.append(round(partial.duration_ms, 3))
                _M_PHASE.observe(partial.duration_ms / 1e3,
                                 phase="aggregate_block")
            if slice_errors:
                with self._lock:
                    self._current_meta.errors.extend(slice_errors)
        elif (self._tree is not None
              and self._aggregator.name in ("fedavg", "scaffold",
                                            "fedstride")):
            # Tree tier (aggregation/tree.py): B-way slice folds in
            # workers, O(branch) root fan-in; applies to the pure
            # weighted-sum rules on the store path. stride_length=0 is
            # passed through as 0 so the tier applies its own bounded
            # sub-block instead of stacking whole slices.
            if self._aggregator.name == "fedstride":
                self._aggregator.reset()  # round-scoped state unused here
            tree_sp = _ttrace.span("round.tree_reduce", parent=agg_sp,
                                   attrs={"cohort": len(ids),
                                          "branch": self._tree.branch})
            with tree_sp:
                reduced = self._tree.reduce(
                    ids, scales,
                    lambda block: self._timed_select(block, k=lineage_k),
                    stride=self.config.aggregation.stride_length)
            if reduced is None:
                logger.warning("no stored models for cohort %s",
                               list(selected))
                return
            community, partials = reduced
            for partial in partials:
                meta_blocks.append(partial.count)
                meta_durations.append(round(partial.duration_ms, 3))
                _M_PHASE.observe(partial.duration_ms / 1e3,
                                 phase="aggregate_block")
        elif hasattr(self._aggregator, "accumulate"):
            # Fold rules (FedAvg and the ServerOpt family wrapping it):
            # accumulate block-by-block so only one stride block of models is
            # ever resident (the point of the reference's stride loop,
            # controller.cc:842-936). ServerOpt applies its optimizer step
            # once, inside result().
            self._aggregator.reset()
            accumulated = 0
            needs_steps = getattr(self._aggregator, "needs_local_steps",
                                  False)
            for i in range(0, len(ids), stride):
                block = ids[i : i + stride]
                sp = block_span(block)
                picked = self._timed_select(block, k=lineage_k)
                pairs = [(picked[lid], scales[lid]) for lid in block if lid in picked]
                if pairs:
                    if needs_steps:
                        # fednova: per-learner completed local steps (one
                        # optimizer step per batch in this engine)
                        steps = [
                            max(1.0, float(metadata.get(lid, {}).get(
                                "completed_batches", 0.0)) or 1.0)
                            for lid in block if lid in picked]
                        self._aggregator.accumulate(pairs, steps=steps)
                    else:
                        self._aggregator.accumulate(pairs)
                    accumulated += len(pairs)
                end_block(sp, block)
            if not accumulated:
                logger.warning("no stored models for cohort %s", list(selected))
                return
            community = self._aggregator.result()
            self._aggregator.reset()
            # ServerOpt stages its optimizer step inside result(); it is
            # committed below only after the community model is installed,
            # so an aggregation-failure retry does not double-step moments.
        else:
            # rolling rules (fedstride / fedrec): incremental block updates
            for i in range(0, len(ids), stride):
                block = ids[i : i + stride]
                sp = block_span(block)
                picked = self._timed_select(block, k=lineage_k)
                pairs = [(picked[lid], scales[lid]) for lid in block if lid in picked]
                present = [lid for lid in block if lid in picked]
                if pairs:
                    community = self._aggregator.aggregate(
                        pairs, learner_ids=present)
                end_block(sp, block)
            if community is None:
                logger.warning("no stored models for cohort %s", list(selected))
                return

        if self._aggregator.name == "scaffold":
            self._fold_scaffold_controls(ids)

        blob = self._community_to_blob(community)
        # close the span here so its duration covers collection +
        # combine + blob encode — the same interval the old t0 delta did
        agg_sp.end()
        with self._lock:
            if self.config.secure.enabled:
                self._community_opaque = community
            else:
                self._community_flat = community
            self._community_blob = blob
            if hasattr(self._aggregator, "commit"):
                self._aggregator.commit()
            meta = self._current_meta
            meta.selected_learners = list(selected)
            meta.scales = {lid: round(float(w), 6)
                           for lid, w in scales.items()}
            # per-uplink dispatch-version lag (FedBuff staleness-aware
            # scaling's input) — nonzero entries only, so synchronous
            # silo lineage serializes unchanged
            meta.staleness = {
                lid: float(m["staleness"])
                for lid, m in metadata.items() if m.get("staleness")}
            meta.aggregation_block_sizes = meta_blocks
            meta.aggregation_block_duration_ms = meta_durations
            meta.aggregation_duration_ms = agg_sp.duration_ms
            if not self.config.secure.enabled:
                sizes = {"values": 0, "non_zeros": 0, "zeros": 0, "bytes": 0}
                for arr in community.values():
                    q = quantify(np.asarray(arr))
                    for key in sizes:
                        sizes[key] += q[key]
                meta.model_size = sizes

    def _settle_masked(self, sums, specs, contributors):
        """Settle one round's masked partial-fold sums (secure/recovery.py)
        into the opaque community payload: reconcile the contributor set
        against the registered mask parties, recover dropouts via
        seed-share disclosure, unmask, and re-wrap under the SecureAgg
        output contract (float64 payloads, CIPHERTEXT-kind specs).
        Raises when the cohort cannot settle so the aggregation-failure
        retry re-runs the round clean."""
        from metisfl_tpu.secure import recovery as _recovery
        from metisfl_tpu.tensor.spec import TensorKind, TensorSpec

        cfg = self.config.secure
        with self._lock:
            idx_of = {lid: self._learners[lid].party_index
                      for lid in contributors if lid in self._learners}
            registered = {r.party_index for r in self._learners.values()
                          if r.party_index >= 0}
        missing = [lid for lid in contributors if lid not in idx_of]
        if missing:
            raise RuntimeError(
                f"masked contributors {missing} have no registration "
                "record; their party indices are unknown and the sum "
                "cannot settle")
        n = cfg.num_parties or (max(registered) + 1 if registered else 0)
        if n <= 0:
            raise RuntimeError(
                "mask settlement needs the registered party count "
                "(secure.num_parties, driver-filled) or joined "
                "capabilities['party_index'] values")
        round_id = self.global_iteration

        def recover_fn(rid, surviving, dropped, lengths):
            return self._request_mask_recovery(
                rid, surviving, dropped, lengths, list(contributors))

        payloads, report = _recovery.settle(
            sums, idx_of, n, max(2, cfg.min_recovery_parties),
            round_id, recover_fn)
        _M_SECURE_SETTLEMENT.observe(report.duration_ms / 1e3)
        if report.recovered:
            _M_SECURE_RECOVERED.inc(len(report.dropped))
        _tevents.emit(
            _tevents.SecureSettlement, round=round_id,
            contributors=len(report.contributors),
            dropped=len(report.dropped), recovered=report.recovered,
            tier="stream" if self._masked_stream is not None else "slice",
            duration_ms=round(report.duration_ms, 3))
        community = {}
        for name, payload in payloads.items():
            spec = specs[name]
            community[name] = (payload, TensorSpec(
                tuple(spec.shape), spec.dtype, TensorKind.CIPHERTEXT))
        return community

    def _request_mask_recovery(self, round_id, surviving, dropped,
                               lengths, candidates):
        """Walk the surviving learners' proxies for ONE residual
        disclosure (MaskingBackend.recovery_correction — the learner
        side enforces the privacy thresholds). Returns the per-tensor
        correction list, None when the transport cannot recover
        (full-cohort semantics apply downstream), and raises when every
        survivor refused or errored."""
        last_error = None
        for lid in candidates:
            record = self._learners.get(lid)
            if record is None or record.proxy is None:
                continue
            if not hasattr(record.proxy, "recover_masks"):
                return None  # transport cannot recover
            try:
                corrections = record.proxy.recover_masks(
                    int(round_id), list(surviving), list(dropped),
                    list(lengths))
            except Exception as exc:  # noqa: BLE001 - try the next one
                last_error = exc
                continue
            logger.warning(
                "masking dropout recovery: %s computed residuals for "
                "dropped parties %s (surviving %d)", lid, list(dropped),
                len(surviving))
            _tevents.emit(_tevents.SecureMasksRecovered,
                          round=int(round_id), survivor=lid,
                          surviving=len(surviving), dropped=len(dropped))
            return corrections
        raise RuntimeError(
            f"masking dropout recovery failed on every survivor: "
            f"{last_error!r}")

    def _masking_dropout_correction(self, present_ids, parsed):
        """Masking dropout recovery: when the aggregating cohort is missing
        registered mask parties (deadline stragglers, crashes), ask ONE
        surviving learner for the dropped parties' residual-mask correction
        (secure/masking.py recovery_correction — the Bonawitz unmasking
        round in this trust model). Returns ``{tensor_name: bytes}`` or
        None when the full cohort is present (masks cancel on their own).
        Raises when recovery is impossible so the aggregation-failure
        full-cohort retry takes over."""
        cfg = self.config.secure
        with self._lock:
            idx_of = {lid: self._learners[lid].party_index
                      for lid in present_ids if lid in self._learners}
            registered = {r.party_index for r in self._learners.values()
                          if r.party_index >= 0}
        surviving = sorted(idx_of.values())
        # party count: driver-filled config, else derived from the joined
        # parties' indices (in-process federations skip the driver)
        n = cfg.num_parties or (max(registered) + 1 if registered else 0)
        if n <= 0 or not surviving or -1 in surviving:
            return None  # party indices unknown: full-cohort semantics
        if len(surviving) == n:
            return None  # nobody dropped
        min_parties = max(2, cfg.min_recovery_parties)
        if len(surviving) < min_parties:
            raise RuntimeError(
                f"masking dropout recovery needs >= {min_parties} surviving "
                f"parties, have {len(surviving)}")
        dropped = sorted(set(range(n)) - set(surviving))
        first_model = parsed[0][0][0]
        names = list(first_model)
        lengths = [int(first_model[name][1].size) for name in names]
        round_id = self.global_iteration
        corrections = self._request_mask_recovery(
            round_id, surviving, dropped, lengths, list(present_ids))
        if corrections is None:
            return None  # transport cannot recover: full-cohort semantics
        return dict(zip(names, corrections))

    def _parse_secure(self, pairs):
        parsed = []
        for lineage, scale in pairs:
            models = []
            for item in lineage:
                if isinstance(item, (bytes, bytearray)):
                    blob = ModelBlob.from_bytes(item)
                    models.append(dict(blob.opaque))
                else:
                    models.append(item)
            parsed.append((models, scale))
        return parsed

    def _community_to_blob(self, community) -> bytes:
        if self.config.secure.enabled:
            return ModelBlob(opaque=dict(community)).to_bytes()
        named = [(name, np.asarray(arr)) for name, arr in community.items()]
        return ModelBlob(tensors=named).to_bytes()

    def _pack_scaffold_c(self) -> bytes:
        """Wire bytes of the server control variate (empty until the first
        cohort's deltas fold in — learners treat empty as zeros). Cached —
        c only changes at fold/restore, and re-serializing a model-sized
        tree per learner per dispatch inside the lock would stall the RPC
        handlers. Call with ``self._lock`` held (dispatch does)."""
        if self._scaffold_c is None:
            return b""
        if self._scaffold_c_blob is None:
            from metisfl_tpu.tensor.pytree import ModelBlob
            self._scaffold_c_blob = ModelBlob(
                tensors=sorted(self._scaffold_c.items())).to_bytes()
        return self._scaffold_c_blob

    def _fold_scaffold_controls(self, cohort: Sequence[str]) -> None:
        """c += (1/N) * sum over the cohort's control deltas (SCAFFOLD
        server update, |S|/N * mean over S — N = active learners)."""
        from metisfl_tpu.tensor.pytree import ModelBlob
        with self._lock:
            blobs = [self._scaffold_deltas.pop(lid)
                     for lid in cohort if lid in self._scaffold_deltas]
            n_active = max(1, len(self._learners))
        if not blobs:
            return
        total: Dict[str, np.ndarray] = {}
        for raw in blobs:
            for name, arr in ModelBlob.from_bytes(raw).tensors:
                arr = np.asarray(arr, np.float32)
                total[name] = total.get(name, 0.0) + arr
        with self._lock:
            if self._scaffold_c is None:
                self._scaffold_c = {n: np.zeros_like(a)
                                    for n, a in total.items()}
            for name, summed in total.items():
                if name in self._scaffold_c:
                    self._scaffold_c[name] = (
                        self._scaffold_c[name] + summed / n_active)
            self._scaffold_c_blob = None  # invalidate the pack cache

    def _scaling_metadata(self, selected: Sequence[str]) -> Dict[str, Dict[str, float]]:
        with self._lock:
            # a learner may leave between cohort selection and aggregation —
            # skip departed ids instead of KeyErroring the round
            records = [(lid, self._learners[lid]) for lid in selected
                       if lid in self._learners]
            return {
                lid: {
                    "num_train_examples": r.num_train_examples,
                    "completed_batches": r.completed_batches,
                    "staleness": float(max(
                        0, self.global_iteration - r.last_result_round))
                    if r.last_result_round >= 0 else 0.0,
                }
                for lid, r in records
                if lid in self._learners
            }

    # -- dispatch ---------------------------------------------------------

    def _dispatch_blob(self) -> Optional[bytes]:
        """The community blob as dispatched: downlink_dtype narrows the
        broadcast wire width (e.g. bf16 halves it across the cohort); the
        narrowed encoding is cached per community model so N dispatches
        re-encode once. Internal state (_community_flat, checkpoints,
        stores) stays full-width."""
        with self._lock:
            blob = self._community_blob
            target_name = self.config.train.downlink_dtype
            if blob is None or not target_name or self.config.secure.enabled:
                return blob
            cached = self._downlink_cache
            if cached is not None and cached[0] is blob:
                return cached[1]
        from metisfl_tpu.tensor.pytree import ModelBlob
        from metisfl_tpu.tensor.spec import narrow_named, resolve_ship_dtype

        parsed = ModelBlob.from_bytes(blob)
        narrowed = ModelBlob(tensors=narrow_named(
            parsed.tensors, resolve_ship_dtype(target_name))).to_bytes()
        with self._lock:
            self._downlink_cache = (blob, narrowed)
        return narrowed

    def _dispatch_train(self, learner_ids: Sequence[str],
                        restart_deadline: bool = True) -> None:
        """SendRunTasks (controller.cc:696-759)."""
        blob = self._dispatch_blob()
        if blob is None:
            logger.warning("no community model yet; cannot dispatch train tasks")
            return
        if restart_deadline:
            with self._lock:
                # a fresh round dispatch renews the per-round retry budget
                # (rejoin/replacement single-learner dispatches do not) and
                # advances the round serial — the staleness fence for BOTH
                # the deadline timer and the retry backoff timers. The bump
                # lives here, not in _arm_round_deadline, so the fence works
                # even with round_deadline_secs=0 (no deadline to arm).
                self._dispatch_retries_used = 0
                self._round_serial += 1
            if self._slices is not None:
                # distributed slice tier: partition the fresh round's
                # cohort into contiguous slices over the live aggregators
                # (and revive any the driver has relaunched). Rejoin /
                # replacement single-learner dispatches keep the round's
                # map — their uplinks route by it (unknowns go to root).
                self._slices.assign(list(learner_ids))
            if self._masked_stream is not None:
                # rotate the masked fold-on-arrival accumulator for the
                # fresh round (mask streams are round-keyed; a stale
                # fold into the new accumulator would never cancel)
                self._masked_stream.begin_round(self.global_iteration)
        # The dispatched set is the synchronous round barrier (participation
        # sampling means it can be a strict subset of the active learners).
        self._scheduler.notify_dispatched(list(learner_ids))
        with self._lock:
            self._phase = "dispatch"
            if not self._current_meta.started_at:
                # first dispatch of this round == round start
                # (reference controller.cc:406-418); the round span is the
                # root of this round's trace — learner train spans parent
                # under it via the RPC metadata the dispatch carries
                self._current_meta.started_at = time.time()
                # deterministic root: the trace id IS the round serial
                # (telemetry/causal.py selects a round's tree by id; a
                # retry dispatch bumped the serial, so its trace never
                # collides with the aborted attempt's)
                self._round_span = _ttrace.span(
                    "round", parent=None,
                    trace_id=_ttrace.round_trace_id(self._round_serial),
                    attrs={"round": self.global_iteration,
                           "serial": self._round_serial})
                _tevents.emit(_tevents.RoundStarted,
                              round=self.global_iteration,
                              cohort=len(learner_ids))
            round_span = self._round_span
        # performance observatory: periodic jax.profiler capture — when
        # this round is due, the dispatched tasks carry a profile_dir and
        # the learners trace one steady-state window each
        profile_trace_dir = ""
        if self._profile is not None:
            profile_trace_dir = self._profile.trace_target(
                self.global_iteration)
        dispatch_sp = _ttrace.span("round.dispatch", parent=round_span,
                                   attrs={"learners": len(learner_ids)})
        with dispatch_sp, dispatch_sp.activate():
            for lid in learner_ids:
                with self._lock:
                    record = self._learners.get(lid)
                    if record is None:
                        continue
                    params = dataclasses.replace(self.config.train)
                    if record.local_steps_override:
                        params.local_steps = record.local_steps_override
                    if self._profile is None:
                        # opt-out contract: the learner's device-stats
                        # path reduces to this one attribute check
                        params.device_stats = False
                    elif profile_trace_dir and not params.profile_dir:
                        params.profile_dir = profile_trace_dir
                    task = TrainTask(
                        task_id=uuid.uuid4().hex,
                        learner_id=lid,
                        round_id=self.global_iteration,
                        global_iteration=self.global_iteration,
                        model=blob,
                        params=params,
                        scaffold=self._aggregator.name == "scaffold",
                        control=self._pack_scaffold_c(),
                        controller_epoch=self.controller_epoch,
                    )
                    self._tasks_in_flight[task.task_id] = lid
                    self._task_dispatched_at[task.task_id] = time.time()
                    self._current_meta.train_submitted_at[lid] = time.time()
                    proxy = record.proxy
                    if self._profile is not None:
                        # downlink wire bytes attributed per learner (the
                        # uplink counterpart lives in _handle_completed).
                        # Under the lock for the same reason as _M_UPLINK:
                        # leave() prunes the series under it, and an
                        # unlocked inc could resurrect a departed
                        # learner's series
                        self._profile.note_downlink(lid, len(blob))
                # journaled BEFORE the send: if the send (or an injected
                # fault) kills the process, the flight recorder still
                # shows what was dispatched
                _tevents.emit(_tevents.TaskDispatched, task_id=task.task_id,
                              learner_id=lid, round=task.round_id)
                try:
                    if hasattr(proxy, "run_task_with_callback"):
                        # async transports surface failures via callback
                        proxy.run_task_with_callback(
                            task, lambda exc, lid=lid, tid=task.task_id:
                            self._note_dispatch_failure(lid, exc, tid))
                    else:
                        proxy.run_task(task)
                except Exception as exc:
                    # Failed dispatches are logged and counted (the reference
                    # only logs and keeps scheduling them, controller.cc:783-786);
                    # async protocols recover, sync rounds rely on the round
                    # deadline / membership changes, and _sample_cohort skips
                    # learners past the consecutive-failure limit.
                    logger.exception("train dispatch to %s failed", lid)
                    self._note_dispatch_failure(lid, exc, task.task_id)
        _M_PHASE.observe(dispatch_sp.duration_ms / 1e3, phase="dispatch")
        with self._lock:
            self._phase = "wait_uplinks"
            # accumulate: join/rejoin re-dispatches add to the same round
            self._current_meta.dispatch_duration_ms += dispatch_sp.duration_ms
            if self._wait_span is None and learner_ids:
                # passive: the wait measures the barrier, not a cause —
                # the critical-path walk (telemetry/causal.py) skips it
                # and descends into the dispatch subtree instead
                self._wait_span = _ttrace.span("round.wait_uplinks",
                                               parent=round_span,
                                               attrs={"passive": True})
        if self._profile is not None:
            # waterfall boundary: the round's FIRST dispatch end (a
            # mid-round rejoin re-dispatch lands inside the wait window
            # and must not move the boundary)
            self._profile.note_mark("dispatch_end", first=True)
        self._arm_round_deadline(restart=restart_deadline)

    def _note_dispatch_failure(self, learner_id: str, exc: Exception,
                               task_id: str = "") -> None:
        with self._lock:
            if task_id:
                # the task never reached the learner, so no completion can
                # ever pop it — without this (and with no round deadline)
                # it would be a forever-"in-flight" ghost in the status
                # plane. The scheduler's round barrier is unaffected: it
                # tracks the dispatched cohort, not task ids.
                self._tasks_in_flight.pop(task_id, None)
                self._task_dispatched_at.pop(task_id, None)
            record = self._learners.get(learner_id)
            if record is None:
                return
            record.dispatch_failures += 1
            count = record.dispatch_failures
        limit = self.config.max_dispatch_failures
        if limit > 0 and count == limit:
            logger.warning(
                "learner %s unreachable after %d failed dispatches (%r); "
                "excluded from cohort sampling until it reports or rejoins",
                learner_id, count, exc)
        self._note_churn(learner_id, "dispatch_failure")
        self._maybe_retry_dispatch(learner_id)

    def _maybe_retry_dispatch(self, failed_id: str) -> None:
        """Bounded dispatch retry-with-backoff (scheduling.dispatch_retries):
        a provably failed dispatch schedules a replacement dispatch after
        doubling backoff, up to the per-round budget. Off (the default)
        this is one attribute check and a failed dispatch keeps today's
        stall-until-deadline behavior."""
        cfg = self.config.scheduling
        if cfg.dispatch_retries <= 0 or self._shutdown.is_set():
            return
        with self._lock:
            if self._dispatch_retries_used >= cfg.dispatch_retries:
                return
            self._dispatch_retries_used += 1
            attempt = self._dispatch_retries_used
            # staleness fence, same posture as the deadline timer: a
            # backoff timer armed for round N must not fire actions into
            # round N+1 (the serial advances per deadline re-arm)
            serial = self._round_serial
        delay = cfg.retry_backoff_s * (2 ** (attempt - 1))

        def _fire():
            with self._lock:
                self._retry_timers.pop(timer, None)
            if self._shutdown.is_set():
                return
            try:
                self._pool.submit(self._guard, self._retry_dispatch,
                                  failed_id, attempt, serial)
            except RuntimeError:  # pool already shut down
                pass

        timer = threading.Timer(delay, _fire)
        timer.daemon = True
        with self._lock:
            if self._shutdown.is_set():
                return
            self._retry_timers[timer] = None
        timer.start()

    def _retry_dispatch(self, failed_id: str, attempt: int,
                        serial: int = 0) -> None:
        """Runs on the scheduling executor after the backoff: drop the
        dead endpoint from the round barrier (the round must not wait on
        a task that was never delivered) and dispatch a replacement
        learner in its place — the reporter pool stays at strength under
        endpoint churn instead of shrinking toward the deadline."""
        if self._shutdown.is_set():
            return
        with self._lock:
            if serial != self._round_serial:
                return  # the round that armed this retry already closed
            busy = set(self._tasks_in_flight.values())
            record = self._learners.get(failed_id)
            healed = record is not None and record.dispatch_failures == 0
        if failed_id in busy or healed:
            # the endpoint healed since the failure (a completion reset
            # its failure count, or a rejoin re-dispatch gave it a LIVE
            # task): ejecting it from the barrier now would silently
            # exclude a deliverable — or already delivered — contribution
            return
        drop = getattr(self._scheduler, "drop_dispatched", None)
        released: List[str] = []
        if drop is not None:
            released = drop(failed_id, self.active_learners())
        dispatched: set = set()
        getter = getattr(self._scheduler, "dispatched_ids", None)
        if getter is not None:
            dispatched = getter()
        pool = [lid for lid in self._admission_pool()
                if lid != failed_id and lid not in dispatched
                and lid not in busy]
        replacement = random.choice(pool) if pool else ""
        _M_DISPATCH_RETRIES.inc()
        _tevents.emit(_tevents.DispatchRetried, learner_id=failed_id,
                      replacement=replacement, attempt=attempt)
        if released:
            # dropping the dead endpoint satisfied the (quorum) barrier:
            # finish the round instead of growing it by a replacement
            if self._quorum > 0:
                self._expire_unreported(released)
            self._complete_round(released)
            return
        if not replacement:
            logger.warning("dispatch retry %d for %s: no replacement "
                           "learner available", attempt, failed_id)
            return
        logger.info("dispatch retry %d: replacing unreachable %s with %s",
                    attempt, failed_id, replacement)
        self._dispatch_train([replacement], restart_deadline=False)

    def _send_eval_tasks(self) -> None:
        """SendEvaluationTasks (controller.cc:571-647) + digest callback."""
        cfg = self.config.eval
        if cfg.every_n_rounds <= 0:
            return
        if (self.global_iteration + 1) % cfg.every_n_rounds != 0:
            return
        blob = self._dispatch_blob()
        with self._lock:
            learners = list(self._learners.values())
            iteration = self.global_iteration
            # bind eval timestamps to the SUBMITTING round's metadata — the
            # digest callback may fire after _complete_round swapped
            # _current_meta, and the received_at must land in the same round
            # record as its submitted_at (the reference keeps this lineage
            # clean, controller.cc:582-586, :673-675)
            meta = self._current_meta
        if blob is None:
            return
        entry: Dict[str, Any] = {"global_iteration": iteration, "evaluations": {}}
        with self._lock:
            self.community_evaluations.append(entry)
        eval_sp = _ttrace.span("round.eval_dispatch",
                               parent=self._round_span,
                               attrs={"learners": len(learners)})
        for record in learners:
            task = EvalTask(
                task_id=uuid.uuid4().hex,
                learner_id=record.learner_id,
                round_id=iteration,
                model=blob,
                batch_size=cfg.batch_size,
                datasets=list(cfg.datasets),
                metrics=list(cfg.metrics),
                local_tensor_regex=self.config.train.local_tensor_regex,
                ship_tensor_regex=self.config.train.ship_tensor_regex,
                controller_epoch=self.controller_epoch,
            )
            with self._lock:
                meta.eval_submitted_at[record.learner_id] = time.time()

            def _digest(result: EvalResult, lid=record.learner_id,
                        entry=entry, meta=meta):
                with self._lock:
                    entry["evaluations"][lid] = result.evaluations
                    now = time.time()
                    meta.eval_received_at[lid] = now
                    rec = self._learners.get(lid)
                    sent = meta.eval_submitted_at.get(lid, 0.0)
                    if rec is not None and sent:
                        rec.ewma_eval_s = _ewma(rec.ewma_eval_s,
                                                max(0.0, now - sent))
                # outside the controller lock: the fold takes the registry
                # lock and may emit promotion events — one attribute check
                # when the registry is off
                if self._registry is not None:
                    self._note_registry_eval(entry, expected=len(learners))

            try:
                with eval_sp.activate():
                    record.proxy.evaluate(task, _digest)
                if self._profile is not None:
                    # eval broadcasts are downlink wire bytes too — under
                    # the lock with a membership re-check (same posture
                    # as _M_UPLINK): leave() prunes the series strictly
                    # after deleting the record, so attributing only to
                    # a still-registered learner cannot resurrect a
                    # pruned series
                    with self._lock:
                        if record.learner_id in self._learners:
                            self._profile.note_downlink(
                                record.learner_id, len(blob))
            except Exception:
                logger.exception("eval dispatch to %s failed", record.learner_id)
        eval_sp.end()

    # ------------------------------------------------------------------ #
    # checkpoint / resume
    # ------------------------------------------------------------------ #

    _CKPT_NAME = "controller_ckpt.bin"

    def _checkpoint_state(self) -> Dict[str, Any]:
        """One serializable capture of everything round bit-identity
        depends on — community model, round counter + lineage metadata,
        learner registry + auth tokens, aggregator/SCAFFOLD state,
        registry lineage, health scores, metric-budget sketches. Shared
        verbatim by the on-disk checkpoint (save_checkpoint) and the
        hot-standby WAL snapshot (controller/wal.py): a promoted standby
        restores exactly what ``--resume`` restores."""
        with self._lock:
            state = {
                "global_iteration": self.global_iteration,
                "community_blob": self._community_blob or b"",
                "round_metadata": [m.to_dict() for m in self.round_metadata],
                "community_evaluations": self._snapshot_evaluations(),
                # Learner registry + auth tokens (crash-failover): a
                # restarted controller must recognize rejoining learners
                # as THEMSELVES — same id, same token, same masking/
                # SCAFFOLD party index — or every credentialed rejoin
                # would register a ghost duplicate and secure-agg party
                # maps would break. Proxies are rebuilt at restore.
                "learners": [self._learner_entry(r)
                             for r in self._learners.values()],
            }
            # Rolling rules (FedRec) carry cross-round state; persist the
            # contribution scales so resume can rebuild wc_scaled/z from the
            # store's lineage (aggregation/rolling.py rehydrate).
            if hasattr(self._aggregator, "export_scales"):
                state["agg_scales"] = self._aggregator.export_scales()
            # server-opt rules persist their moments + step-from model
            if hasattr(self._aggregator, "export_state"):
                state["agg_state"] = self._aggregator.export_state()
            if self._scaffold_c is not None:
                state["scaffold_c"] = self._pack_scaffold_c()
            if self._health is not None:
                # divergence scores + last-uplink summaries + the latest
                # round snapshot survive a failover restart (same posture
                # as the straggler EWMAs above) — scores must not reset
                # to "everyone is typical" after a crash
                state["health"] = self._health.export_state()
        if self._registry is not None:
            # model-lifecycle lineage (+ retained blobs, retention-
            # bounded): channel heads and rollback targets must survive
            # --resume failover or the serving plane would lose its
            # promoted model across a controller crash. Outside the
            # controller lock — the export takes the registry's own.
            state["registry"] = self._registry.export_state()
        if self._cardinality_budget > 0:
            # collapsed per-learner families persist as sketches —
            # O(budget) checkpoint bytes however large the fleet, and
            # the digest quantiles survive --resume failover (empty dict
            # below budget: nothing has collapsed, series are exact)
            budget_state = _REG.budget_state()
            if budget_state:
                state["metrics_budget"] = budget_state
        return state

    @staticmethod
    def _learner_entry(r: LearnerRecord) -> Dict[str, Any]:
        """The learner's serialized registry entry — one shape shared by
        checkpoint/WAL-snapshot state and the WAL's per-join delta, so
        replay merge (wal.py) and restore agree field-for-field.
        Straggler EWMAs ride along so scores do not reset to "everyone
        is typical" after a failover."""
        return {"learner_id": r.learner_id,
                "auth_token": r.auth_token,
                "hostname": r.hostname,
                "port": r.port,
                "num_train_examples": r.num_train_examples,
                "num_val_examples": r.num_val_examples,
                "num_test_examples": r.num_test_examples,
                "completed_batches": r.completed_batches,
                "ms_per_step": float(r.ms_per_step),
                "last_result_round": r.last_result_round,
                "party_index": r.party_index,
                "local_steps_override": r.local_steps_override,
                "ewma_train_s": float(r.ewma_train_s),
                "ewma_eval_s": float(r.ewma_eval_s)}

    def save_checkpoint(self, path: Optional[str] = None,
                        state: Optional[Dict[str, Any]] = None) -> str:
        """Persist community model + round counter + lineage metadata.

        Closes the reference's resume gap (SURVEY.md §5.4: resume there is
        manual re-seeding via ReplaceCommunityModel, controller.cc:85-96 —
        the round counter and metadata lineage are lost). ``state`` lets
        the coalesced saver reuse one capture for checkpoint + WAL
        snapshot; the write is atomic-rename durable (store/durable.py)."""
        if path is None:
            path = os.path.join(self.config.checkpoint.dir, self._CKPT_NAME)
        if state is None:
            state = self._checkpoint_state()
        buf = codec_dumps(state)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        _durable.atomic_write(path, buf, prefix=".ckpt_")
        return path

    def restore_checkpoint(self, path: Optional[str] = None) -> bool:
        """Restore from ``save_checkpoint`` output; returns False when no
        checkpoint exists (fresh start)."""
        if path is None:
            path = self.config.checkpoint.dir
        if os.path.isdir(path):
            path = os.path.join(path, self._CKPT_NAME)
        if not os.path.exists(path):
            return False
        with open(path, "rb") as f:
            state = codec_loads(f.read())
        self._restore_state(state)
        with self._lock:
            n_learners = len(self._learners)
        logger.info("restored checkpoint %s at round %d (%d learner(s) in "
                    "registry, epoch %s)", path, self.global_iteration,
                    n_learners, self.controller_epoch[:8])
        return True

    def restore_from_wal(self) -> bool:
        """Promote-time restore for the hot standby: merge the WAL's
        latest snapshot with every registry delta appended after it
        (controller/wal.py replay/merge) and restore exactly like
        ``--resume`` does from a checkpoint. Returns False when the log
        is empty (primary died before anything durable happened — the
        standby then serves a fresh federation and learners re-attach
        via their own join path)."""
        if self._wal is None:
            return False
        from metisfl_tpu.controller.wal import RoundStateLog
        snapshot, deltas = self._wal.replay()
        state = RoundStateLog.merge(snapshot, deltas)
        if state is None:
            return False
        self._restore_state(state)
        with self._lock:
            n_learners = len(self._learners)
        logger.info("restored WAL round state at round %d (%d learner(s), "
                    "%d registry delta(s) past the snapshot, epoch %s)",
                    self.global_iteration, n_learners, len(deltas),
                    self.controller_epoch[:8])
        return True

    def _restore_state(self, state: Dict[str, Any]) -> None:
        """Apply one ``_checkpoint_state``-shaped dict to this (fresh)
        controller — shared by checkpoint restore and WAL promotion."""
        blob = state.get("community_blob") or None
        with self._lock:
            self.global_iteration = int(state["global_iteration"])
            self.round_metadata = [
                RoundMetadata(**m) for m in state.get("round_metadata", [])]
            self.community_evaluations = list(
                state.get("community_evaluations", []))
            self._current_meta = RoundMetadata(
                global_iteration=self.global_iteration)
        known_fields = {f.name for f in dataclasses.fields(LearnerRecord)}
        for entry in state.get("learners", []):
            record = LearnerRecord(**{k: v for k, v in entry.items()
                                      if k in known_fields})
            try:
                # the checkpointed endpoint may still be live (controller
                # crashed, learners did not): a working proxy lets the
                # restored controller re-dispatch the in-flight round
                # immediately; a dead endpoint surfaces as a dispatch
                # failure and heals when the learner re-attaches
                record.proxy = self._proxy_factory(record)
            except Exception:  # noqa: BLE001 - proxy rebuilt on rejoin
                logger.warning("could not rebuild proxy for %s; waiting "
                               "for re-attach", record.learner_id)
            with self._lock:
                self._learners[record.learner_id] = record
                self._tokens[record.learner_id] = record.auth_token
        with self._lock:
            _M_ACTIVE_LEARNERS.set(len(self._learners))
        if blob:
            self._in_restore = True
            try:
                self.set_community_model(blob)
            finally:
                self._in_restore = False
        agg_scales = state.get("agg_scales")
        if agg_scales and hasattr(self._aggregator, "rehydrate"):
            # FedRec restart-correctness: without this, the rolling sum would
            # silently rebuild from scratch and stragglers' prior
            # contributions would double-count on their next report.
            restored = self._aggregator.rehydrate(self._store, agg_scales)
            logger.info("rehydrated %d/%d rolling contributions from store",
                        restored, len(agg_scales))
        scaffold_c = state.get("scaffold_c")
        if scaffold_c:
            from metisfl_tpu.tensor.pytree import ModelBlob
            with self._lock:
                self._scaffold_c = {
                    name: np.asarray(arr, np.float32)
                    for name, arr in ModelBlob.from_bytes(scaffold_c).tensors}
                self._scaffold_c_blob = None
        agg_state = state.get("agg_state")
        if agg_state and hasattr(self._aggregator, "restore_state"):
            # server-opt restart-correctness: moments + step counter resume
            # the exact update sequence of an uninterrupted run
            self._aggregator.restore_state(agg_state)
        registry_state = state.get("registry")
        if registry_state and self._registry is not None:
            # lifecycle lineage survives failover: version ids stay
            # monotonic across incarnations and the serving gateway's
            # next poll sees the same stable head it served before
            self._registry.restore_state(registry_state)
        metrics_budget = state.get("metrics_budget")
        if metrics_budget and self._cardinality_budget > 0:
            # rehydrate the collapsed families' sketches: the restored
            # controller keeps answering digest quantiles for the whole
            # pre-crash fleet instead of restarting from "no history"
            _REG.restore_budget_state(metrics_budget)
        health_state = state.get("health")
        if health_state and self._health is not None:
            self._health.restore_state(health_state)
            with self._lock:
                for lid, score in self._health.scores().items():
                    if lid in self._learners:
                        _M_DIVERGENCE.set(round(score, 4), learner=lid)

    def resume_round(self) -> bool:
        """Kick the restored federation: dispatch a fresh round to the
        checkpointed cohort (the crash abandoned whatever round was in
        flight — its tasks carry the dead epoch and their completions,
        if any arrive, fold in as regular contributions). Returns False
        when there is nothing to resume (no community model or empty
        registry); rejoining learners then restart rounds via their own
        initial dispatch."""
        with self._lock:
            ready = (self._community_blob is not None
                     and bool(self._learners))
        if not ready or self._shutdown.is_set():
            return False
        self._pool.submit(self._guard, self._resume_dispatch)
        return True

    def _resume_dispatch(self) -> None:
        if self._shutdown.is_set():
            return
        self._scheduler.reset()
        cohort = self._sample_cohort()
        if not cohort:
            return
        logger.info("resuming round %d after restore: dispatching to %s",
                    self.global_iteration, cohort)
        self._dispatch_train(cohort)

    # ------------------------------------------------------------------ #
    # live status plane (DescribeFederation)
    # ------------------------------------------------------------------ #

    def _straggler_scores(self) -> Dict[str, float]:
        """Round-relative straggler scores: each learner's EWMA train
        duration over the registry median (1.0 = typical, >1 = slower,
        0.0 = no observation yet). Call with ``self._lock`` held."""
        from statistics import median

        ewmas = {lid: r.ewma_train_s for lid, r in self._learners.items()}
        positive = [v for v in ewmas.values() if v > 0.0]
        mid = median(positive) if positive else 0.0
        return {lid: (v / mid if (v > 0.0 and mid > 0.0) else 0.0)
                for lid, v in ewmas.items()}

    def _describe_digest_locked(self, scores: Dict[str, float],
                                div_scores: Dict[str, float],
                                churn_scores: Dict[str, float],
                                quarantined: set, limit: int
                                ) -> Dict[str, Any]:
        """Quantile columns for the above-budget DescribeFederation
        snapshot: the registry records are exact controller state, so
        the p50/p90/p99 here are exact — it is the *payload*, not the
        math, the budget bounds. Call with ``self._lock`` held."""
        def _q(values: List[float]) -> Dict[str, float]:
            if not values:
                return {"p50": 0.0, "p90": 0.0, "p99": 0.0, "max": 0.0}
            ordered = sorted(values)
            at = partial(_tmetrics.exact_quantile, ordered)
            return {"p50": round(at(0.5), 4), "p90": round(at(0.9), 4),
                    "p99": round(at(0.99), 4), "max": round(ordered[-1], 4)}

        records = self._learners
        live = sum(1 for r in records.values()
                   if limit <= 0 or r.dispatch_failures < limit)
        columns = {
            "straggler_score": _q([scores.get(lid, 0.0) for lid in records]),
            "ewma_train_s": _q([r.ewma_train_s for r in records.values()]),
            "dispatch_failures": _q([float(r.dispatch_failures)
                                     for r in records.values()]),
        }
        if self._health is not None:
            columns["divergence_score"] = _q(
                [div_scores.get(lid, 0.0) for lid in records])
        if self._churn is not None:
            columns["churn_score"] = _q(
                [churn_scores.get(lid, 0.0) for lid in records])
        return {
            "count": len(records),
            "live": live,
            "budget": self._cardinality_budget,
            "quarantined": len(quarantined),
            "columns": columns,
        }

    def _fold_round_health(self) -> None:
        """Learning-health cohort fold for the round that just aggregated
        (telemetry/health.py): per-learner cohort cosines + robust-z
        divergence scores, the round's convergence snapshot into
        ``RoundMetadata.health``, the ``learner_divergence_score`` /
        ``round_update_norm`` gauges, and the ``UpdateAnomalous`` /
        ``RoundHealth`` journal events. Runs on the scheduling executor
        with ``global_iteration`` still naming the completing round;
        never raises (telemetry must not trip the aggregation-failure
        retry path)."""
        if self._health is None:
            return
        try:
            with self._lock:
                # replaced, never mutated in place — safe un-copied
                community = self._community_flat or {}
                scales = dict(self._current_meta.scales)
            health, anomalies = self._health.complete_round(
                self.global_iteration, community, scales)
            with self._lock:
                self._current_meta.health = health
                # set() under the controller lock for the same
                # churn-prune race reason as the straggler gauge
                for lid, score in health["divergence_score"].items():
                    if lid in self._learners:
                        _M_DIVERGENCE.set(score, learner=lid)
            _M_ROUND_UPDATE_NORM.set(health["round_update_norm"])
            for anomaly in anomalies:
                logger.warning(
                    "learner %s update anomalous in round %d (robust z "
                    "%.2f >= %.2f; divergence score %.2f)",
                    anomaly["learner_id"], anomaly["round"], anomaly["raw"],
                    self._health.anomaly_threshold, anomaly["score"])
                _tevents.emit(_tevents.UpdateAnomalous, **anomaly)
            _tevents.emit(
                _tevents.RoundHealth, round=health["round"],
                update_norm=health["round_update_norm"],
                effective_step=health["effective_step"],
                participation_entropy=health["participation_entropy"],
                anomalous=len(anomalies))
        except Exception:  # noqa: BLE001 - telemetry never fails a round
            logger.exception("round health fold failed")

    # ------------------------------------------------------------------ #
    # model lifecycle plane (registry/registry.py)
    # ------------------------------------------------------------------ #

    def _register_round_version(self) -> None:
        """Mint a registry candidate from the round that just aggregated
        and record the lifecycle lineage into ``RoundMetadata``. Runs on
        the scheduling executor with ``global_iteration`` still naming
        the completing round; never raises (lifecycle bookkeeping must
        not trip the aggregation-failure retry path). One attribute
        check when the registry is off."""
        if self._registry is None:
            return
        try:
            with self._lock:
                blob = self._community_blob
                health = dict(self._current_meta.health)
            if blob is None:
                return
            info = self._registry.register(self.global_iteration, blob,
                                           health)
            from metisfl_tpu.registry import CHANNEL_STABLE
            stable = self._registry.head(CHANNEL_STABLE)
            with self._lock:
                self._current_meta.registered_version = info.version
                self._current_meta.stable_version = (
                    stable.version if stable is not None else 0)
        except Exception:  # noqa: BLE001 - lifecycle never fails a round
            logger.exception("model version registration failed")

    def _note_registry_eval(self, entry: Dict[str, Any],
                            expected: int = 0) -> None:
        """Fold a round's community evaluation into its registered
        version ({"<dataset>/<metric>": mean across learners}); under
        promotion.auto this is what tips a candidate to stable — but the
        gate only arms once ALL ``expected`` digests landed, so a single
        fast learner's partial mean can never promote a model the full
        cohort would have rejected. Runs on eval-digest threads; never
        raises."""
        if self._registry is None:
            return
        try:
            with self._lock:
                evals = {lid: dict(v)
                         for lid, v in entry["evaluations"].items()}
                round_id = int(entry["global_iteration"])
            per: Dict[str, List[float]] = {}
            for learner_evals in evals.values():
                for ds, metrics in learner_evals.items():
                    for name, value in metrics.items():
                        try:
                            per.setdefault(f"{ds}/{name}", []).append(
                                float(value))
                        except (TypeError, ValueError):
                            continue
            if not per:
                return
            folded = {k: sum(v) / len(v) for k, v in per.items()}
            promoted = self._registry.note_eval(
                round_id, folded, gate=len(evals) >= expected)
            if promoted is not None:
                logger.info("round %d eval promoted model version v%d to "
                            "stable", round_id, promoted.version)
        except Exception:  # noqa: BLE001 - eval digest must never break
            logger.exception("registry eval fold failed")

    def describe_registry(self) -> Dict[str, Any]:
        """Registry snapshot for the DescribeRegistry RPC / status CLI /
        serving-gateway polls; ``{"enabled": False}`` when off."""
        if self._registry is None:
            return {"enabled": False}
        return self._registry.describe()

    def registered_model(self, version: int = 0,
                         channel: str = "") -> Optional[bytes]:
        """A registered version's blob, by id or channel head."""
        if self._registry is None:
            return None
        if not version and channel:
            head = self._registry.head(channel)
            if head is None:
                return None
            version = head.version
        return self._registry.blob(version) if version else None

    def promote_version(self, version: int, force: bool = False):
        if self._registry is None:
            raise ValueError("model registry is not enabled")
        info = self._registry.promote(version, force=force)
        # durability: the new stable head must survive a crash landing
        # between this promotion and the next round's auto-checkpoint
        # (the queued save snapshots state at run time, post-promotion)
        self._checkpoint_async()
        return info

    def rollback_version(self):
        if self._registry is None:
            raise ValueError("model registry is not enabled")
        info = self._registry.rollback()
        if info is not None:
            self._checkpoint_async()
        return info

    def _update_straggler_gauge(self, completed: Optional[str] = None
                                ) -> None:
        # set() under the controller lock, like _M_UPLINK.inc: leave()
        # deletes the record under this lock and prunes the series after,
        # so an unlocked set here could interleave and resurrect a
        # departed learner's series (unbounded cardinality under churn)
        with self._lock:
            if completed is not None and _M_STRAGGLER.collapsed():
                # cross-device scale: a full-fleet refresh per uplink is
                # O(fleet) work 600 times a round at 10k clients. Once
                # the family is actually COLLAPSED (not merely budget-
                # armed: a sub-budget fleet keeps exact series, and
                # exact series must keep re-normalizing against the
                # moving median) only the reporter's score is
                # re-observed — against the median of OBSERVED ewmas,
                # which is what the full refresh normalizes by too.
                record = self._learners.get(completed)
                if record is None or record.ewma_train_s <= 0.0:
                    return
                mid = self._straggler_median_cache
                if mid is None:
                    # recomputed at most once per round (invalidated at
                    # round close): the O(fleet) scan must not run per
                    # uplink under the controller lock
                    from statistics import median

                    positive = [r.ewma_train_s
                                for r in self._learners.values()
                                if r.ewma_train_s > 0.0]
                    mid = median(positive) if positive else 0.0
                    self._straggler_median_cache = mid
                score = record.ewma_train_s / mid if mid > 0.0 else 0.0
                _M_STRAGGLER.set(round(score, 4), learner=completed)
                return
            for lid, score in self._straggler_scores().items():
                _M_STRAGGLER.set(round(score, 4), learner=lid)

    def describe(self, event_tail: int = 50) -> Dict[str, Any]:
        """Live federation snapshot for the ``DescribeFederation`` RPC /
        ``python -m metisfl_tpu.status`` watch CLI: current round + phase,
        per-learner liveness and straggler analytics, in-flight tasks,
        store occupancy, and the event-ring tail. Read-only and cheap —
        safe to poll every couple of seconds."""
        now = time.time()
        div_scores: Dict[str, float] = {}
        div_last: Dict[str, Dict[str, Any]] = {}
        if self._health is not None:
            div_scores = self._health.scores()
            div_last = self._health.last_stats()
        churn_scores: Dict[str, float] = {}
        quarantined: set = set()
        if self._churn is not None:
            churn_scores = self._churn.scores()
            quarantined = set(self._churn.quarantined_ids(now))
        budget = self._cardinality_budget
        learners_digest: Optional[Dict[str, Any]] = None
        with self._lock:
            scores = self._straggler_scores()
            limit = self.config.max_dispatch_failures

            def _row(lid: str, r: "LearnerRecord") -> Dict[str, Any]:
                return {
                    "learner_id": r.learner_id,
                    "hostname": r.hostname,
                    "port": r.port,
                    # liveness mirrors _sample_cohort's exclusion rule
                    "live": limit <= 0 or r.dispatch_failures < limit,
                    "dispatch_failures": r.dispatch_failures,
                    "num_train_examples": r.num_train_examples,
                    "last_result_round": r.last_result_round,
                    "ewma_train_s": round(r.ewma_train_s, 3),
                    "ewma_eval_s": round(r.ewma_eval_s, 3),
                    "straggler_score": round(scores.get(lid, 0.0), 4),
                    # learning-health analytics (0.0 until observed;
                    # keys present iff the health plane is on)
                    **({"divergence_score":
                        round(div_scores.get(lid, 0.0), 4),
                        "last_update_norm":
                        div_last.get(lid, {}).get("update_norm", 0.0)}
                       if self._health is not None else {}),
                    # churn-aware admission analytics (keys present iff
                    # the churn plane is on)
                    **({"churn_score": round(churn_scores.get(lid, 0.0), 4),
                        "quarantined": lid in quarantined}
                       if self._churn is not None else {}),
                }

            if budget > 0 and len(self._learners) > budget:
                # cardinality-safe snapshot (docs/OBSERVABILITY.md
                # "Telemetry at scale"): above budget the per-learner
                # table would make every status poll O(fleet) — ship
                # quantile columns + the top offenders instead. Below
                # budget (or budget off) the snapshot is byte-identical
                # to the exact shape (test-pinned).
                learners_digest = self._describe_digest_locked(
                    scores, div_scores, churn_scores, quarantined, limit)
                offenders = sorted(
                    self._learners,
                    key=lambda lid: -scores.get(lid, 0.0))[:10]
                learners = [_row(lid, self._learners[lid])
                            for lid in sorted(offenders)]
            else:
                learners = [_row(lid, r)
                            for lid, r in sorted(self._learners.items())]
            in_flight = [
                {"task_id": tid, "learner_id": lid,
                 "age_s": round(max(
                     0.0, now - self._task_dispatched_at.get(tid, now)), 3)}
                for tid, lid in self._tasks_in_flight.items()
            ]
            snapshot = {
                "controller_epoch": self.controller_epoch,
                "round": self.global_iteration,
                "phase": self._phase,
                "protocol": self.config.protocol,
                "round_started_at": self._current_meta.started_at,
                "aggregation_rule": self._aggregator.name,
                "shutdown": self._shutdown.is_set(),
            }
        # store occupancy OUTSIDE our lock (the store has its own). In
        # digest mode the per-learner map is elided too — it is the same
        # O(fleet) payload the learner table was.
        occupancy = {lid: self._store.size(lid)
                     for lid in self._store.learner_ids()}
        snapshot.update({
            "learners": learners,
            "in_flight": in_flight,
            "store": ({"models": {}, "learners": len(occupancy),
                       "total": sum(occupancy.values())}
                      if learners_digest is not None else
                      {"models": occupancy,
                       "total": sum(occupancy.values())}),
            "events": _tevents.tail(event_tail) if event_tail else [],
            "time": round(now, 6),
        })
        if learners_digest is not None:
            snapshot["learners_digest"] = learners_digest
        if self._alerts is not None:
            # SLO alerting plane: active alerts + lifecycle counts, and
            # the bounded time-series ring behind status sparklines
            snapshot["alerts"] = self._alerts.summary(now=now)
            snapshot["timeseries"] = self._alerts.series_snapshot()
        sched_cfg = self.config.scheduling
        if (self._quorum > 0 or sched_cfg.dispatch_retries > 0
                or self._scheduler.name == "asynchronous_buffered"
                or quarantined):
            # churn-tolerant scheduling section: present only when one of
            # its planes is armed, so silo-regime snapshots are unchanged
            section: Dict[str, Any] = {}
            if self._quorum > 0:
                section["quorum"] = self._quorum
                section["overprovision"] = sched_cfg.overprovision
            if self._scheduler.name == "asynchronous_buffered":
                section["buffer_size"] = self._scheduler.buffer_size
                section["buffer_pending"] = self._scheduler.pending()
            if sched_cfg.dispatch_retries > 0:
                with self._lock:
                    section["dispatch_retries_used"] = \
                        self._dispatch_retries_used
                section["dispatch_retries"] = sched_cfg.dispatch_retries
            if quarantined:
                section["quarantined"] = sorted(quarantined)
            snapshot["scheduling"] = section
        if self._ingest is not None:
            errors, _ = self._ingest.errors()
            snapshot["ingest"] = {"workers": self._ingest.workers,
                                  "queue_depth": self._ingest.queue_depth(),
                                  "errors": errors}
        if self._slices is not None:
            # distributed slice tier: per-aggregator liveness/re-home
            # state + the O(branch) merged uplink-byte rollup
            snapshot["slices"] = self._slices.describe()
        if self._streaming is not None:
            snapshot["streaming"] = self._streaming.stats()
        if self._masked_stream is not None:
            snapshot["secure_stream"] = self._masked_stream.stats()
        if self._health is not None:
            # latest round's convergence snapshot ({} before round 1)
            snapshot["health"] = self._health.snapshot()
        if self._registry is not None:
            # model-lifecycle snapshot (channel heads + version lineage)
            snapshot["registry"] = self._registry.describe()
        if self._profile is not None:
            # latest round's cost profile (phase waterfall + wire totals)
            snapshot["profile"] = self._profile.summary()
        return snapshot

    # ------------------------------------------------------------------ #
    # statistics (driver)
    # ------------------------------------------------------------------ #

    def _snapshot_evaluations(self, tail: int = 0) -> List[dict]:
        """Copy evaluation entries deep enough to detach the mutable
        ``evaluations`` dict, which eval-digest callbacks keep inserting into
        under the lock — a caller serializing a shallow copy outside the lock
        would race those inserts. Call with ``self._lock`` held."""
        entries = (self.community_evaluations[-tail:] if tail > 0
                   else self.community_evaluations)
        return [{**e, "evaluations": dict(e["evaluations"])}
                for e in entries]

    def get_statistics(self) -> dict:
        with self._lock:
            return {
                "global_iteration": self.global_iteration,
                "learners": sorted(self._learners.keys()),
                "round_metadata": [m.to_dict() for m in self.round_metadata],
                "community_evaluations": self._snapshot_evaluations(),
            }

    def get_runtime_metadata(self, tail: int = 0) -> List[dict]:
        """Round-metadata lineage, optionally only the last ``tail`` rounds
        (the reference's granular lineage getters, controller.proto:27-44 —
        a 10k-round federation must not ship its whole history per poll)."""
        with self._lock:
            metas = (self.round_metadata[-tail:] if tail > 0
                     else list(self.round_metadata))
            return [m.to_dict() for m in metas]

    def get_evaluation_lineage(self, tail: int = 0) -> List[dict]:
        """Community-model evaluation lineage, optionally tail-bounded
        (reference GetCommunityModelEvaluationLineage, controller.proto:27)."""
        with self._lock:
            return self._snapshot_evaluations(tail)
