"""Write-ahead round-state log for controller hot-standby failover.

The controller is the federation's last single point of failure
(docs/RESILIENCE.md): registry, scheduler barriers, model store lineage
and the aggregation root all live in one process. This log replicates
the round state a warm standby (``python -m metisfl_tpu.controller
--standby``) needs to take over mid-run, using the acked⇒durable
atomic-rename discipline the slice-aggregator spool established
(store/durable.py):

- **Registry deltas** (``join`` / ``leave``) are appended synchronously
  on the RPC path, BEFORE the join/leave ack returns — a learner the
  primary acked is a learner the promoted standby recognizes (same id,
  token, party index), never a ghost.
- **Snapshots** carry the full checkpoint state
  (``Controller._checkpoint_state()``: community blob, round counter,
  aggregator/SCAFFOLD state, registry lineage, health scores…) and are
  appended by the same coalesced scheduling-executor hook that writes
  the on-disk checkpoint — at model seed, round close, and membership
  bursts. A snapshot makes every older record dead weight, so the log
  self-compacts on append.

Replay (:meth:`RoundStateLog.replay`) merges the latest snapshot with
every registry delta that follows it. Deltas *behind* the snapshot are
already inside it; deltas *after* it keep the registry exact for the
window before the next snapshot lands. The in-flight round itself is
deliberately NOT replicated uplink-by-uplink: promotion re-dispatches it
from the last snapshot's community model (``resume_round``), and because
training and aggregation are deterministic functions of (model, cohort),
the re-run round completes bit-identical to an undisturbed run — the
same argument (and test pin) as checkpoint ``--resume``.

File format: one record per file, ``<seq:010d>.<kind>.rec`` holding a
codec envelope ``{"seq", "kind", "data"}``. One-file-per-record keeps
every append atomic (rename), keeps a torn tail record from corrupting
the log, and lets the standby tail the directory with nothing but
``listdir``.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from metisfl_tpu.comm.codec import dumps as codec_dumps
from metisfl_tpu.comm.codec import loads as codec_loads
from metisfl_tpu.store import durable as _durable

logger = logging.getLogger("metisfl_tpu.controller.wal")

SNAPSHOT = "snapshot"
# registry deltas appended synchronously before the membership ack
JOIN = "join"
LEAVE = "leave"

_RECORD_SUFFIX = ".rec"


def _record_name(seq: int, kind: str) -> str:
    return f"{seq:010d}.{_durable.sanitize_id(kind)}{_RECORD_SUFFIX}"


def _parse_name(name: str) -> Optional[Tuple[int, str]]:
    if not name.endswith(_RECORD_SUFFIX):
        return None
    stem = name[: -len(_RECORD_SUFFIX)]
    seq_part, dot, kind = stem.partition(".")
    if not dot or not seq_part.isdigit():
        return None
    return int(seq_part), kind


class RoundStateLog:
    """Durable, self-compacting record log in one directory.

    Writer side (the primary): :meth:`append` / :meth:`snapshot`, both
    atomic-rename durable before they return. Reader side (the
    standby): :meth:`poll` for cheap tail progress, :meth:`replay` for
    the promote-time state merge. The two sides share nothing but the
    directory — the standby never dials the primary for state."""

    def __init__(self, wal_dir: str):
        if not wal_dir:
            raise ValueError("RoundStateLog requires a wal_dir")
        self.wal_dir = wal_dir
        os.makedirs(wal_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._seq = self._scan_last_seq()

    # -- writer (primary) --------------------------------------------------

    def append(self, kind: str, data: Any) -> int:
        """Durably append one record; returns its sequence number. The
        record is on disk (atomic rename) before this returns — callers
        on the RPC path ack only after."""
        with self._lock:
            self._seq += 1
            seq = self._seq
        payload = codec_dumps({"seq": seq, "kind": kind, "data": data})
        _durable.atomic_write(os.path.join(self.wal_dir,
                                           _record_name(seq, kind)),
                              payload, prefix=".wal_")
        return seq

    def snapshot(self, state: Dict[str, Any]) -> int:
        """Append a full-state snapshot, then prune every older record —
        the snapshot subsumes them, and an unbounded log would make
        promote-time replay (and disk) grow with run length."""
        seq = self.append(SNAPSHOT, state)
        self._compact(before=seq)
        return seq

    def _compact(self, before: int) -> None:
        for name in self._list_records():
            parsed = _parse_name(name)
            if parsed is not None and parsed[0] < before:
                try:
                    os.unlink(os.path.join(self.wal_dir, name))
                except OSError:  # pragma: no cover - racing reader is fine
                    pass

    # -- reader (standby) --------------------------------------------------

    def poll(self) -> int:
        """Highest sequence number currently on disk (0 = empty) — the
        standby's cheap liveness signal: a healthy primary keeps
        appending, a stale tail triggers the health-probe escalation."""
        return self._scan_last_seq()

    def replay(self) -> Tuple[Optional[Dict[str, Any]], List[Dict[str, Any]]]:
        """``(snapshot_state, deltas_after_it)`` — the latest readable
        snapshot's state (None when none landed yet) plus every
        join/leave delta with a higher sequence number, in order. Torn
        or unreadable records are skipped (store/durable.py posture):
        promotion recovers what landed, it does not abort on what did
        not."""
        records: List[Dict[str, Any]] = []
        for name in self._list_records():
            if _parse_name(name) is None:
                continue
            record = _durable.read_tolerant(
                os.path.join(self.wal_dir, name), codec_loads)
            if isinstance(record, dict) and "seq" in record:
                records.append(record)
        records.sort(key=lambda r: int(r["seq"]))
        state: Optional[Dict[str, Any]] = None
        snap_seq = -1
        for record in records:
            if record.get("kind") == SNAPSHOT:
                state, snap_seq = record.get("data"), int(record["seq"])
        deltas = [r for r in records
                  if r.get("kind") != SNAPSHOT and int(r["seq"]) > snap_seq]
        return state, deltas

    @staticmethod
    def merge(state: Optional[Dict[str, Any]],
              deltas: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
        """Fold registry deltas into a snapshot's ``learners`` list —
        the promote-time state the standby restores from. A join delta
        carries the full learner record (insert-or-replace by id); a
        leave delta removes it. With no snapshot yet, deltas alone
        build a model-less state (registry-only promotion: the round
        restarts once a model is seeded, exactly like a fresh
        ``--resume`` with an empty checkpoint)."""
        if state is None and not deltas:
            return None
        merged = dict(state or {"global_iteration": 0,
                                "community_blob": b"",
                                "round_metadata": [],
                                "community_evaluations": []})
        learners = {entry["learner_id"]: dict(entry)
                    for entry in merged.get("learners", [])}
        for delta in deltas:
            data = delta.get("data") or {}
            if delta.get("kind") == JOIN and data.get("learner_id"):
                learners[data["learner_id"]] = dict(data)
            elif delta.get("kind") == LEAVE:
                learners.pop(data.get("learner_id"), None)
        merged["learners"] = list(learners.values())
        return merged

    # -- internals ---------------------------------------------------------

    def _list_records(self) -> List[str]:
        try:
            return sorted(os.listdir(self.wal_dir))
        except OSError:
            return []

    def _scan_last_seq(self) -> int:
        last = 0
        for name in self._list_records():
            parsed = _parse_name(name)
            if parsed is not None:
                last = max(last, parsed[0])
        return last
