"""Controller gRPC service + RPC-backed learner proxy.

RPC surface of the reference's ``ControllerServicer``
(reference metisfl/controller/core/controller_servicer.cc:110-382,
metisfl/proto/controller.proto:8-49): join/leave federation, mark task
completed, replace/get community model, statistics lineage, health, shutdown.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from metisfl_tpu.comm import codec as _codec
from metisfl_tpu.comm.codec import dumps, loads
from metisfl_tpu.comm.messages import (
    EvalResult,
    EvalTask,
    JoinReply,
    JoinRequest,
    TaskResult,
    TrainTask,
)
from metisfl_tpu.comm.rpc import BytesService, RpcClient, RpcServer
from metisfl_tpu.controller.core import Controller, LearnerRecord
from metisfl_tpu.telemetry import profile as _tprofile

logger = logging.getLogger("metisfl_tpu.controller.service")

CONTROLLER_SERVICE = "metisfl_tpu.Controller"
LEARNER_SERVICE = "metisfl_tpu.Learner"


def _comm_kwargs(comm) -> dict:
    """RpcClient kwargs from a config ``comm`` section (None → library
    defaults) — one translation point so every client construction stays
    deadline-bounded by default."""
    if comm is None:
        return {}
    return {"default_deadline_s": comm.default_deadline_s,
            "retries": comm.retries,
            "retry_sleep_s": comm.retry_sleep_s}


class RpcLearnerProxy:
    """Controller → remote learner over gRPC (async dispatch, mirroring the
    reference's CompletionQueue fan-out, controller.cc:713-759)."""

    def __init__(self, record: LearnerRecord, ssl=None, comm=None):
        # peer=learner_id: the transport attributes this channel's wire
        # bytes (envelopes included) to the learner — the performance
        # observatory's rpc_peer_bytes_total series, pruned on leave.
        # Gated on the ACTIVE collector (set at controller construction,
        # before any proxy exists): with the profile plane off, no
        # per-learner attribution series are ever minted — the opt-out
        # contract — and nothing needs pruning on leave.
        profiled = _tprofile.collector() is not None
        self._client = RpcClient(record.hostname, record.port, LEARNER_SERVICE,
                                 ssl=ssl,
                                 peer=record.learner_id if profiled else "",
                                 **_comm_kwargs(comm))

    @staticmethod
    def _to_wire_attributed(task) -> bytes:
        # attributed(): the envelope encode (which embeds the model blob)
        # lands in the learner's codec_learner_seconds_total series;
        # profile off → plain encode, no attribution series minted
        if _tprofile.collector() is None:
            return task.to_wire()
        with _codec.attributed(task.learner_id):
            return task.to_wire()

    def run_task(self, task: TrainTask) -> None:
        self._client.call_async("RunTask", self._to_wire_attributed(task))

    def run_task_with_callback(self, task: TrainTask, on_error) -> None:
        """Dispatch + failure notification: feeds the controller's learner
        liveness tracking (consecutive failed dispatches)."""
        payload = self._to_wire_attributed(task)
        # RunTask acks immediately (non-blocking learner dispatch):
        # wait_ready=False surfaces UNAVAILABLE from a dead endpoint at once
        # (liveness counts in seconds, not 60 s deadlines), and the timeout
        # bounds a connected-but-unresponsive peer.
        self._client.call_async("RunTask", payload,
                                error_callback=on_error, timeout=60.0,
                                wait_ready=False)

    def evaluate(self, task: EvalTask, callback: Callable[[EvalResult], None]) -> None:
        self._client.call_async(
            "EvaluateModel", self._to_wire_attributed(task),
            callback=lambda raw: callback(EvalResult.from_wire(raw)))

    def recover_masks(self, round_id: int, surviving, dropped,
                      lengths) -> list:
        """Blocking masking-dropout-recovery request (secure/masking.py):
        one survivor computes the dropped parties' residual masks."""
        from metisfl_tpu.comm.codec import dumps, loads

        raw = self._client.call("RecoverMasks", dumps(
            {"round_id": int(round_id), "surviving": list(surviving),
             "dropped": list(dropped), "lengths": list(lengths)}),
            timeout=60.0, wait_ready=False)
        return loads(raw)["corrections"]

    def detach_peer(self) -> None:
        """Stop attributing this channel's bytes to the learner: called
        on leave, BEFORE the per-peer series are pruned, so an in-flight
        call's completion callback cannot re-mint them afterwards."""
        self._client.peer = ""

    def shutdown(self) -> None:
        try:
            self._client.call_async("ShutDown", b"")
        finally:
            pass


class ControllerServer:
    """Host a :class:`Controller` behind gRPC."""

    def __init__(self, controller: Controller, host: str = "0.0.0.0",
                 port: int = 50051, ssl=None):
        from metisfl_tpu.comm.health import SERVING, HealthServicer

        self.controller = controller
        self._server = RpcServer(host, port, ssl=ssl)
        # standard grpc.health.v1 alongside the custom status RPC
        # (reference controller_servicer.cc:7-9,32-33)
        self._health_servicer = HealthServicer()
        self._health_servicer.set_status(CONTROLLER_SERVICE, SERVING)
        self._server.add_service(self._health_servicer.service())
        self._server.add_service(BytesService(CONTROLLER_SERVICE, {
            "JoinFederation": self._join,
            "LeaveFederation": self._leave,
            "MarkTaskCompleted": self._mark_completed,
            "ReplaceCommunityModel": self._replace_model,
            "GetCommunityModel": self._get_model,
            "GetStatistics": self._get_statistics,
            "GetRuntimeMetadata": self._get_runtime_metadata,
            "GetEvaluationLineage": self._get_evaluation_lineage,
            "ListLearners": self._list_learners,
            "GetHealthStatus": self._health,
            "GetMetrics": self._get_metrics,
            "DescribeFederation": self._describe,
            "DescribeRegistry": self._describe_registry,
            "GetRegisteredModel": self._get_registered_model,
            "PromoteVersion": self._promote_version,
            "RollbackVersion": self._rollback_version,
            "ShutDown": self._shutdown_rpc,
        }, role="controller"))
        self._shutdown_event = threading.Event()
        self.port: Optional[int] = None

    # -- handlers (RPC threads) -------------------------------------------
    def _join(self, raw: bytes) -> bytes:
        return self.controller.join(JoinRequest.from_wire(raw)).to_wire()

    def _leave(self, raw: bytes) -> bytes:
        req = loads(raw)
        ok = self.controller.leave(req["learner_id"], req["auth_token"])
        return dumps({"ok": ok})

    def _mark_completed(self, raw: bytes) -> bytes:
        if _tprofile.collector() is None:
            # profile plane off: one attribute check, no timing, no
            # per-learner attribution series
            result = TaskResult.from_wire(raw)
        else:
            # the decode only reveals WHICH learner the payload belongs
            # to after it runs — attribute the elapsed time post hoc,
            # membership-gated under the controller lock: a late
            # completion racing leave() must not re-mint the series the
            # prune just dropped (the bounded-cardinality posture)
            t0 = time.perf_counter()
            result = TaskResult.from_wire(raw)
            self.controller.attribute_decode(result.learner_id,
                                             time.perf_counter() - t0)
        ok = self.controller.task_completed(result)
        return dumps({"ok": ok})

    def _replace_model(self, raw: bytes) -> bytes:
        self.controller.set_community_model(raw)
        return dumps({"ok": True})

    def _get_model(self, raw: bytes) -> bytes:
        return self.controller.community_model_bytes() or b""

    def _get_statistics(self, raw: bytes) -> bytes:
        return dumps(self.controller.get_statistics())

    def _get_runtime_metadata(self, raw: bytes) -> bytes:
        tail = int(loads(raw).get("tail", 0)) if raw else 0
        return dumps({"global_iteration": self.controller.global_iteration,
                      "round_metadata":
                      self.controller.get_runtime_metadata(tail)})

    def _get_evaluation_lineage(self, raw: bytes) -> bytes:
        tail = int(loads(raw).get("tail", 0)) if raw else 0
        return dumps({"community_evaluations":
                      self.controller.get_evaluation_lineage(tail)})

    def _list_learners(self, raw: bytes) -> bytes:
        return dumps({"learners": self.controller.learner_endpoints()})

    def _health(self, raw: bytes) -> bytes:
        return dumps({"status": "SERVING",
                      "learners": self.controller.active_learners()})

    def _get_metrics(self, raw: bytes) -> bytes:
        # Prometheus text exposition of the process registry (served next
        # to grpc.health.v1 like the scrape surface of a normal service;
        # plain-HTTP scrapers use telemetry.httpd instead)
        from metisfl_tpu.telemetry import render_metrics
        return render_metrics().encode("utf-8")

    def _describe(self, raw: bytes) -> bytes:
        # live status snapshot (round/phase, per-learner straggler +
        # divergence analytics, the learning-health round snapshot,
        # in-flight tasks, event-ring tail) — the status plane behind
        # python -m metisfl_tpu.status
        tail = int(loads(raw).get("event_tail", 50)) if raw else 50
        return dumps(self.controller.describe(event_tail=tail))

    def _describe_registry(self, raw: bytes) -> bytes:
        # model-registry snapshot (channel heads + retained lineage) —
        # the serving gateway's poll target and the status CLI's source
        return dumps(self.controller.describe_registry())

    def _get_registered_model(self, raw: bytes) -> bytes:
        req = loads(raw) if raw else {}
        blob = self.controller.registered_model(
            version=int(req.get("version", 0) or 0),
            channel=str(req.get("channel", "") or ""))
        return blob or b""

    def _promote_version(self, raw: bytes) -> bytes:
        req = loads(raw)
        try:
            info = self.controller.promote_version(
                int(req["version"]), force=bool(req.get("force", False)))
        except ValueError as exc:
            # a rejected gate is an answer, not a transport error
            return dumps({"ok": False, "error": str(exc)})
        return dumps({"ok": True, "version": info.to_dict()})

    def _rollback_version(self, raw: bytes) -> bytes:
        try:
            info = self.controller.rollback_version()
        except ValueError as exc:
            # registry disabled: same {ok: false} answer shape as a
            # rejected promotion, not a transport-level error
            return dumps({"ok": False, "error": str(exc)})
        if info is None:
            return dumps({"ok": False,
                          "error": "nothing to roll back to"})
        return dumps({"ok": True, "version": info.to_dict()})

    def _shutdown_rpc(self, raw: bytes) -> bytes:
        # ack first, then tear down off-thread (servicer :364-375 pattern)
        threading.Thread(target=self.stop, daemon=True).start()
        return dumps({"ok": True})

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> int:
        self.port = self._server.start()
        return self.port

    def stop(self) -> None:
        if self._shutdown_event.is_set():
            return
        from metisfl_tpu.comm.health import NOT_SERVING

        self._health_servicer.set_all(NOT_SERVING)
        self._shutdown_event.set()
        self.controller.shutdown()
        self._server.stop()

    def wait_for_shutdown(self, timeout: Optional[float] = None) -> bool:
        return self._shutdown_event.wait(timeout)


class ControllerClient:
    """Learner/driver → controller client (reference
    grpc_controller_client.py:11-297).

    ``standby`` is the hot-standby's ``(host, port)``: when set, a call
    that exhausts the transport's own bounded UNAVAILABLE retries
    re-resolves the controller by grpc.health.v1 probe over BOTH known
    endpoints (primary first, then standby) and re-issues once against
    whichever answers SERVING — the two-endpoint redial contract of
    docs/RESILIENCE.md "Controller hot-standby". Peers never discover
    endpoints at failover time; both are pinned at construction."""

    def __init__(self, host: str, port: int, ssl=None, comm=None,
                 standby: Optional[tuple] = None):
        self._ssl, self._comm = ssl, comm
        self._endpoints = [(host, int(port))]
        if standby and int(standby[1]) > 0:
            self._endpoints.append((standby[0], int(standby[1])))
        self._redial_lock = threading.Lock()
        self._generation = 0
        self._retries = comm.retries if comm is not None else 10
        self._retry_sleep_s = (comm.retry_sleep_s if comm is not None
                               else 1.0)
        self._active = (host, int(port))
        self._client = RpcClient(host, port, CONTROLLER_SERVICE, ssl=ssl,
                                 **_comm_kwargs(comm))

    def endpoint(self) -> tuple:
        """The (host, port) currently dialed."""
        return self._active

    def _call(self, method: str, payload: bytes, **kwargs) -> bytes:
        """One RPC with failover redial: the underlying client already
        retries UNAVAILABLE in place (comm.retries × retry_sleep_s);
        only when that budget is spent — the endpoint is DEAD, not
        blinking — do we probe for the promoted standby and re-issue.
        Without a standby endpoint this is exactly ``RpcClient.call``."""
        import grpc

        if len(self._endpoints) > 1:
            # HA mode: fail FAST on a dead endpoint. wait-for-ready would
            # park the call until the full deadline (120 s default) on a
            # SIGKILLed primary — the bounded in-place UNAVAILABLE
            # retries plus the redial probe below are the failure
            # detector, and they need the UNAVAILABLE immediately. The
            # retry budget covers the standby's promotion window (and
            # its ms-scale stop→start listener gap) with round-seconds
            # to spare. Explicit caller wait_ready always wins.
            kwargs.setdefault("wait_ready", False)
        gen = self._generation
        try:
            return self._client.call(method, payload, **kwargs)
        except (grpc.RpcError, ValueError):
            # ValueError: another thread's redial closed our channel
            # mid-call — fall through and retry on the fresh client
            if not self._redial(gen):
                raise
        return self._client.call(method, payload, **kwargs)

    def _redial(self, gen: int) -> bool:
        """Re-resolve the controller endpoint after a dead-channel call.
        Probes every known endpoint (bounded: ``comm.retries`` rounds at
        ``retry_sleep_s`` cadence — the promotion window the standby
        needs is well inside it) and swaps the transport to whichever
        answers SERVING. Serialized: concurrent failed callers re-dial
        once, the rest piggyback on the fresh channel."""
        if len(self._endpoints) < 2:
            return False
        from metisfl_tpu.comm.health import probe_health

        with self._redial_lock:
            if self._generation != gen:
                return True  # another caller already re-dialed
            for _ in range(max(1, self._retries)):
                for host, port in self._endpoints:
                    if probe_health(host, port, CONTROLLER_SERVICE,
                                    ssl=self._ssl,
                                    comm=self._comm) != "SERVING":
                        continue
                    old = self._client
                    self._client = RpcClient(host, port, CONTROLLER_SERVICE,
                                             ssl=self._ssl,
                                             **_comm_kwargs(self._comm))
                    self._active = (host, port)
                    self._generation += 1
                    try:
                        old.close()
                    except Exception:  # noqa: BLE001 - already dead
                        pass
                    logger.warning("controller re-dialed to %s:%d "
                                   "(failover)", host, port)
                    return True
                time.sleep(self._retry_sleep_s)
            return False

    def join(self, request: JoinRequest) -> JoinReply:
        # idempotent: a re-sent join lands on the rejoin path
        return JoinReply.from_wire(self._call(
            "JoinFederation", request.to_wire(), idempotent=True))

    def leave(self, learner_id: str, auth_token: str) -> bool:
        raw = self._call("LeaveFederation", dumps(
            {"learner_id": learner_id, "auth_token": auth_token}))
        return bool(loads(raw)["ok"])

    def task_completed(self, result: TaskResult) -> bool:
        raw = self._call("MarkTaskCompleted", result.to_wire())
        return bool(loads(raw)["ok"])

    def replace_community_model(self, blob: bytes) -> bool:
        return bool(loads(self._call("ReplaceCommunityModel", blob))["ok"])

    def get_community_model(self) -> bytes:
        return self._call("GetCommunityModel", b"", idempotent=True)

    def get_statistics(self) -> dict:
        return loads(self._call("GetStatistics", b"",
                                       idempotent=True))

    def get_runtime_metadata(self, tail: int = 0,
                             timeout: Optional[float] = None,
                             wait_ready: bool = True) -> dict:
        """{'global_iteration', 'round_metadata': last ``tail`` rounds}
        (0 = full lineage). ``wait_ready=False`` + a short timeout makes
        a poll against a dead controller fail fast instead of parking in
        the channel's wait-for-ready — the driver's supervision loop
        needs the failure signal to trigger the failover restart."""
        raw = self._call("GetRuntimeMetadata", dumps({"tail": tail}),
                                timeout=timeout, wait_ready=wait_ready,
                                idempotent=True)
        return loads(raw)

    def get_evaluation_lineage(self, tail: int = 0) -> list:
        """Last ``tail`` evaluation entries (0 = full lineage)."""
        raw = self._call("GetEvaluationLineage", dumps({"tail": tail}),
                                idempotent=True)
        return loads(raw)["community_evaluations"]

    def list_learners(self, timeout: Optional[float] = None,
                      wait_ready: bool = True) -> list:
        """Registered learner endpoints [{learner_id, hostname, port}] — the
        ports learners actually bound (JoinRequest.port), for shutdown and
        monitoring (replaces any port-arithmetic assumptions driver-side)."""
        return loads(self._call("ListLearners", b"", timeout=timeout,
                                       wait_ready=wait_ready,
                                       idempotent=True))["learners"]

    def health(self, timeout: float = 5.0) -> dict:
        return loads(self._call("GetHealthStatus", b"",
                                       timeout=timeout, idempotent=True))

    def get_metrics(self, timeout: float = 5.0) -> str:
        """The controller's Prometheus text exposition (GetMetrics RPC)."""
        return self._call("GetMetrics", b"", timeout=timeout,
                                 idempotent=True).decode("utf-8")

    def describe_federation(self, event_tail: int = 50,
                            timeout: Optional[float] = None,
                            wait_ready: bool = True) -> dict:
        """Live status snapshot (Controller.describe): round/phase,
        per-learner liveness + straggler scores, in-flight tasks, store
        occupancy, event-ring tail. Fail-fast polling works like
        get_runtime_metadata: short ``timeout`` + ``wait_ready=False``."""
        raw = self._call("DescribeFederation",
                                dumps({"event_tail": int(event_tail)}),
                                timeout=timeout, wait_ready=wait_ready,
                                idempotent=True)
        return loads(raw)

    def describe_registry(self, timeout: Optional[float] = None,
                          wait_ready: bool = True) -> dict:
        """Model-registry snapshot (channel heads + retained version
        lineage); ``{"enabled": False}`` when the registry is off. The
        serving gateway polls this fail-fast (short timeout, no
        wait-for-ready) like the driver's supervision polls."""
        raw = self._call("DescribeRegistry", b"", timeout=timeout,
                                wait_ready=wait_ready, idempotent=True)
        return loads(raw)

    def get_registered_model(self, version: int = 0, channel: str = "",
                             timeout: Optional[float] = None) -> bytes:
        """A registered version's community blob, by version id or channel
        name (b'' when absent)."""
        return self._call(
            "GetRegisteredModel",
            dumps({"version": int(version), "channel": channel}),
            timeout=timeout, idempotent=True)

    def promote_version(self, version: int, force: bool = False,
                        timeout: Optional[float] = None) -> dict:
        """Operator promotion: ``{"ok": bool, ...}`` — a failing gate
        comes back as ``ok=False`` with the reasons, not an exception."""
        return loads(self._call(
            "PromoteVersion", dumps({"version": int(version),
                                     "force": bool(force)}),
            timeout=timeout))

    def rollback_version(self, timeout: Optional[float] = None) -> dict:
        return loads(self._call("RollbackVersion", dumps({}),
                                       timeout=timeout))

    def list_methods(self, timeout: float = 5.0) -> dict:
        """The service's RPC surface (ListMethods reflection): method
        names + transport capability flags, JSON-encoded so non-codec
        tooling can probe it too."""
        import json as _json
        raw = self._call("ListMethods", b"", timeout=timeout,
                                idempotent=True)
        return _json.loads(raw.decode("utf-8"))

    def shutdown_controller(self) -> bool:
        return bool(loads(self._call("ShutDown", b""))["ok"])

    def close(self) -> None:
        self._client.close()
