"""Controller process entry point: ``python -m metisfl_tpu.controller``.

Reference: metisfl/controller/__main__.py:12-94 — but configuration arrives
as one file (codec-serialized ``FederationConfig`` or YAML), not hex-proto
CLI flags (SURVEY.md §5.6 flags that design as user-hostile).

``--standby`` runs the warm hot-standby instead (docs/RESILIENCE.md
"Controller hot-standby"): tail the primary's write-ahead round-state
log (controller/wal.py), answer grpc.health.v1 with NOT_SERVING for the
controller service (alive, not promoted — probes can tell a warm standby
from a corpse), and promote when the WAL tail goes stale AND
``probe_failures`` consecutive health probes of the primary come back
non-SERVING — the exact staleness→probe escalation the slice re-homing
and serving-fleet paths standardized. Promotion restores the replicated
state, starts the full controller on the standby's own pinned port
(every peer holds both endpoints up front), and re-dispatches the
abandoned round.
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading
import time

from metisfl_tpu.config import FederationConfig, load_config
from metisfl_tpu.controller.core import Controller
from metisfl_tpu.controller.service import ControllerServer, RpcLearnerProxy


def _build_controller(config, parser) -> Controller:
    """Construct the Controller exactly as the primary path does — the
    promoted standby must run the same aggregation/secure stack or the
    resumed round could not be bit-identical."""
    secure_backend = None
    if config.secure.enabled:
        from metisfl_tpu.secure import make_backend
        kwargs = {}
        if config.secure.scheme == "masking":
            num_parties = config.secure.num_parties or len(config.learners)
            if num_parties <= 0:
                parser.error(
                    "masking secure aggregation needs secure.num_parties "
                    "(the driver fills it in) or a configured learner list")
            kwargs["num_parties"] = num_parties
        secure_backend = make_backend(config.secure, role="controller",
                                      **kwargs)
    return Controller(
        config,
        lambda record: RpcLearnerProxy(record, ssl=config.ssl,
                                       comm=config.comm),
        secure_backend=secure_backend)


def _standby_main(args, config, parser, metrics_http) -> int:
    from metisfl_tpu import telemetry
    from metisfl_tpu.comm.health import (NOT_SERVING, HealthServicer,
                                         probe_health)
    from metisfl_tpu.comm.rpc import BytesService, RpcServer
    from metisfl_tpu.controller.service import CONTROLLER_SERVICE
    from metisfl_tpu.controller.wal import RoundStateLog
    from metisfl_tpu.telemetry import events as tevents
    from metisfl_tpu.telemetry import metrics as tmetrics

    standby = config.controller.standby
    if not (standby.enabled and standby.wal_dir):
        parser.error("--standby requires controller.standby.enabled and "
                     "controller.standby.wal_dir (the driver pins both)")
    log = logging.getLogger("metisfl_tpu.controller.standby")
    wal = RoundStateLog(standby.wal_dir)

    # Warm phase: health-only server on the standby's pinned port. The
    # overall server ("") answers SERVING — the driver's boot wait and
    # the fleet collector's liveness column see a live process — while
    # the controller service answers NOT_SERVING until promotion, so
    # nobody re-dials here early.
    health = HealthServicer()
    health.set_status(CONTROLLER_SERVICE, NOT_SERVING)
    idle = RpcServer(args.host, args.port or standby.port, ssl=config.ssl)
    idle.add_service(health.service())
    # role-tagged methodless service: the fleet collector's
    # CollectTelemetry pulls (and the status CLI's --probe) see the warm
    # standby as a live role="standby" peer — without mounting a single
    # controller method, so a misdirected RPC stays loudly UNIMPLEMENTED
    idle.add_service(BytesService(CONTROLLER_SERVICE, {}, role="standby"))
    port = idle.start()
    print(f"METISFL_TPU_CONTROLLER_STANDBY_READY port={port}", flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())

    # Tail loop: WAL progress is the cheap liveness signal (the primary
    # snapshots every membership change and round close); only a stale
    # tail escalates to health probes, so a healthy primary costs one
    # listdir per tick and zero RPCs.
    last_seq = wal.poll()
    last_progress = time.monotonic()
    failures = 0
    promoted = False
    # standby replication lag: primary WAL head vs the tail position
    # this loop has caught up to — records that landed inside one probe
    # interval. Rides CollectTelemetry off the warm standby's server, so
    # the status --fleet ha: line can show the standby keeping up (or
    # not) while the primary is still alive.
    lag_gauge = tmetrics.registry().gauge(
        telemetry.M_CONTROLLER_WAL_LAG_RECORDS,
        "Standby tail position behind the primary's WAL head (records "
        "observed landing per probe tick; 0 = caught up)")
    lag_gauge.set(0.0)
    while not stop.is_set():
        stop.wait(standby.probe_interval_s)
        if stop.is_set():
            break
        seq = wal.poll()
        lag_gauge.set(float(max(0, seq - last_seq)))
        if seq != last_seq:
            last_seq, last_progress, failures = seq, time.monotonic(), 0
            continue
        if time.monotonic() - last_progress < standby.stale_after_s:
            continue
        verdict = probe_health(config.controller_host,
                               config.controller_port, CONTROLLER_SERVICE,
                               ssl=config.ssl, comm=config.comm)
        if verdict == "SERVING":
            # healthy but quiet (long round, idle federation): reset the
            # staleness clock, keep tailing
            failures, last_progress = 0, time.monotonic()
            continue
        failures += 1
        log.warning("primary %s:%d %s after %.1fs WAL stall (%d/%d "
                    "consecutive probe failures)", config.controller_host,
                    config.controller_port, verdict,
                    time.monotonic() - last_progress, failures,
                    standby.probe_failures)
        if failures >= standby.probe_failures:
            promoted = True
            break

    if not promoted:  # clean shutdown while warm
        idle.stop()
        if metrics_http is not None:
            metrics_http.close()
        telemetry.trace.flush()
        telemetry.events.flush()
        return 0

    # Promote: stop the health-only server, restore the WAL state into a
    # full controller, and serve on the SAME pinned port — peers redial
    # a known endpoint, not a discovered one. The brief UNREACHABLE
    # window between stop() and start() is covered by every client's
    # bounded UNAVAILABLE retry.
    t0 = time.monotonic()
    idle.stop()
    log.warning("promoting: restoring WAL round state from %s",
                standby.wal_dir)
    controller = _build_controller(config, parser)
    restored = controller.restore_from_wal()
    server = ControllerServer(controller, host=args.host, port=port,
                              ssl=config.ssl)
    port = server.start()
    promote_s = time.monotonic() - t0
    n_learners = len(controller.active_learners())
    reg = tmetrics.registry()
    reg.counter(telemetry.M_CONTROLLER_FAILOVER_TOTAL,
                "Standby promotions to controller, by role of the "
                "emitting process", ("role",)).inc(role="standby")
    reg.histogram(telemetry.M_CONTROLLER_FAILOVER_PROMOTE_SECONDS,
                  "Wall-clock from promotion decision to the promoted "
                  "controller serving").observe(promote_s)
    tevents.emit(tevents.ControllerFailover, role="standby",
                 host=standby.host, port=port,
                 round=controller.global_iteration, learners=n_learners,
                 wal_records=last_seq, promote_s=round(promote_s, 4),
                 reason="wal_stale_probe_failed")
    print(f"METISFL_TPU_CONTROLLER_PROMOTED port={port}", flush=True)
    log.warning("promoted in %.2fs at round %d (%d learner(s) restored)",
                promote_s, controller.global_iteration, n_learners)
    if restored:
        # re-dispatch the round the dead primary abandoned (same posture
        # as --resume); the fresh controller_epoch makes surviving
        # learners re-attach and completions fold in deterministically
        controller.resume_round()

    signal.signal(signal.SIGTERM, lambda *_: server.stop())
    signal.signal(signal.SIGINT, lambda *_: server.stop())
    server.wait_for_shutdown()
    if metrics_http is not None:
        metrics_http.close()
    telemetry.trace.flush()
    telemetry.events.flush()
    return 0


def main(argv=None) -> int:
    from metisfl_tpu.platform import honor_platform_env
    honor_platform_env()
    parser = argparse.ArgumentParser("metisfl_tpu.controller")
    parser.add_argument("--config", required=True,
                        help="path to FederationConfig (.bin codec or .yaml)")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=0,
                        help="override config controller_port (primary) or "
                             "controller.standby.port (--standby)")
    parser.add_argument("--resume", action="store_true",
                        help="restore community model + round counter from "
                             "config.checkpoint.dir before serving")
    parser.add_argument("--standby", action="store_true",
                        help="run as the warm hot-standby: tail the WAL, "
                             "promote on primary death")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    if args.config.endswith((".yaml", ".yml")):
        config = load_config(args.config)
    else:
        with open(args.config, "rb") as f:
            config = FederationConfig.from_wire(f.read())

    from metisfl_tpu import telemetry
    import hashlib
    config_hash = hashlib.sha256(config.to_wire()).hexdigest()[:16]
    telemetry.apply_config(config.telemetry,
                           service="standby" if args.standby
                           else "controller",
                           config_hash=config_hash)
    metrics_http = None
    if config.telemetry.enabled and config.telemetry.http_port > 0:
        from metisfl_tpu.telemetry.httpd import start_metrics_http
        metrics_http = start_metrics_http(config.telemetry.http_port,
                                          host=args.host)

    if args.standby:
        return _standby_main(args, config, parser, metrics_http)

    controller = _build_controller(config, parser)
    restored = False
    if args.resume:
        if not config.checkpoint.dir:
            parser.error("--resume requires config.checkpoint.dir")
        restored = controller.restore_checkpoint()
        if not restored:
            logging.getLogger("metisfl_tpu.controller").warning(
                "--resume: no checkpoint found under %r — starting FRESH "
                "at round 0", config.checkpoint.dir)
    server = ControllerServer(controller, host=args.host,
                              port=args.port or config.controller_port,
                              ssl=config.ssl)
    port = server.start()
    print(f"METISFL_TPU_CONTROLLER_READY port={port}", flush=True)
    if restored:
        # crash-failover: re-dispatch the abandoned round to the restored
        # registry (learners that stayed alive resume immediately; dead
        # endpoints heal via re-attach). AFTER start(): dispatches dial
        # out and completions dial back in through the live server.
        controller.resume_round()

    signal.signal(signal.SIGTERM, lambda *_: server.stop())
    signal.signal(signal.SIGINT, lambda *_: server.stop())
    server.wait_for_shutdown()
    if metrics_http is not None:
        metrics_http.close()
    telemetry.trace.flush()
    telemetry.events.flush()
    return 0


if __name__ == "__main__":
    sys.exit(main())
