"""Controller process entry point: ``python -m metisfl_tpu.controller``.

Reference: metisfl/controller/__main__.py:12-94 — but configuration arrives
as one file (codec-serialized ``FederationConfig`` or YAML), not hex-proto
CLI flags (SURVEY.md §5.6 flags that design as user-hostile).
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys

from metisfl_tpu.config import FederationConfig, load_config
from metisfl_tpu.controller.core import Controller
from metisfl_tpu.controller.service import ControllerServer, RpcLearnerProxy


def main(argv=None) -> int:
    from metisfl_tpu.platform import honor_platform_env
    honor_platform_env()
    parser = argparse.ArgumentParser("metisfl_tpu.controller")
    parser.add_argument("--config", required=True,
                        help="path to FederationConfig (.bin codec or .yaml)")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=0,
                        help="override config controller_port")
    parser.add_argument("--resume", action="store_true",
                        help="restore community model + round counter from "
                             "config.checkpoint.dir before serving")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    if args.config.endswith((".yaml", ".yml")):
        config = load_config(args.config)
    else:
        with open(args.config, "rb") as f:
            config = FederationConfig.from_wire(f.read())

    from metisfl_tpu import telemetry
    import hashlib
    config_hash = hashlib.sha256(config.to_wire()).hexdigest()[:16]
    telemetry.apply_config(config.telemetry, service="controller",
                           config_hash=config_hash)
    metrics_http = None
    if config.telemetry.enabled and config.telemetry.http_port > 0:
        from metisfl_tpu.telemetry.httpd import start_metrics_http
        metrics_http = start_metrics_http(config.telemetry.http_port,
                                          host=args.host)

    secure_backend = None
    if config.secure.enabled:
        from metisfl_tpu.secure import make_backend
        kwargs = {}
        if config.secure.scheme == "masking":
            num_parties = config.secure.num_parties or len(config.learners)
            if num_parties <= 0:
                parser.error(
                    "masking secure aggregation needs secure.num_parties "
                    "(the driver fills it in) or a configured learner list")
            kwargs["num_parties"] = num_parties
        secure_backend = make_backend(config.secure, role="controller",
                                      **kwargs)

    controller = Controller(
        config,
        lambda record: RpcLearnerProxy(record, ssl=config.ssl,
                                       comm=config.comm),
        secure_backend=secure_backend)
    restored = False
    if args.resume:
        if not config.checkpoint.dir:
            parser.error("--resume requires config.checkpoint.dir")
        restored = controller.restore_checkpoint()
        if not restored:
            logging.getLogger("metisfl_tpu.controller").warning(
                "--resume: no checkpoint found under %r — starting FRESH "
                "at round 0", config.checkpoint.dir)
    server = ControllerServer(controller, host=args.host,
                              port=args.port or config.controller_port,
                              ssl=config.ssl)
    port = server.start()
    print(f"METISFL_TPU_CONTROLLER_READY port={port}", flush=True)
    if restored:
        # crash-failover: re-dispatch the abandoned round to the restored
        # registry (learners that stayed alive resume immediately; dead
        # endpoints heal via re-attach). AFTER start(): dispatches dial
        # out and completions dial back in through the live server.
        controller.resume_round()

    signal.signal(signal.SIGTERM, lambda *_: server.stop())
    signal.signal(signal.SIGINT, lambda *_: server.stop())
    server.wait_for_shutdown()
    if metrics_http is not None:
        metrics_http.close()
    telemetry.trace.flush()
    telemetry.events.flush()
    return 0


if __name__ == "__main__":
    sys.exit(main())
