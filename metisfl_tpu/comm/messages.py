"""Federation message schemas.

Typed dataclass messages serialized through :mod:`metisfl_tpu.comm.codec`.
Capability map to the reference's protos:

- ``JoinRequest``/``JoinReply``  ≈ JoinFederationRequest/Response
  (reference metisfl/proto/controller.proto:120-150, metis.proto ServerEntity).
- ``TrainParams``/``TrainTask``  ≈ LearningTask + Hyperparameters + RunTaskRequest
  (metis.proto:95-147, learner.proto:9-24).
- ``TaskResult``                 ≈ CompletedLearningTask + TaskExecutionMetadata
  (metis.proto:104-147).
- ``EvalTask``/``EvalResult``    ≈ EvaluateModelRequest/Response + ModelEvaluations
  (metis.proto:149-196).

Unlike the reference, ML metric values are typed floats, not strings
(SURVEY.md §5.5 flags the reference's stringly-typed metrics as a defect).
"""

from __future__ import annotations

import dataclasses
import functools
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, get_type_hints

from metisfl_tpu.comm.codec import dumps, loads


@functools.lru_cache(maxsize=None)
def _hints_for(cls):
    return get_type_hints(cls)


class Message:
    """Base: dataclass ⇄ codec bytes, with nested-message support."""

    def to_dict(self) -> dict:
        out = {}
        for f in dataclasses.fields(self):
            value = getattr(self, f.name)
            if isinstance(value, Message):
                value = value.to_dict()
            elif isinstance(value, list) and value and isinstance(value[0], Message):
                value = [v.to_dict() for v in value]
            out[f.name] = value
        return out

    @classmethod
    def from_dict(cls, data: dict):
        hints = _hints_for(cls)
        kwargs = {}
        for f in dataclasses.fields(cls):
            if f.name not in data:
                continue
            value = data[f.name]
            hint = hints.get(f.name)
            nested = _nested_message_type(hint)
            if nested is not None and isinstance(value, dict):
                value = nested.from_dict(value)
            elif isinstance(value, list):
                item_type = _list_item_message_type(hint)
                if item_type is not None:
                    value = [item_type.from_dict(v) for v in value]
            kwargs[f.name] = value
        return cls(**kwargs)

    def to_wire(self) -> bytes:
        return dumps(self.to_dict())

    @classmethod
    def from_wire(cls, buf):
        return cls.from_dict(loads(buf))


def _nested_message_type(hint):
    if isinstance(hint, type) and issubclass(hint, Message):
        return hint
    for arg in getattr(hint, "__args__", ()):  # Optional[Msg]
        if isinstance(arg, type) and issubclass(arg, Message):
            return arg
    return None


def _list_item_message_type(hint):
    args = getattr(hint, "__args__", ())
    if args and isinstance(args[0], type) and issubclass(args[0], Message):
        return args[0]
    return None


@dataclass
class TrainParams(Message):
    """Local-training hyperparameters shipped with every task."""

    batch_size: int = 32
    local_steps: int = 0        # exact optimizer steps; 0 → derive from epochs
    local_epochs: float = 1.0   # used when local_steps == 0
    optimizer: str = "sgd"
    learning_rate: float = 0.01
    optimizer_kwargs: Dict[str, Any] = field(default_factory=dict)
    # FedProx proximal term weight; 0 disables (reference fed_prox.py:10-103).
    proximal_mu: float = 0.0
    # weight on sown auxiliary losses (MoE router load balancing); 0 disables
    moe_aux_weight: float = 0.01
    # jax.profiler trace capture (SURVEY.md §5.1): when set, each training
    # task traces ``profile_steps`` steady-state (post-compile) steps into
    # this directory — TensorBoard/xprof-readable. With scan_chunk > 1 the
    # trace covers exactly ONE steady-state fused chunk (scan_chunk steps),
    # since steps inside a compiled scan cannot be traced individually; a
    # run whose only chunk is the compiling one captures no trace rather
    # than a compile-dominated one.
    profile_dir: str = ""
    profile_steps: int = 3
    # Performance-observatory gating (telemetry/profile.py): when true the
    # learner captures device utilization per train task (step-time EWMA,
    # achieved-MFU estimate, HBM watermark) and ships it back in
    # ``TaskResult.device_stats``. The controller stamps this false when
    # ``telemetry.profile.enabled=false``, reducing the learner hot path
    # to this one attribute check.
    device_stats: bool = True
    # Fuse this many optimizer steps into ONE jit-compiled lax.scan program.
    # Cuts host→device dispatch to 1/scan_chunk of the per-step path — the
    # difference is pure overhead on TPU (and dominant when the chip sits
    # behind a network tunnel). Cancellation is checked between chunks.
    scan_chunk: int = 1
    # Wire dtype for shipped model weights (a DType name: "bf16", "f16",
    # "f32", ..., or "int8q" for int8 absmax quantization with per-tensor
    # scales — tensor/quantize.py). "" ships the training dtype unchanged.
    # bf16 halves federation bandwidth; int8q quarters it (the controller
    # dequantizes before aggregating). Aggregation still accumulates in
    # f32 and each learner restores its own training dtypes on receipt, so
    # only the wire representation is narrowed. Ignored under secure
    # aggregation (HE/masking payloads have their own fixed-point
    # encoding; int8q+secure is rejected at config time).
    ship_dtype: str = ""
    # Wire dtype for the DOWNLINK (controller → learner community-model
    # broadcast): a float DType name, typically "bf16" to halve broadcast
    # bandwidth across the cohort. "" ships the stored dtype unchanged.
    # Like ship_dtype, only the wire narrows — the controller's own
    # community state stays f32 and each learner restores its training
    # dtypes on receipt. Learners also evaluate the narrowed weights (the
    # model they actually received). Rejected with secure aggregation
    # (opaque payloads) and with ship_dtype='topk...' (sparse updates
    # reconstruct against the controller's exact f32 model).
    downlink_dtype: str = ""
    # FedBN-style personalization (Li et al., ICLR 2021): tensors whose
    # flattened name matches this regex stay LOCAL to each learner — they
    # never ship to the controller, drop out of the community model after
    # round 1, and each learner retains (and evaluates with) its own
    # values. The canonical use is BatchNorm under feature-shift non-IID:
    # local_tensor_regex="batch_stats|/bn" keeps running stats AND the
    # learnable scale/bias per-learner. Incompatible with secure
    # aggregation and with stateful server rules (fedavgm/fedadam/
    # fedyogi/fednova/scaffold track a full model tree) — config-checked.
    local_tensor_regex: str = ""
    # Ship-only-trainable transport (the selective complement of
    # local_tensor_regex — that one RETAINS, this one SELECTS): tensors
    # whose flattened name matches this regex are the ONLY federated
    # state. Learners ship just the matching subset, the controller holds
    # and aggregates ONLY that subset (the frozen base never occupies
    # controller memory or the wire), and the downlink broadcasts the
    # aggregated subset; each learner backfills non-matching tensors from
    # its own construction-time values. Contract: every learner holds the
    # IDENTICAL base (the usual LoRA/linear-probe setting —
    # ship_tensor_regex="lora_" with FlaxModelOps(trainable_regex="lora_")
    # turns an 8B-param federation into an adapter-sized one, MBs instead
    # of GBs both directions). Non-matching tensors are effectively
    # frozen by the transport regardless of the optimizer mask.
    # Composes with secure aggregation (the subset is identical across
    # parties, so the uniform-shape masking/HE payload contract holds —
    # and encrypting adapters instead of the full model is what makes
    # secure LoRA federations practical); incompatible with
    # local_tensor_regex, scaffold, and client-level DP — config-checked.
    # The reference hit the full-model-blob wall and worked around it
    # with a stub-per-request hack (reference
    # metisfl/controller/core/controller.cc:594-604); shipping only the
    # trainable subset removes the wall instead.
    ship_tensor_regex: str = ""
    # Client-level differential privacy on the shipped update
    # (secure/dp.py): the delta vs the received community model is
    # L2-clipped to dp_clip_norm (> 0 enables; also a robustness tool on
    # its own) and Gaussian noise with per-coordinate std
    # dp_noise_multiplier * dp_clip_norm is added. Composes with secure
    # aggregation (privatize, then encrypt/mask). Account the guarantee
    # with secure.dp.rdp_epsilon(noise_multiplier, rounds, delta).
    dp_clip_norm: float = 0.0
    dp_noise_multiplier: float = 0.0


@dataclass
class JoinRequest(Message):
    hostname: str = "localhost"
    port: int = 0
    num_train_examples: int = 0
    num_val_examples: int = 0
    num_test_examples: int = 0
    # Rejoin support: a learner that restarts presents its previous identity
    # (reference grpc_controller_client.py:96-107 rejoin-on-ALREADY_EXISTS).
    previous_id: str = ""
    auth_token: str = ""
    capabilities: Dict[str, Any] = field(default_factory=dict)


@dataclass
class JoinReply(Message):
    learner_id: str = ""
    auth_token: str = ""
    rejoined: bool = False
    # Controller incarnation id: a fresh uuid per controller process. A
    # learner that observes a DIFFERENT epoch in a later task envelope
    # knows the controller crashed and restarted, and re-attaches
    # (re-runs join_federation) instead of trusting stale registration.
    controller_epoch: str = ""


@dataclass
class TrainTask(Message):
    task_id: str = ""
    learner_id: str = ""
    round_id: int = 0
    global_iteration: int = 0
    model: bytes = b""          # ModelBlob wire bytes (community model)
    params: TrainParams = field(default_factory=TrainParams)
    # SCAFFOLD (aggregation.rule='scaffold'): ``scaffold`` marks the task
    # as control-variate-corrected (the learner must report a delta even
    # while the server variate is still zero), ``control`` carries the
    # server variate c as a ModelBlob (empty = zeros).
    scaffold: bool = False
    control: bytes = b""
    # controller incarnation id (see JoinReply.controller_epoch): a
    # mismatch against the epoch the learner joined under triggers
    # learner-side re-attach before the task runs
    controller_epoch: str = ""


@dataclass
class TaskResult(Message):
    task_id: str = ""
    learner_id: str = ""
    # Composite-key auth: the controller validates (learner_id, auth_token)
    # before accepting a model (reference controller.proto:146-148).
    auth_token: str = ""
    # Incarnation the answered task was dispatched under (the TrainTask's
    # controller_epoch, echoed back). A controller that restored another
    # incarnation's state (hot-standby promotion, --resume relaunch)
    # re-dispatches the abandoned round itself — an uplink the DEAD
    # incarnation dispatched must land as a stale store, never advance
    # the restored round's barrier, or it double-folds against the
    # re-trained copy. Empty (legacy/test producers) means no check.
    controller_epoch: str = ""
    round_id: int = 0
    model: bytes = b""          # locally trained ModelBlob
    num_train_examples: int = 0
    completed_steps: int = 0
    completed_epochs: float = 0.0
    completed_batches: int = 0
    processing_ms_per_step: float = 0.0
    # Final train-task metrics and the per-epoch trajectory. Consumed
    # controller-side: recorded into RoundMetadata (experiment.json,
    # stats.py per-learner convergence tables) and — train_metrics'
    # "loss" specifically — folded into the learning-health plane's
    # cohort loss quantiles (telemetry/health.py).
    train_metrics: Dict[str, float] = field(default_factory=dict)
    epoch_metrics: List[Dict[str, float]] = field(default_factory=list)
    # SCAFFOLD client control-variate delta (c_i_new - c_i, ModelBlob);
    # the controller folds the cohort's deltas into the server variate.
    control_delta: bytes = b""
    # Device-utilization capture (telemetry/profile.py DeviceMonitor):
    # step_ms_ewma, achieved mfu, hbm_peak_bytes, device_kind — folded
    # into the controller's RoundProfile so the cost profile is
    # federation-wide. Empty when TrainParams.device_stats is false
    # (profile plane opted out) or the task completed zero steps.
    device_stats: Dict[str, Any] = field(default_factory=dict)


@dataclass
class EvalTask(Message):
    task_id: str = ""
    learner_id: str = ""
    round_id: int = 0
    model: bytes = b""
    batch_size: int = 256
    datasets: List[str] = field(default_factory=lambda: ["test"])
    metrics: List[str] = field(default_factory=lambda: ["loss", "accuracy"])
    # FedBN (TrainParams.local_tensor_regex): round-2+ community blobs
    # omit the local tensors, and a learner that has never trained (not
    # yet sampled, or crash-rejoined) must still be able to reconstruct
    # the model — the regex rides every eval/infer task too
    local_tensor_regex: str = ""
    # Ship-only-trainable (TrainParams.ship_tensor_regex): community blobs
    # carry ONLY the federated subset; a never-trained learner must know
    # to backfill the frozen base from its own initial values
    ship_tensor_regex: str = ""
    # controller incarnation id (see JoinReply.controller_epoch)
    controller_epoch: str = ""


@dataclass
class EvalResult(Message):
    task_id: str = ""
    learner_id: str = ""
    round_id: int = 0
    # dataset name -> {metric -> value}
    evaluations: Dict[str, Dict[str, float]] = field(default_factory=dict)
    duration_ms: float = 0.0


@dataclass
class InferTask(Message):
    """Inference request — the reference learner's third task type
    (reference metisfl/learner/learner.py:311-330 run_inference_task)."""

    task_id: str = ""
    learner_id: str = ""
    round_id: int = 0
    model: bytes = b""          # ModelBlob to infer with (may be encrypted)
    batch_size: int = 256
    # either a named local dataset split ("train"/"valid"/"test")...
    dataset: str = "test"
    # ...or explicit inputs shipped as a packed {"x": array} ModelBlob
    inputs: bytes = b""
    max_examples: int = 0       # 0 = all
    # > 0 turns the task into autoregressive generation on a causal-LM
    # engine (models/generate.py): inputs are token prompts, the result
    # packs the generated continuations instead of logits
    generate_tokens: int = 0
    # FedBN merge for partial community blobs (see EvalTask)
    local_tensor_regex: str = ""
    # ship-only-trainable backfill for subset community blobs (see EvalTask)
    ship_tensor_regex: str = ""
    temperature: float = 0.0    # 0 = greedy
    top_k: int = 0
    top_p: float = 0.0          # nucleus sampling mass; 0/1 = off
    eos_id: int = -1            # < 0 = no early stop


@dataclass
class ServeRequest(Message):
    """Serving-gateway inference request (serving/gateway.py). Unlike
    :class:`InferTask`, no model rides along — the gateway serves the
    registry's promoted community model, hot-swapped server-side."""

    request_id: str = ""
    # deterministic canary routing key (a session/user id); "" falls back
    # to request_id so every request still routes deterministically
    key: str = ""
    inputs: bytes = b""         # packed {"x": array} ModelBlob


@dataclass
class ServeReply(Message):
    request_id: str = ""
    predictions: bytes = b""    # packed {"predictions": array} ModelBlob
    # which registry version / channel actually served this request —
    # canary observability is per-response, not config inference
    model_version: int = 0
    channel: str = ""
    duration_ms: float = 0.0


@dataclass
class GenerateRequest(Message):
    """Serving-gateway generation request (serving/decode.py): an
    autoregressive continuation of ``prompt`` through the gateway's
    continuous-batching decode loop. Greedy by contract — a shared
    in-flight batch cannot reproduce any single request's sampling
    stream, and serving replies must be replica-independent."""

    request_id: str = ""
    # deterministic canary/consistent-hash routing key (see ServeRequest)
    key: str = ""
    prompt: bytes = b""         # packed {"tokens": (L,) int32} ModelBlob
    max_new_tokens: int = 16
    eos_id: int = -1            # < 0 = no early stop


@dataclass
class GenerateReply(Message):
    request_id: str = ""
    # packed {"tokens": (max_new_tokens,) int32} ModelBlob; pad (0) after
    # an emitted eos — models/generate.py's exact contract
    tokens: bytes = b""
    model_version: int = 0
    channel: str = ""
    duration_ms: float = 0.0


@dataclass
class InferResult(Message):
    task_id: str = ""
    learner_id: str = ""
    round_id: int = 0
    predictions: bytes = b""    # packed {"predictions": array} ModelBlob
    num_examples: int = 0
    duration_ms: float = 0.0

