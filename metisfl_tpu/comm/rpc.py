"""gRPC bytes transport.

The reference builds protobuf-codegen services with unlimited message sizes
(reference metisfl/utils/grpc_services.py:22-110). Here services are generic
byte methods (no codegen): each endpoint is a named unary handler taking and
returning codec/blob bytes. Retry-with-backoff on UNAVAILABLE mirrors
grpc_services.py:60-75; unlimited message lengths mirror :28-30 and :93-97.

Chunked transfer (SURVEY.md §7 hard parts): "unlimited" gRPC message sizes
still stop at protobuf's ~2 GiB per-message framing, and the reference
already collapsed well before that — its controller opens a fresh
channel+stub per request to dodge a throughput cliff at ~100 MB FHE models
(FIXME, reference metisfl/controller/core/controller.cc:594-604). Here
every unary method transparently doubles as a chunked stream-stream method:
payloads above ``STREAM_THRESHOLD`` are framed into ``CHUNK_BYTES``
segments and reassembled server-side (and response-side), so a >2 GiB
model blob round-trips through the same ``call()`` API. A unary response
that would exceed framing is refused server-side with RESOURCE_EXHAUSTED
and the client transparently retries over the chunked path.
"""

from __future__ import annotations

import json
import logging
import time
from concurrent import futures
from typing import Callable, Dict, Optional

import grpc

from metisfl_tpu import chaos as _chaos
from metisfl_tpu.telemetry import events as _events
from metisfl_tpu import telemetry as _tel
from metisfl_tpu.telemetry import metrics as _metrics
from metisfl_tpu.telemetry import trace as _trace

logger = logging.getLogger("metisfl_tpu.rpc")

# Default per-call deadline when the caller passes timeout=None. An
# unbounded RPC means one hung peer can park a dispatch thread forever
# (SURVEY.md §5.3 is full of exactly that failure); every call gets a
# bound unless the caller explicitly opts out (timeout <= 0 via
# CommConfig.default_deadline_s <= 0). Sized for cold-jit learners and
# multi-GB chunked model transfers, not for acks.
DEFAULT_DEADLINE_S = 120.0

# Per-method RPC metrics (telemetry registry; families are idempotent so
# module reload is safe). Client counters are LOGICAL: one sample per
# call() regardless of transparent retries — the retried label says
# whether any fail-then-retry (UNAVAILABLE backoff or unary-oversize →
# chunked) happened inside. Server counters are per handler invocation,
# so the oversize path visibly costs two invocations for one call.
_REG = _metrics.registry()
_M_CLIENT_CALLS = _REG.counter(
    _tel.M_RPC_CLIENT_CALLS_TOTAL, "Logical client calls (retries collapsed)",
    ("service", "method", "retried"))
_M_CLIENT_LATENCY = _REG.histogram(
    _tel.M_RPC_CLIENT_LATENCY_SECONDS, "Logical client call latency",
    ("service", "method"))
_M_CLIENT_BYTES = _REG.counter(
    _tel.M_RPC_CLIENT_BYTES_TOTAL, "Client payload bytes by direction",
    ("service", "method", "direction"))
_M_CLIENT_ERRORS = _REG.counter(
    _tel.M_RPC_CLIENT_ERRORS_TOTAL, "Client calls that raised after retries",
    ("service", "method", "code"))
_M_SERVER_CALLS = _REG.counter(
    _tel.M_RPC_SERVER_CALLS_TOTAL, "Handler invocations",
    ("service", "method", "transport"))
_M_SERVER_LATENCY = _REG.histogram(
    _tel.M_RPC_SERVER_LATENCY_SECONDS, "Server handler latency",
    ("service", "method"))
_M_SERVER_BYTES = _REG.counter(
    _tel.M_RPC_SERVER_BYTES_TOTAL, "Server payload bytes by direction",
    ("service", "method", "direction"))
_M_SERVER_ERRORS = _REG.counter(
    _tel.M_RPC_SERVER_ERRORS_TOTAL, "Handler invocations that raised",
    ("service", "method"))
# Per-peer wire bytes (performance observatory): a client constructed
# with ``peer=<learner_id>`` additionally attributes its payload bytes —
# envelopes included, unlike the controller's payload-level
# uplink/downlink counters — to that peer. Series are pruned on learner
# leave via ``prune_peer_series`` (bounded cardinality under churn).
_M_PEER_BYTES = _REG.counter(
    _tel.M_RPC_PEER_BYTES_TOTAL,
    "Client payload bytes attributed to one peer (learner id), by "
    "direction", ("peer", "direction"), budget_label="peer")


def prune_peer_series(peer: str) -> None:
    for direction in ("sent", "received"):
        _M_PEER_BYTES.remove(peer=peer, direction=direction)


def _error_code_name(exc: Exception) -> str:
    code = exc.code() if hasattr(exc, "code") else None
    return code.name if isinstance(code, grpc.StatusCode) else "UNKNOWN"

_UNLIMITED = [
    ("grpc.max_send_message_length", -1),
    ("grpc.max_receive_message_length", -1),
    # gRPC servers default to SO_REUSEPORT on Linux: two federations (or a
    # stale controller from a crashed run) binding the same port would
    # silently load-balance RPCs between unrelated processes. Fail loudly.
    ("grpc.so_reuseport", 0),
]

_IDENTITY = lambda b: b  # noqa: E731 - bytes in, bytes out

# Chunked-transfer framing. CHUNK_BYTES balances per-message overhead
# against flow-control pipelining; STREAM_THRESHOLD stays far under both
# protobuf's ~2 GiB hard framing limit and the reference's observed
# ~100 MB reused-channel throughput cliff. Module-level so tests (and
# operators) can tune them.
CHUNK_BYTES = 32 * 1024 * 1024
STREAM_THRESHOLD = 128 * 1024 * 1024
# a unary RESPONSE above this cannot be framed — refuse server-side and
# let the client retry chunked (margin under the 2 GiB wire limit)
UNARY_RESPONSE_LIMIT = (2 << 30) - (64 << 20)
_CHUNK_SUFFIX = "Chunked"
_OVERSIZE_MARK = "response exceeds unary framing; retry chunked"


def _iter_chunks(payload: bytes):
    if not payload:
        yield b""
        return
    view = memoryview(payload)
    for i in range(0, len(payload), CHUNK_BYTES):
        yield bytes(view[i : i + CHUNK_BYTES])


class BytesService:
    """A named set of unary bytes→bytes methods served over gRPC.

    Every service automatically answers ``ListMethods`` (the reference's
    gRPC-reflection role): the dispatch table's method names plus the
    transport capability flags — every method doubles as a chunked
    stream, and oversize unary responses fall back to it. The reply is
    JSON (not the wire codec) so generic tooling — the status CLI's
    endpoint probe, a curl through grpcurl — can read it without this
    package.

    Handler contract: a handler whose response can exceed
    :data:`UNARY_RESPONSE_LIMIT` MUST be idempotent — the oversize
    fallback refuses the unary response after the handler already ran
    and the client transparently re-invokes it over the chunked method,
    so such a handler executes twice per logical call (fine for getters
    like GetCommunityModel; a non-idempotent method must keep its
    responses under the limit or route clients to chunked up front).
    """

    def __init__(self, service_name: str,
                 handlers: Dict[str, Callable[[bytes], bytes]],
                 role: str = ""):
        self.service_name = service_name
        # endpoint role ("controller" | "learner" | "serving" | ...): the
        # status CLI's --probe tells a serving gateway apart from a
        # learner without guessing from method names
        self.role = role
        self.handlers = dict(handlers)
        self.handlers.setdefault("ListMethods", self._list_methods)
        if role:
            # fleet telemetry fabric (telemetry/fabric.py): every
            # role-carrying endpoint answers cursor-based telemetry
            # pulls next to ListMethods/GetMetrics — event tail,
            # finished-span ring, metrics state, and the continuous-
            # profiling section (telemetry/prof.py folded stacks + lock
            # contention). With telemetry.fabric.enabled=false the
            # handler answers a one-attribute-check {"enabled": false}
            # stub (telemetry.prof.enabled=false stubs just its
            # section).
            self.handlers.setdefault("CollectTelemetry",
                                     self._collect_telemetry)

    def _collect_telemetry(self, raw: bytes) -> bytes:
        from metisfl_tpu.telemetry import fabric as _fabric
        return _fabric.handle_collect(raw, self.service_name, self.role)

    def _list_methods(self, raw: bytes) -> bytes:
        methods = [
            {"name": name, "transports": ["unary", "chunked"],
             "oversize_unary_fallback": True}
            for name in sorted(self.handlers)
        ]
        reply = {"service": self.service_name, "methods": methods}
        if self.role:
            reply["role"] = self.role
        return json.dumps(reply).encode("utf-8")

    def _generic_handler(self) -> grpc.GenericRpcHandler:
        method_handlers = {}
        for name, fn in self.handlers.items():
            method_handlers[name] = grpc.unary_unary_rpc_method_handler(
                self._wrap(name, fn),
                request_deserializer=_IDENTITY,
                response_serializer=_IDENTITY,
            )
            # every method transparently doubles as a chunked stream:
            # RpcClient routes payloads above STREAM_THRESHOLD (and
            # oversize-response retries) here
            method_handlers[name + _CHUNK_SUFFIX] = \
                grpc.stream_stream_rpc_method_handler(
                    self._wrap_chunked(name, fn),
                    request_deserializer=_IDENTITY,
                    response_serializer=_IDENTITY,
                )
        return grpc.method_handlers_generic_handler(
            self.service_name, method_handlers)

    @staticmethod
    def _abort(context: grpc.ServicerContext, exc: Exception):
        code = getattr(exc, "code", None)
        if callable(code):  # RpcError-shaped (incl. chaos FaultInjected)
            try:
                code = code()
            except Exception:  # noqa: BLE001 - fall through to INTERNAL
                code = None
        if isinstance(code, grpc.StatusCode):
            context.abort(code, str(exc))
        if isinstance(exc, ValueError):
            # malformed input (codec framing, blob integrity/checksum) is
            # the caller's defect, not a server bug: reject it as
            # INVALID_ARGUMENT so clients/retry ladders never treat a
            # corrupt payload as a transient server failure
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"{type(exc).__name__}: {exc}")
        logger.exception("RPC handler failed")
        context.abort(grpc.StatusCode.INTERNAL,
                      f"{type(exc).__name__}: {exc}")

    def _wrap(self, method: str, fn: Callable[[bytes], bytes]):
        service = self.service_name

        def handler(request: bytes, context: grpc.ServicerContext) -> bytes:
            t0 = time.perf_counter()
            _M_SERVER_CALLS.inc(service=service, method=method,
                                transport="unary")
            _M_SERVER_BYTES.inc(len(request), service=service,
                                method=method, direction="in")
            sp = _trace.span(
                f"rpc.server/{method}",
                parent=_trace.extract(context.invocation_metadata()),
                attrs={"service": service})
            try:
                with sp, sp.activate():
                    try:
                        inj = _chaos.get()
                        if inj is not None:
                            request = inj.intercept("server", service,
                                                    method, request)
                        result = fn(request)
                    except Exception as exc:
                        _M_SERVER_ERRORS.inc(service=service, method=method)
                        sp.set_attr("error", f"{type(exc).__name__}: {exc}")
                        BytesService._abort(context, exc)
                if len(result) > UNARY_RESPONSE_LIMIT:
                    # cannot frame this as one message — the client retries
                    # over the chunked method on this exact status+detail.
                    # NOTE the handler has already run to completion here
                    # and will run AGAIN on the retry: only idempotent
                    # handlers may return oversize responses (see the
                    # BytesService class docstring).
                    context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                                  _OVERSIZE_MARK)
                _M_SERVER_BYTES.inc(len(result), service=service,
                                    method=method, direction="out")
                return result
            finally:
                _M_SERVER_LATENCY.observe(time.perf_counter() - t0,
                                          service=service, method=method)

        return handler

    def _wrap_chunked(self, method: str, fn: Callable[[bytes], bytes]):
        service = self.service_name

        def handler(request_iter, context: grpc.ServicerContext):
            t0 = time.perf_counter()
            _M_SERVER_CALLS.inc(service=service, method=method,
                                transport="chunked")
            try:
                try:
                    # draining the request stream can itself fail (client
                    # cancelled mid-upload): shape it like a handler error
                    # so metrics and status stay consistent
                    request = b"".join(request_iter)
                except Exception as exc:
                    _M_SERVER_ERRORS.inc(service=service, method=method)
                    BytesService._abort(context, exc)
                _M_SERVER_BYTES.inc(len(request), service=service,
                                    method=method, direction="in")
                sp = _trace.span(
                    f"rpc.server/{method}",
                    parent=_trace.extract(context.invocation_metadata()),
                    attrs={"service": service, "transport": "chunked"})
                with sp, sp.activate():
                    try:
                        inj = _chaos.get()
                        if inj is not None:
                            request = inj.intercept("server", service,
                                                    method, request)
                        result = fn(request)
                    except Exception as exc:
                        _M_SERVER_ERRORS.inc(service=service, method=method)
                        sp.set_attr("error", f"{type(exc).__name__}: {exc}")
                        BytesService._abort(context, exc)
                _M_SERVER_BYTES.inc(len(result), service=service,
                                    method=method, direction="out")
            finally:
                _M_SERVER_LATENCY.observe(time.perf_counter() - t0,
                                          service=service, method=method)
            yield from _iter_chunks(result)

        return handler


class RpcServer:
    """gRPC server hosting one or more :class:`BytesService`s.

    ``ssl``: an enabled :class:`metisfl_tpu.comm.ssl.SSLConfig` serves TLS
    (reference controller_servicer.cc:38-74); None serves plaintext.
    """

    def __init__(self, host: str, port: int, max_workers: int = 16, ssl=None):
        self.host = host
        self.port = port
        self.ssl = ssl if (ssl is not None and ssl.enabled) else None
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=_UNLIMITED,
        )
        self._bound_port: Optional[int] = None

    def add_service(self, service: BytesService) -> None:
        self._server.add_generic_rpc_handlers((service._generic_handler(),))

    def start(self) -> int:
        addr = f"{self.host}:{self.port}"
        if self.ssl is not None:
            from metisfl_tpu.comm.ssl import server_credentials
            self._bound_port = self._server.add_secure_port(
                addr, server_credentials(self.ssl))
        else:
            self._bound_port = self._server.add_insecure_port(addr)
        if self._bound_port == 0:
            raise RuntimeError(f"could not bind gRPC server on {addr}")
        self._server.start()
        logger.info("gRPC server listening on %s:%d%s", self.host,
                    self._bound_port, " (TLS)" if self.ssl else "")
        return self._bound_port

    def stop(self, grace: float = 1.0) -> None:
        self._server.stop(grace).wait()

    def wait(self) -> None:
        self._server.wait_for_termination()


class RpcClient:
    """Channel to a :class:`BytesService` with retry/backoff on UNAVAILABLE.

    ``default_deadline_s``: deadline applied when a call passes
    ``timeout=None`` (config ``comm.default_deadline_s``). ``None`` →
    :data:`DEFAULT_DEADLINE_S`; ``<= 0`` → explicitly unbounded (the old
    behavior, for operators who really want it).
    """

    def __init__(self, host: str, port: int, service_name: str,
                 retries: int = 10, retry_sleep_s: float = 1.0, ssl=None,
                 default_deadline_s: Optional[float] = None,
                 peer: str = ""):
        self.target = f"{host}:{port}"
        self.service_name = service_name
        # optional peer identity (a learner id): when set, payload bytes
        # additionally land in the peer-labeled wire-byte counter (the
        # performance observatory's per-learner wire attribution)
        self.peer = peer
        self.retries = retries
        self.retry_sleep_s = retry_sleep_s
        if default_deadline_s is None:
            default_deadline_s = DEFAULT_DEADLINE_S
        self.default_deadline_s = (default_deadline_s
                                   if default_deadline_s > 0 else None)
        if ssl is not None and ssl.enabled:
            from metisfl_tpu.comm.ssl import channel_credentials
            self._channel = grpc.secure_channel(
                self.target, channel_credentials(ssl), options=_UNLIMITED)
        else:
            self._channel = grpc.insecure_channel(self.target, options=_UNLIMITED)
        # eager (threads only spawn on first submit): lazy init would race
        # between the app thread and grpc callback threads
        self._stream_pool = futures.ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="rpc-chunked")
        # methods observed to need chunked responses: remember so later
        # calls skip the fail-then-retry (which runs the handler twice)
        self._chunked_methods: set = set()

    def call(self, method: str, payload: bytes, timeout: Optional[float] = None,
             wait_ready: bool = True, idempotent: bool = False) -> bytes:
        """``idempotent=True`` additionally retries DEADLINE_EXCEEDED —
        only safe for methods whose re-execution cannot double-apply
        (getters, join/rejoin, health)."""
        if timeout is None:
            timeout = self.default_deadline_s
        chunked = (len(payload) > STREAM_THRESHOLD
                   or method in self._chunked_methods)
        attempt = 0
        retried = 0
        t0 = time.perf_counter()
        try:
            while True:
                try:
                    inj = _chaos.get()
                    send = (payload if inj is None else inj.intercept(
                        "client", self.service_name, method, payload))
                    if chunked:
                        result = self._call_chunked(method, send, timeout,
                                                    wait_ready)
                    else:
                        fn = self._channel.unary_unary(
                            f"/{self.service_name}/{method}",
                            request_serializer=_IDENTITY,
                            response_deserializer=_IDENTITY,
                        )
                        result = fn(send, timeout=timeout,
                                    wait_for_ready=wait_ready,
                                    metadata=_trace.outbound_metadata())
                    self._count_bytes(len(payload), "sent", method=method)
                    self._count_bytes(len(result), "received", method=method)
                    return result
                except (grpc.RpcError, _chaos.FaultInjected) as exc:
                    code = exc.code() if hasattr(exc, "code") else None
                    if (not chunked
                            and code == grpc.StatusCode.RESOURCE_EXHAUSTED
                            and _OVERSIZE_MARK in (exc.details() or "")):
                        # the handler's response exceeds unary framing (e.g. a
                        # >2 GiB community model behind a tiny request):
                        # transparently re-issue over the chunked stream, and
                        # remember — the fail-then-retry runs the handler twice
                        chunked = True
                        retried = 1
                        self._chunked_methods.add(method)
                        _events.emit(_events.RetryScheduled,
                                     service=self.service_name,
                                     method=method, code="OVERSIZE_UNARY")
                        continue
                    retryable = (code == grpc.StatusCode.UNAVAILABLE
                                 or (idempotent and code
                                     == grpc.StatusCode.DEADLINE_EXCEEDED))
                    if retryable and attempt < self.retries:
                        attempt += 1
                        retried = 1
                        logger.warning("%s/%s %s (attempt %d/%d)",
                                       self.target, method,
                                       code.name.lower(), attempt,
                                       self.retries)
                        _events.emit(_events.RetryScheduled,
                                     service=self.service_name,
                                     method=method, code=code.name,
                                     attempt=attempt)
                        time.sleep(self.retry_sleep_s)
                        continue
                    _M_CLIENT_ERRORS.inc(service=self.service_name,
                                         method=method,
                                         code=_error_code_name(exc))
                    raise
        finally:
            # ONE logical-call sample however many transparent retries ran
            # inside (the regression contract tests/test_rpc.py pins)
            self._record_client_call(method, str(retried), t0)

    def _call_chunked(self, method: str, payload: bytes,
                      timeout: Optional[float], wait_ready: bool) -> bytes:
        fn = self._channel.stream_stream(
            f"/{self.service_name}/{method}{_CHUNK_SUFFIX}",
            request_serializer=_IDENTITY,
            response_deserializer=_IDENTITY,
        )
        return b"".join(fn(_iter_chunks(payload), timeout=timeout,
                           wait_for_ready=wait_ready,
                           metadata=_trace.outbound_metadata()))

    @staticmethod
    def _resolve(outer: "futures.Future", result=None,
                 exc: Optional[Exception] = None) -> None:
        """Resolve the caller-facing wrapper future, tolerating a caller
        that cancelled it while the call was in flight."""
        try:
            if exc is not None:
                outer.set_exception(exc)
            else:
                outer.set_result(result)
        except futures.InvalidStateError:  # pragma: no cover - cancelled
            pass

    def call_async(self, method: str, payload: bytes,
                   callback: Optional[Callable[[bytes], None]] = None,
                   error_callback: Optional[Callable[[Exception], None]] = None,
                   timeout: Optional[float] = None,
                   wait_ready: bool = True) -> "futures.Future":
        """Non-blocking unary call (the reference's CompletionQueue pattern,
        controller.cc:713-759, via grpc futures). ``wait_ready=False`` fails
        fast with UNAVAILABLE on a dead endpoint instead of queueing.
        Payloads above STREAM_THRESHOLD (and oversize unary responses)
        route through the chunked stream on a worker thread — stream
        draining has no grpc-future form.

        Returns a wrapper :class:`concurrent.futures.Future` resolved
        only by the FINAL outcome: a unary attempt refused oversize
        retries transparently over the chunked stream, and the wrapper
        stays pending until that retry settles — the caller never sees a
        failure for a call that then succeeds (the ADVICE r5 double
        signal). Callbacks fire exactly once either way."""
        # capture the span context HERE, on the caller's thread: grpc
        # completion callbacks and the stream pool run in their own
        # (empty) contextvars contexts, so an oversize retry issued from
        # _done would otherwise lose the trace parent
        ctx = _trace.current_context()
        t0 = time.perf_counter()
        outer: "futures.Future" = futures.Future()
        if timeout is None:
            timeout = self.default_deadline_s
        inj = _chaos.get()
        if inj is not None:
            # chaos fires synchronously on the caller's thread: a drop
            # raises here, which dispatch paths already treat as a failed
            # dispatch (liveness accounting)
            payload = inj.intercept("client", self.service_name, method,
                                    payload)
        if (len(payload) > STREAM_THRESHOLD
                or method in self._chunked_methods):
            self._async_chunked(method, payload, callback,
                                error_callback, timeout, wait_ready,
                                ctx=ctx, t0=t0, outer=outer)
            return outer
        fn = self._channel.unary_unary(
            f"/{self.service_name}/{method}",
            request_serializer=_IDENTITY,
            response_deserializer=_IDENTITY,
        )
        future = fn.future(payload, timeout=timeout, wait_for_ready=wait_ready,
                           metadata=_trace.outbound_metadata())

        def _done(f):
            try:
                result = f.result()
            except Exception as exc:  # noqa: BLE001 - surfaced via callback
                if (isinstance(exc, grpc.RpcError)
                        and exc.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
                        and _OVERSIZE_MARK in (exc.details() or "")):
                    self._chunked_methods.add(method)
                    _events.emit(_events.RetryScheduled,
                                 service=self.service_name,
                                 method=method, code="OVERSIZE_UNARY")
                    # still ONE logical call — the chunked leg records it
                    # (with retried="1"), not this failed unary attempt;
                    # the wrapper future resolves only with ITS outcome
                    self._async_chunked(method, payload, callback,
                                        error_callback, timeout, wait_ready,
                                        retried="1", ctx=ctx, t0=t0,
                                        outer=outer)
                    return
                # never invisible: count the failure whether or not the
                # caller asked to hear about it — and keep the logical-call
                # denominator honest (errors_total/calls_total <= 1)
                _M_CLIENT_ERRORS.inc(service=self.service_name,
                                     method=method,
                                     code=_error_code_name(exc))
                self._record_client_call(method, "0", t0)
                self._resolve(outer, exc=exc)
                if error_callback is not None:
                    error_callback(exc)
                else:
                    logger.warning("async RPC %s failed with no "
                                   "error_callback: %s", method, exc)
                return
            self._record_client_call(method, "0", t0, sent=len(payload),
                                     received=len(result))
            self._resolve(outer, result=result)
            if callback is not None:
                callback(result)

        future.add_done_callback(_done)
        return outer

    def _count_bytes(self, nbytes: int, direction: str,
                     method: str = "") -> None:
        """Payload bytes by direction: the per-method client counter, plus
        the peer-labeled series when this client is pinned to a peer."""
        if method:
            _M_CLIENT_BYTES.inc(nbytes, service=self.service_name,
                                method=method, direction=direction)
        if self.peer:
            _M_PEER_BYTES.inc(nbytes, peer=self.peer, direction=direction)

    def _record_client_call(self, method: str, retried: str, t0: float,
                            sent: Optional[int] = None,
                            received: Optional[int] = None) -> None:
        """One logical-call sample (calls + latency, and bytes on
        success) — async paths share the sync ``call()`` contract so the
        client metric families stay mutually consistent."""
        _M_CLIENT_CALLS.inc(service=self.service_name, method=method,
                            retried=retried)
        _M_CLIENT_LATENCY.observe(time.perf_counter() - t0,
                                  service=self.service_name, method=method)
        if sent is not None:
            self._count_bytes(sent, "sent", method=method)
        if received is not None:
            self._count_bytes(received, "received", method=method)

    def _async_chunked(self, method, payload, callback, error_callback,
                       timeout, wait_ready, retried: str = "0",
                       ctx=None, t0: Optional[float] = None,
                       outer: Optional["futures.Future"] = None):
        # ``ctx``/``t0`` arrive from call_async's caller thread (a grpc
        # completion thread has no useful contextvars state); direct
        # callers fall back to capturing here. ``retried="1"`` marks this
        # leg as the transparent continuation of a failed unary attempt —
        # one logical call either way, and ``outer`` (the caller-facing
        # wrapper future) resolves only with THIS leg's final outcome.
        if ctx is None:
            ctx = _trace.current_context()
        if t0 is None:
            t0 = time.perf_counter()

        def _run():
            try:
                with _trace.use_context(ctx):
                    result = self._call_chunked(method, payload, timeout,
                                                wait_ready)
            except Exception as exc:  # noqa: BLE001 - surfaced via callback
                _M_CLIENT_ERRORS.inc(service=self.service_name,
                                     method=method,
                                     code=_error_code_name(exc))
                self._record_client_call(method, retried, t0)
                if outer is not None:
                    self._resolve(outer, exc=exc)
                if error_callback is not None:
                    error_callback(exc)
                else:
                    logger.warning("async chunked RPC %s failed with no "
                                   "error_callback: %s", method, exc)
                return
            self._record_client_call(method, retried, t0,
                                     sent=len(payload),
                                     received=len(result))
            if outer is not None:
                self._resolve(outer, result=result)
            if callback is not None:
                callback(result)

        try:
            return self._stream_pool.submit(_run)
        except RuntimeError as exc:
            # pool already shut down (client.close() raced the oversize
            # retry issued from a grpc completion thread): the wrapper
            # future must still settle — a swallowed submit failure would
            # leave the caller blocked on it forever
            _M_CLIENT_ERRORS.inc(service=self.service_name, method=method,
                                 code="UNKNOWN")
            self._record_client_call(method, retried, t0)
            if outer is not None:
                self._resolve(outer, exc=exc)
            if error_callback is not None:
                error_callback(exc)
            else:
                logger.warning("async chunked RPC %s could not be "
                               "scheduled: %s", method, exc)
            return None

    def close(self) -> None:
        self._stream_pool.shutdown(wait=False)
        self._channel.close()
