"""Standard gRPC health-checking protocol (``grpc.health.v1.Health``).

The reference registers grpc's default health-check service so off-the-shelf
probes (grpc_health_probe, k8s) work against controller and learner
(reference metisfl/controller/core/controller_servicer.cc:7-9,32-33). The
``grpc_health`` codegen package is not available in this environment, so the
two protobuf messages are encoded by hand — they are a single string field
(HealthCheckRequest.service, field 1) and a single enum field
(HealthCheckResponse.status, field 1), both trivially wire-stable:

    https://github.com/grpc/grpc/blob/master/doc/health-checking.md

Served alongside the framework's richer custom ``GetHealthStatus`` RPC.
"""

from __future__ import annotations

import threading
from typing import Dict

import grpc

from metisfl_tpu.comm.rpc import BytesService

HEALTH_SERVICE = "grpc.health.v1.Health"

UNKNOWN = 0
SERVING = 1
NOT_SERVING = 2
SERVICE_UNKNOWN = 3


def _read_varint(raw: bytes, pos: int):
    value, shift = 0, 0
    while pos < len(raw):
        byte = raw[pos]
        pos += 1
        value |= (byte & 0x7F) << shift
        if not byte & 0x80:
            return value, pos
        shift += 7
    raise ValueError("truncated varint")


def decode_request(raw: bytes) -> str:
    """HealthCheckRequest → service name ('' = overall server health)."""
    pos = 0
    while pos < len(raw):
        tag, pos = _read_varint(raw, pos)
        if tag == 0x0A:  # field 1, length-delimited
            length, pos = _read_varint(raw, pos)
            return raw[pos : pos + length].decode("utf-8", "replace")
        # skip unknown fields conservatively
        wire_type = tag & 0x07
        if wire_type == 0:
            _, pos = _read_varint(raw, pos)
        elif wire_type == 2:
            length, pos = _read_varint(raw, pos)
            pos += length
        else:  # pragma: no cover - not produced by health clients
            break
    return ""


def encode_response(status: int) -> bytes:
    """HealthCheckResponse{status}: field 1 varint (status < 128 always)."""
    return bytes([0x08, status])


def encode_request(service: str = "") -> bytes:
    """Client-side helper (tests / probing peers)."""
    if not service:
        return b""
    payload = service.encode()
    if len(payload) > 127:  # pragma: no cover - service names are short
        raise ValueError("service name too long")
    return bytes([0x0A, len(payload)]) + payload


def decode_response(raw: bytes) -> int:
    pos = 0
    while pos < len(raw):
        tag, pos = _read_varint(raw, pos)
        if tag == 0x08:
            value, pos = _read_varint(raw, pos)
            return value
        break
    return UNKNOWN


class HealthServicer:
    """Serve ``Check`` with per-service statuses (Watch is streaming and not
    required by probes; unary-only transport here)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._status: Dict[str, int] = {"": SERVING}

    def set_status(self, service: str, status: int) -> None:
        with self._lock:
            self._status[service] = status

    def set_all(self, status: int) -> None:
        with self._lock:
            for service in self._status:
                self._status[service] = status

    def service(self) -> BytesService:
        return BytesService(HEALTH_SERVICE, {"Check": self._check})

    def _check(self, raw: bytes) -> bytes:
        service = decode_request(raw)
        with self._lock:
            status = self._status.get(service)
        if status is None:
            # spec: unknown service → NOT_FOUND
            raise _NotFound(service)
        return encode_response(status)


class _NotFound(Exception):
    def __init__(self, service: str):
        super().__init__(f"unknown health service {service!r}")
        self.code = grpc.StatusCode.NOT_FOUND


STATUS_NAMES = {UNKNOWN: "UNKNOWN", SERVING: "SERVING",
                NOT_SERVING: "NOT_SERVING",
                SERVICE_UNKNOWN: "SERVICE_UNKNOWN"}


def probe_health(host: str, port: int, service: str = "", ssl=None,
                 comm=None, timeout: float = 2.0) -> str:
    """One ``grpc.health.v1.Health/Check`` against an endpoint, as a
    status name ("SERVING" / "NOT_SERVING" / ... / "UNREACHABLE") —
    the status CLI's ``--probe``/``--fleet`` peer-row probe and the
    fleet collector's liveness column. Fail-fast (no wait-for-ready, no
    retries) and never raises: a dead endpoint is an answer here, not
    an error."""
    from metisfl_tpu.comm.rpc import RpcClient

    kwargs = {}
    if comm is not None:
        kwargs = {"default_deadline_s": comm.default_deadline_s}
    client = RpcClient(host, port, HEALTH_SERVICE, retries=0, ssl=ssl,
                       **kwargs)
    try:
        raw = client.call("Check", encode_request(service),
                          timeout=timeout, wait_ready=False,
                          idempotent=True)
        return STATUS_NAMES.get(decode_response(raw), "UNKNOWN")
    except Exception:  # noqa: BLE001 - unreachable IS the probe answer
        return "UNREACHABLE"
    finally:
        client.close()
