"""Self-describing binary codec for federation messages.

A minimal tagged format (msgpack-flavored, but ours — stable and trivially
implementable in C++): values are ``None``, bools, signed ints (zigzag
varint), float64, utf-8 strings, bytes, lists and string-keyed dicts. Bulk
tensors never pass through this codec — they travel as raw tensor blobs
(:mod:`metisfl_tpu.tensor`) referenced from messages as ``bytes`` fields, so
the codec stays small and the hot path stays memcpy-shaped.

Replaces the reference's protobuf layer (metisfl/proto/*.proto) at the
message level; see messages.py for the concrete message schemas.
"""

from __future__ import annotations

import contextlib
import contextvars
import struct
import threading
import time
from typing import Any, Dict, Tuple

import numpy as np

from metisfl_tpu import telemetry as _tel
from metisfl_tpu.telemetry import metrics as _tmetrics
from metisfl_tpu.telemetry import trace as _ttrace

# codec hot-path telemetry: histograms always (cheap), spans only for
# payloads big enough to matter in a round trace — every tiny ack would
# otherwise flood the JSONL sink
_M_CODEC = _tmetrics.registry().histogram(
    _tel.M_CODEC_DURATION_SECONDS, "Message codec encode/decode time", ("op",))
_M_CODEC_BYTES = _tmetrics.registry().counter(
    _tel.M_CODEC_BYTES_TOTAL, "Message codec bytes by operation", ("op",))
_SPAN_MIN_BYTES = 1 << 18

# Per-learner codec attribution (performance observatory): call sites
# that know which learner a message belongs to wrap the encode in
# ``attributed(learner_id)`` (or report a self-timed decode via
# ``attribute``), and the time lands in a labeled counter + the process
# totals the profile collector diffs per round. Series are pruned on
# learner leave (``prune_attribution``) — bounded cardinality under
# churn, the same posture as the controller's per-learner gauges.
_M_CODEC_LEARNER = _tmetrics.registry().counter(
    _tel.M_CODEC_LEARNER_SECONDS,
    "Codec encode/decode seconds attributed to one learner's messages",
    ("learner", "op"), budget_label="learner")
_ATTR: "contextvars.ContextVar[str]" = contextvars.ContextVar(
    "metisfl_tpu_codec_attr", default="")
_ATTR_LOCK = threading.Lock()
_ATTR_TOTALS: Dict[Tuple[str, str], float] = {}


@contextlib.contextmanager
def attributed(learner_id: str):
    """Attribute every dumps/loads inside the block to ``learner_id``."""
    token = _ATTR.set(learner_id or "")
    try:
        yield
    finally:
        _ATTR.reset(token)


def attribute(learner_id: str, op: str, seconds: float) -> None:
    """Record codec time for a learner's message (post-hoc form, for
    decode sites that only learn the learner id FROM the decode)."""
    if not learner_id or not _tmetrics.enabled():
        return
    _M_CODEC_LEARNER.inc(seconds, learner=learner_id, op=op)
    with _ATTR_LOCK:
        key = (learner_id, op)
        _ATTR_TOTALS[key] = _ATTR_TOTALS.get(key, 0.0) + seconds


def attributed_totals() -> Dict[Tuple[str, str], float]:
    """Cumulative attributed seconds ``{(learner_id, op): s}`` — the
    profile collector snapshots this per round and diffs."""
    with _ATTR_LOCK:
        return dict(_ATTR_TOTALS)


def prune_attribution(learner_id: str) -> None:
    for op in ("encode", "decode"):
        _M_CODEC_LEARNER.remove(learner=learner_id, op=op)
    with _ATTR_LOCK:
        for key in [k for k in _ATTR_TOTALS if k[0] == learner_id]:
            del _ATTR_TOTALS[key]

_T_NONE = 0x00
_T_FALSE = 0x01
_T_TRUE = 0x02
_T_INT = 0x03
_T_FLOAT = 0x04
_T_STR = 0x05
_T_BYTES = 0x06
_T_LIST = 0x07
_T_DICT = 0x08


def _write_varint(out: bytearray, value: int) -> None:
    while True:
        byte = value & 0x7F
        value >>= 7
        if value:
            out.append(byte | 0x80)
        else:
            out.append(byte)
            return


_INT64_MIN = -(2**63)
_INT64_MAX = 2**63 - 1


def _zigzag(value: int) -> int:
    if not _INT64_MIN <= value <= _INT64_MAX:
        raise OverflowError(f"codec ints are 64-bit; {value} out of range")
    return (value << 1) ^ (value >> 63) if value < 0 else value << 1


def _unzigzag(value: int) -> int:
    return (value >> 1) ^ -(value & 1)


def _encode(out: bytearray, value: Any) -> None:
    # Coerce numpy scalars (jit outputs land here via metric dicts).
    if isinstance(value, np.generic):
        value = value.item()
    if value is None:
        out.append(_T_NONE)
    elif value is True:
        out.append(_T_TRUE)
    elif value is False:
        out.append(_T_FALSE)
    elif isinstance(value, int):
        out.append(_T_INT)
        _write_varint(out, _zigzag(value))
    elif isinstance(value, float):
        out.append(_T_FLOAT)
        out.extend(struct.pack("<d", value))
    elif isinstance(value, str):
        encoded = value.encode("utf-8")
        out.append(_T_STR)
        _write_varint(out, len(encoded))
        out.extend(encoded)
    elif isinstance(value, (bytes, bytearray, memoryview)):
        if isinstance(value, memoryview) and (value.itemsize != 1 or value.ndim != 1):
            value = bytes(value)  # measure/extend in bytes, not elements
        out.append(_T_BYTES)
        _write_varint(out, len(value))
        out.extend(value)
    elif isinstance(value, (list, tuple)):
        out.append(_T_LIST)
        _write_varint(out, len(value))
        for item in value:
            _encode(out, item)
    elif isinstance(value, dict):
        out.append(_T_DICT)
        _write_varint(out, len(value))
        for key, item in value.items():
            if not isinstance(key, str):
                raise TypeError(f"dict keys must be str, got {type(key)!r}")
            encoded = key.encode("utf-8")
            _write_varint(out, len(encoded))
            out.extend(encoded)
            _encode(out, item)
    else:
        raise TypeError(f"codec cannot encode {type(value)!r}")


def dumps(value: Any) -> bytes:
    out = bytearray()
    if not _tmetrics.enabled():
        _encode(out, value)
        return bytes(out)
    t0 = time.perf_counter()
    _encode(out, value)
    buf = bytes(out)
    elapsed = time.perf_counter() - t0
    _M_CODEC.observe(elapsed, op="encode")
    _M_CODEC_BYTES.inc(len(buf), op="encode")
    lid = _ATTR.get()
    if lid:
        attribute(lid, "encode", elapsed)
    if len(buf) >= _SPAN_MIN_BYTES:
        _ttrace.event("codec.encode", elapsed, attrs={"bytes": len(buf)})
    return buf


def _read_varint(view: memoryview, offset: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if offset >= len(view):
            raise ValueError("codec: truncated varint")
        if shift > 63:  # match the encoder's 64-bit contract (C++ interop)
            raise ValueError("codec: varint exceeds 64 bits")
        byte = view[offset]
        offset += 1
        result |= (byte & 0x7F) << shift
        if not byte & 0x80:
            if result > 0xFFFFFFFFFFFFFFFF:
                raise ValueError("codec: varint exceeds 64 bits")
            return result, offset
        shift += 7


def _take(view: memoryview, offset: int, length: int) -> tuple[memoryview, int]:
    end = offset + length
    if end > len(view):
        raise ValueError(
            f"codec: truncated buffer (need {end} bytes, have {len(view)})"
        )
    return view[offset:end], end


# Nesting bound for the RECURSIVE decoder: crafted deep nesting (~2 bytes
# per level) must raise a clean ValueError at the wire boundary, not blow
# the interpreter stack with RecursionError. Far above any real message
# (messages nest < 10 deep).
_MAX_DEPTH = 100


def _decode(view: memoryview, offset: int, depth: int = 0) -> tuple[Any, int]:
    if depth > _MAX_DEPTH:
        raise ValueError(f"codec: nesting exceeds {_MAX_DEPTH} levels")
    if offset >= len(view):
        raise ValueError("codec: truncated buffer (empty value)")
    tag = view[offset]
    offset += 1
    if tag == _T_NONE:
        return None, offset
    if tag == _T_TRUE:
        return True, offset
    if tag == _T_FALSE:
        return False, offset
    if tag == _T_INT:
        raw, offset = _read_varint(view, offset)
        return _unzigzag(raw), offset
    if tag == _T_FLOAT:
        raw, offset = _take(view, offset, 8)
        return struct.unpack("<d", raw)[0], offset
    if tag == _T_STR:
        length, offset = _read_varint(view, offset)
        raw, offset = _take(view, offset, length)
        return bytes(raw).decode("utf-8"), offset
    if tag == _T_BYTES:
        length, offset = _read_varint(view, offset)
        raw, offset = _take(view, offset, length)
        return bytes(raw), offset
    if tag == _T_LIST:
        length, offset = _read_varint(view, offset)
        items = []
        for _ in range(length):
            item, offset = _decode(view, offset, depth + 1)
            items.append(item)
        return items, offset
    if tag == _T_DICT:
        length, offset = _read_varint(view, offset)
        result = {}
        for _ in range(length):
            klen, offset = _read_varint(view, offset)
            raw, offset = _take(view, offset, klen)
            key = bytes(raw).decode("utf-8")
            result[key], offset = _decode(view, offset, depth + 1)
        return result, offset
    raise ValueError(f"codec: unknown tag 0x{tag:02x} at offset {offset - 1}")


def loads(buf) -> Any:
    if not _tmetrics.enabled():
        return _loads(buf)
    t0 = time.perf_counter()
    value = _loads(buf)
    elapsed = time.perf_counter() - t0
    nbytes = memoryview(buf).nbytes
    _M_CODEC.observe(elapsed, op="decode")
    _M_CODEC_BYTES.inc(nbytes, op="decode")
    lid = _ATTR.get()
    if lid:
        attribute(lid, "decode", elapsed)
    if nbytes >= _SPAN_MIN_BYTES:
        _ttrace.event("codec.decode", elapsed, attrs={"bytes": nbytes})
    return value


def _loads(buf) -> Any:
    view = memoryview(buf)
    value, offset = _decode(view, 0)
    if offset != len(view):
        # trailing bytes mean a framing error (truncated write spliced with
        # the next frame, corrupt length prefix): decoding a prefix and
        # silently discarding the rest would return a wrong value
        raise ValueError(
            f"codec: {len(view) - offset} trailing byte(s) after value")
    return value
