"""Structured event journal: the federation's flight-data recorder.

Third telemetry layer next to spans (how long did it take) and metrics
(how much / how often): a typed, ordered record of *what the process was
doing* — learners joining, rounds starting, tasks dispatching, retries
scheduling, faults firing. Spans and metrics answer performance
questions after the fact; the journal answers "what was in flight when
it died" and feeds the live `DescribeFederation` snapshot.

Events are frozen dataclasses (one class per kind, typed fields), stamped
at emit time with a process-monotonic ``seq`` and a wall-clock ``ts``,
and kept in a bounded in-memory ring buffer. With a sink directory
configured, each event additionally appends one JSON line to
``<dir>/<service>-<pid>-events.jsonl`` (same per-process-file +
torn-sink-tolerant posture as :mod:`metisfl_tpu.telemetry.trace`). The
ring tail is exported over ``DescribeFederation`` and into post-mortem
bundles (:mod:`metisfl_tpu.telemetry.postmortem`).

Overhead contract: a disabled journal costs one attribute read per call
site — :func:`emit` returns before the event dataclass is even
constructed (federation config ``telemetry.events.enabled=false``).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from dataclasses import asdict, dataclass
from typing import Any, ClassVar, Dict, List, Optional, Type

DEFAULT_RING_SIZE = 512


# --------------------------------------------------------------------- #
# event catalog (docs/OBSERVABILITY.md "Events, status, and post-mortems")
# --------------------------------------------------------------------- #

@dataclass(frozen=True)
class LearnerJoined:
    kind: ClassVar[str] = "learner_joined"
    learner_id: str
    hostname: str = ""
    port: int = 0
    rejoined: bool = False


@dataclass(frozen=True)
class LearnerLost:
    kind: ClassVar[str] = "learner_lost"
    learner_id: str
    reason: str = "leave"


@dataclass(frozen=True)
class RoundStarted:
    kind: ClassVar[str] = "round_started"
    round: int
    cohort: int = 0


@dataclass(frozen=True)
class TaskDispatched:
    kind: ClassVar[str] = "task_dispatched"
    task_id: str
    learner_id: str
    round: int = 0


@dataclass(frozen=True)
class TaskCompleted:
    kind: ClassVar[str] = "task_completed"
    task_id: str
    learner_id: str
    round: int = 0
    stale: bool = False
    uplink_bytes: int = 0


@dataclass(frozen=True)
class RetryScheduled:
    """A transparent RPC-client retry (UNAVAILABLE backoff, idempotent
    DEADLINE_EXCEEDED, or the unary-oversize → chunked fallback)."""

    kind: ClassVar[str] = "retry_scheduled"
    service: str
    method: str
    code: str = ""
    attempt: int = 0


@dataclass(frozen=True)
class FaultInjected:
    kind: ClassVar[str] = "fault_injected"
    fault: str
    side: str = ""
    method: str = ""


@dataclass(frozen=True)
class EpochChanged:
    """A learner observed a controller-incarnation change (crash+restart)."""

    kind: ClassVar[str] = "epoch_changed"
    learner_id: str
    old_epoch: str = ""
    new_epoch: str = ""
    reason: str = ""


@dataclass(frozen=True)
class AggregationDone:
    kind: ClassVar[str] = "aggregation_done"
    round: int
    selected: int = 0
    duration_ms: float = 0.0


@dataclass(frozen=True)
class FailoverBegan:
    """The driver began a supervised controller relaunch."""

    kind: ClassVar[str] = "failover_began"
    restart: int
    exit_code: Optional[int] = None


@dataclass(frozen=True)
class UpdateAnomalous:
    """A learner's update diverged from its cohort past the configured
    robust-z threshold (telemetry/health.py; ``raw`` is this round's
    z-score, ``score`` the EWMA divergence score after folding it)."""

    kind: ClassVar[str] = "update_anomalous"
    learner_id: str
    round: int = 0
    score: float = 0.0
    raw: float = 0.0
    update_norm: float = 0.0


@dataclass(frozen=True)
class RoundHealth:
    """Per-round learning-health snapshot (telemetry/health.py):
    community update norm, effective step size, participation entropy,
    and how many cohort updates scored anomalous."""

    kind: ClassVar[str] = "round_health"
    round: int
    update_norm: float = 0.0
    effective_step: float = 0.0
    participation_entropy: float = 0.0
    anomalous: int = 0


@dataclass(frozen=True)
class LearnerQuarantined:
    """A flapping learner's churn score crossed the quarantine threshold
    (selection.py ChurnTracker): excluded from cohort sampling until
    ``until_s`` seconds of quarantine elapse."""

    kind: ClassVar[str] = "learner_quarantined"
    learner_id: str
    score: float = 0.0
    until_s: float = 0.0


@dataclass(frozen=True)
class DispatchRetried:
    """A failed train dispatch was retried to a replacement learner
    (scheduling.dispatch_retries): the dead endpoint left the round
    barrier and ``replacement`` was dispatched in its place."""

    kind: ClassVar[str] = "dispatch_retried"
    learner_id: str
    replacement: str = ""
    attempt: int = 0


@dataclass(frozen=True)
class RoundHalted:
    """The controller stopped re-dispatching a round that can never
    complete (consecutive zero-reporter deadlines past
    scheduling.max_empty_redispatch, or the aggregation-failure limit)."""

    kind: ClassVar[str] = "round_halted"
    round: int
    reason: str = ""


@dataclass(frozen=True)
class VersionRegistered:
    """The model registry minted a candidate version from an aggregated
    round (registry/registry.py)."""

    kind: ClassVar[str] = "version_registered"
    version: int
    round: int = 0
    parent: int = 0
    channel: str = "candidate"


@dataclass(frozen=True)
class VersionPromoted:
    """A registry version moved channels (candidate → stable), through
    the promotion gate or an operator's PromoteVersion."""

    kind: ClassVar[str] = "version_promoted"
    version: int
    round: int = 0
    previous_stable: int = 0
    forced: bool = False


@dataclass(frozen=True)
class VersionRolledBack:
    """The stable channel was rolled back to the prior stable version."""

    kind: ClassVar[str] = "version_rolled_back"
    version: int
    rolled_back_from: int = 0


@dataclass(frozen=True)
class ServingSwapped:
    """The serving gateway hot-swapped a channel to a new version
    (serving/gateway.py) without dropping in-flight requests."""

    kind: ClassVar[str] = "serving_swapped"
    channel: str
    version: int
    previous: int = 0


@dataclass(frozen=True)
class AlertFiring:
    """An alert rule's expression breached its threshold and held past
    its ``for:`` duration (telemetry/alerts.py AlertEngine)."""

    kind: ClassVar[str] = "alert_firing"
    name: str
    expr: str = ""
    value: float = 0.0
    threshold: float = 0.0
    severity: str = "warning"


@dataclass(frozen=True)
class AlertResolved:
    """A firing alert's value crossed back past its resolve-hysteresis
    bound after ``active_s`` seconds."""

    kind: ClassVar[str] = "alert_resolved"
    name: str
    value: float = 0.0
    active_s: float = 0.0


@dataclass(frozen=True)
class FabricPeerStale:
    """The fleet collector's consecutive pulls from a telemetry peer
    failed past the staleness threshold (telemetry/fabric.py
    FleetCollector) — the peer stays in the fleet view, marked stale,
    and collection continues for everyone else."""

    kind: ClassVar[str] = "fabric_peer_stale"
    peer: str
    failures: int = 0


@dataclass(frozen=True)
class FabricPeerRecovered:
    """A stale telemetry peer answered a fleet pull again; its cursors
    resumed (or reset, when the peer restarted with a new epoch)."""

    kind: ClassVar[str] = "fabric_peer_recovered"
    peer: str
    stale_s: float = 0.0
    epoch_changed: bool = False


@dataclass(frozen=True)
class ServingReplicaDead:
    """The serving router declared a gateway replica dead (consecutive
    forward failures confirmed by a grpc.health.v1 probe, or the probe
    loop itself); its keyspace arcs fell to the next consistent-hash
    owners (serving/fleet.py)."""

    kind: ClassVar[str] = "serving_replica_dead"
    replica: str
    reason: str = ""
    failures: int = 0


@dataclass(frozen=True)
class ServingReplicaRecovered:
    """A dead or draining serving replica probed SERVING again and
    rejoined the router's hash ring."""

    kind: ClassVar[str] = "serving_replica_recovered"
    replica: str


@dataclass(frozen=True)
class ServingScaledUp:
    """The serving autoscaler booted a gateway replica (a ``serving_*``
    scale-up rule fired past its hold; driver/session.py). ``value`` is
    the rule's sampled value at the decision — the evidence trail next
    to the queue-occupancy profile."""

    kind: ClassVar[str] = "serving_scaled_up"
    replica: str
    replicas: int = 0
    rule: str = ""
    value: float = 0.0


@dataclass(frozen=True)
class ServingScaledDown:
    """The serving autoscaler drained a gateway replica back out of the
    fleet (scale-down rule fired, floor ``serving.fleet.min_replicas``
    respected)."""

    kind: ClassVar[str] = "serving_scaled_down"
    replica: str
    replicas: int = 0
    rule: str = ""
    value: float = 0.0


@dataclass(frozen=True)
class ControllerFailover:
    """The warm standby promoted itself to controller: the primary's WAL
    tail went stale, ``probe_failures`` consecutive grpc.health.v1
    probes confirmed it down, and the standby restored the replicated
    round state and started serving on its own pinned port
    (controller/__main__.py ``--standby``). Also emitted by the driver
    when it hands the federation's controller endpoint over to the
    promoted standby."""

    kind: ClassVar[str] = "controller_failover"
    role: str            # "standby" (promotion) | "driver" (handoff)
    host: str = ""
    port: int = 0
    round: int = 0
    learners: int = 0
    wal_records: int = 0
    promote_s: float = 0.0
    reason: str = ""


@dataclass(frozen=True)
class RecompileStorm:
    """One jitted function recompiled ``count`` times inside
    ``window_s`` seconds (telemetry/runtime.py): its abstract input
    signature keeps changing — unpadded shapes, an LRU bound too small
    for the live working set, or a Python-side cache miss — and every
    recompile stalls the caller for the full XLA compile. Muted per
    function for one window after firing."""

    kind: ClassVar[str] = "jax_recompile_storm"
    fn: str
    count: int = 0
    window_s: float = 0.0
    last_sig: str = ""


@dataclass(frozen=True)
class SliceAggregatorLost:
    """A slice aggregator process stopped answering (consecutive RPC
    failures confirmed by a grpc.health.v1 probe); its cohort slice is
    about to re-home (aggregation/distributed.py)."""

    kind: ClassVar[str] = "slice_aggregator_lost"
    slice: str
    failures: int = 0


@dataclass(frozen=True)
class SliceRehomed:
    """A dead slice aggregator's cohort slice re-homed mid-round: its
    spooled uplinks were recovered and its learners re-pointed at a
    surviving aggregator (``target=<slice>``) or folded directly at the
    root (``target="root"``) — the round completes without it."""

    kind: ClassVar[str] = "slice_rehomed"
    slice: str
    target: str
    round: int = 0
    recovered: int = 0
    lost: int = 0
    reason: str = ""


@dataclass(frozen=True)
class SecureSettlement:
    """The root settled a masked round (secure/recovery.py): the
    contributor set was reconciled against the dispatched mask-party
    cohort, dropout residuals (if any) were subtracted, and the sum
    decoded to the plain community payload. ``tier`` names which masked
    plane fed the root: ``stream`` (fold-on-arrival), ``slice``
    (distributed partial folds) or ``store`` (the in-process path)."""

    kind: ClassVar[str] = "secure_settlement"
    round: int
    contributors: int = 0
    dropped: int = 0
    recovered: bool = False
    tier: str = ""
    duration_ms: float = 0.0


@dataclass(frozen=True)
class SecureMasksRecovered:
    """A surviving learner disclosed the dropped parties' residual masks
    (seed-share disclosure through the quorum/deadline expiry path):
    ``survivor`` recomputed Σ±stream(i, d) for every dropped d so the
    partial sum unmasks to exactly the survivors' sum — the dropped
    payloads are settled OUT, never silently folded in."""

    kind: ClassVar[str] = "secure_masks_recovered"
    round: int
    survivor: str = ""
    surviving: int = 0
    dropped: int = 0


EVENT_TYPES: Dict[str, type] = {
    cls.kind: cls
    for cls in (LearnerJoined, LearnerLost, RoundStarted, TaskDispatched,
                TaskCompleted, RetryScheduled, FaultInjected, EpochChanged,
                AggregationDone, FailoverBegan, UpdateAnomalous,
                RoundHealth, LearnerQuarantined, DispatchRetried,
                RoundHalted, VersionRegistered, VersionPromoted,
                VersionRolledBack, ServingSwapped, AlertFiring,
                AlertResolved, FabricPeerStale, FabricPeerRecovered,
                SliceAggregatorLost, SliceRehomed, ServingReplicaDead,
                ServingReplicaRecovered, ServingScaledUp,
                ServingScaledDown, ControllerFailover, RecompileStorm,
                SecureSettlement, SecureMasksRecovered)
}


# --------------------------------------------------------------------- #
# journal
# --------------------------------------------------------------------- #

class Journal:
    """Bounded ring of event records + optional JSONL sink. A *record* is
    the emitted event's fields plus ``{seq, ts, kind}`` — plain dicts so
    the ring tail serializes straight into RPC snapshots and bundles."""

    def __init__(self):
        self.enabled = True
        self.service = ""
        self._ring: "collections.deque[dict]" = collections.deque(
            maxlen=DEFAULT_RING_SIZE)
        self._seq = 0
        self._lock = threading.Lock()
        self._path = ""
        self._fh = None

    def configure(self, enabled: bool = True, service: str = "",
                  dir: str = "", ring_size: int = 0) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:  # pragma: no cover - close never critical
                    pass
                self._fh = None
            self.enabled = bool(enabled)
            self.service = service or self.service or "proc"
            if ring_size and ring_size != self._ring.maxlen:
                self._ring = collections.deque(self._ring,
                                               maxlen=int(ring_size))
            self._path = ""
            if enabled and dir:
                try:
                    os.makedirs(dir, exist_ok=True)
                except OSError as exc:
                    import logging
                    logging.getLogger("metisfl_tpu.telemetry").warning(
                        "event sink dir %r not creatable (%s); events stay "
                        "ring-only", dir, exc)
                    return
                self._path = os.path.join(
                    dir, f"{self.service}-{os.getpid()}-events.jsonl")

    def emit(self, event_cls: Type, **fields) -> Optional[dict]:
        """Construct + journal one event; returns the record, or None
        when the journal is disabled (the hot-path no-op)."""
        if not self.enabled:
            return None
        event = event_cls(**fields)  # typed validation at the call site
        record = {"kind": event.kind, "ts": round(time.time(), 6)}
        record.update(asdict(event))
        with self._lock:
            self._seq += 1
            record["seq"] = self._seq
            self._ring.append(record)
            path = self._path
        if path:
            self._sink(record)
        return record

    def _sink(self, record: dict) -> None:
        line = json.dumps(record, default=str) + "\n"
        with self._lock:
            try:
                if self._fh is None:
                    if not self._path:
                        return
                    self._fh = open(self._path, "a", buffering=1)
                self._fh.write(line)
            except OSError:
                # a torn sink (deleted dir, full disk) must never take an
                # instrumented code path down with it — stop persisting
                self._path = ""
                self._fh = None

    def set_ring_size(self, ring_size: int) -> None:
        """Resize the ring without touching the sink configuration (the
        in-process federation honors ``events.ring_size`` while leaving
        any host-configured sink alone)."""
        with self._lock:
            if ring_size and ring_size != self._ring.maxlen:
                self._ring = collections.deque(self._ring,
                                               maxlen=int(ring_size))

    def tail(self, n: int = 0) -> List[dict]:
        """The last ``n`` records (0 = the whole ring), oldest first."""
        with self._lock:
            records = list(self._ring)
        return records[-n:] if n > 0 else records

    def tail_since(self, seq: int, limit: int = 0) -> List[dict]:
        """Records with ``seq > cursor`` (oldest first) — the fleet
        fabric's cursor pull (telemetry/fabric.py). A cursor older than
        the ring tail silently skips the evicted records; the JSONL sink
        keeps the full history."""
        with self._lock:
            records = [r for r in self._ring if r.get("seq", 0) > seq]
        return records[:limit] if limit > 0 else records

    def flush(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.flush()

    def reset(self) -> None:
        """Drop the ring + seq counter (tests)."""
        with self._lock:
            self._ring.clear()
            self._seq = 0


_JOURNAL = Journal()


def journal() -> Journal:
    return _JOURNAL


def configure(enabled: bool = True, service: str = "", dir: str = "",
              ring_size: int = 0) -> None:
    _JOURNAL.configure(enabled=enabled, service=service, dir=dir,
                       ring_size=ring_size)


def set_enabled(value: bool) -> None:
    _JOURNAL.enabled = bool(value)


def enabled() -> bool:
    return _JOURNAL.enabled


def emit(event_cls: Type, **fields) -> Optional[dict]:
    """Module-level emit: ``events.emit(events.RoundStarted, round=3)``.
    One attribute check when the journal is off."""
    if not _JOURNAL.enabled:
        return None
    return _JOURNAL.emit(event_cls, **fields)


def tail(n: int = 0) -> List[dict]:
    return _JOURNAL.tail(n)


def tail_since(seq: int, limit: int = 0) -> List[dict]:
    return _JOURNAL.tail_since(seq, limit=limit)


def flush() -> None:
    _JOURNAL.flush()


def event_path() -> str:
    """The JSONL file this process appends events to ('' = ring-only)."""
    return _JOURNAL._path


def format_record(record: Dict[str, Any], t0: Optional[float] = None) -> str:
    """One human line per record (status CLI + post-mortem viewer):
    ``+12.345s #17 task_dispatched learner_id=L0 task_id=ab12``."""
    ts = float(record.get("ts", 0.0))
    rel = f"+{ts - t0:8.3f}s" if t0 is not None else (
        time.strftime("%H:%M:%S", time.localtime(ts)))
    seq = record.get("seq", "?")
    kind = record.get("kind", "?")
    skip = {"ts", "seq", "kind"}
    fields = " ".join(f"{k}={v}" for k, v in record.items()
                      if k not in skip and v not in ("", None))
    return f"{rel}  #{seq:<5} {kind:<18} {fields}".rstrip()
