"""Federation-wide trace spans with a process-local JSONL sink.

A span is a named, timed interval with a trace id shared by every span in
one logical operation (a federation round), a span id of its own, and its
parent's span id — enough to stitch controller → learner → aggregation
into one tree after the fact (rooted at the controller's round span; the
driver collects the sink files rather than opening spans). Spans are:

- cheap: ids are ``os.urandom`` hex, timestamps are ``time.time()``/
  ``perf_counter``; a disabled tracer hands out one shared no-op span;
- cross-thread: the active span context lives in a ``contextvars``
  variable for same-thread nesting, and is passed EXPLICITLY wherever work
  hops threads (the controller's scheduling executor, the learner's train
  thread) — never inferred across a pool boundary;
- cross-process: :func:`outbound_metadata` / :func:`extract` carry the
  context over gRPC metadata (key ``metisfl-trace-ctx``) in a
  W3C-traceparent-style frame (``00-<trace_id>-<span_id>-01``), so a
  learner's train span parents under the controller round span that
  dispatched it;
- deterministic at the root: the controller derives the round trace id
  from its round serial (:func:`round_trace_id`) and serving clients
  derive theirs from the request id (:func:`request_trace_id`), so the
  causal analyzer (telemetry/causal.py) can name a round's or request's
  trace without a join table.

Finished spans append one JSON line to ``<dir>/<service>-<pid>.jsonl``
(per-process file: concurrent federation processes on one host must not
interleave writes). ``python -m metisfl_tpu.telemetry`` renders the tree.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import hashlib
import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Optional, Tuple

METADATA_KEY = "metisfl-trace-ctx"

# Finished-span ring capacity (fleet-fabric cursor pulls,
# telemetry/fabric.py): bounded per process; 0 disables the ring (the
# ``telemetry.fabric.enabled=false`` opt-out path — span recording then
# costs one attribute check over today's sink-only behavior).
DEFAULT_SPAN_RING = 4096

_CURRENT: "contextvars.ContextVar[Optional[SpanContext]]" = \
    contextvars.ContextVar("metisfl_tpu_trace_ctx", default=None)

# sentinel: "parent not given — use the calling context's active span"
_USE_CURRENT = object()


@dataclass(frozen=True)
class SpanContext:
    """The propagatable identity of a span: enough to parent children
    anywhere — another thread, another process, another host."""

    trace_id: str
    span_id: str

    def to_wire(self) -> str:
        # W3C-traceparent framing: version 00, sampled flag 01. Trace and
        # span ids are hex (never contain "-"), so the frame splits
        # unambiguously.
        return f"00-{self.trace_id}-{self.span_id}-01"

    @classmethod
    def from_wire(cls, value: str) -> Optional["SpanContext"]:
        parts = value.split("-")
        if len(parts) == 4:
            _version, trace_id, span_id, _flags = parts
            if trace_id and span_id:
                return cls(trace_id=trace_id, span_id=span_id)
            return None
        # pre-traceparent peers framed the context as "trace/span" —
        # tolerated so a mixed-version fleet keeps stitching
        trace_id, sep, span_id = value.partition("/")
        if not sep or not trace_id or not span_id:
            return None
        return cls(trace_id=trace_id, span_id=span_id)


def round_trace_id(serial: int) -> str:
    """Deterministic 32-hex trace id for one federation round dispatch:
    the controller's round serial, zero-extended. Every hop the round
    causes — dispatch, train, uplink, ingest, slice fold, finalize —
    shares it, so ``perf --critical-path --round N`` selects the round's
    causal tree by id, not by timestamp heuristics."""
    return f"{int(serial) & ((1 << 128) - 1):032x}"


def request_trace_id(request_id: str) -> str:
    """Deterministic 32-hex trace id for one serving request (router →
    replica → decode-slot chain), derived from the request id. The raw
    request id travels as a span attribute; the hash keeps the trace id
    fixed-width for arbitrary caller-chosen ids."""
    digest = hashlib.sha256(b"metisfl-req:"
                            + str(request_id).encode("utf-8", "replace"))
    return digest.hexdigest()[:32]


class Span:
    """A timed interval. Use as a context manager, or call :meth:`end`
    explicitly for spans that outlive one scope (the controller's round
    span stays open across many scheduling-executor invocations)."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "attrs",
                 "start", "_t0", "_duration_ms", "_tracer", "__weakref__")

    def __init__(self, tracer: "_Tracer", name: str,
                 parent: Optional[SpanContext],
                 attrs: Optional[Dict[str, Any]] = None,
                 trace_id: Optional[str] = None):
        self.name = name
        # a parent's trace wins; an explicit trace_id names a NEW root
        # trace deterministically (round serial / serving request id)
        self.trace_id = (parent.trace_id if parent
                         else (trace_id or os.urandom(16).hex()))
        self.span_id = os.urandom(8).hex()
        self.parent_id = parent.span_id if parent else ""
        self.attrs: Dict[str, Any] = dict(attrs or {})
        self.start = time.time()
        self._t0 = time.perf_counter()
        self._duration_ms: Optional[float] = None
        self._tracer = tracer

    # -- identity ---------------------------------------------------------
    def context(self) -> SpanContext:
        return SpanContext(trace_id=self.trace_id, span_id=self.span_id)

    @property
    def duration_ms(self) -> float:
        """Elapsed so far, or the final duration once ended."""
        if self._duration_ms is not None:
            return self._duration_ms
        return (time.perf_counter() - self._t0) * 1e3

    # -- mutation ---------------------------------------------------------
    def set_attr(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def end(self) -> float:
        """Finish the span (idempotent) and write it to the sink."""
        if self._duration_ms is None:
            self._duration_ms = (time.perf_counter() - self._t0) * 1e3
            self._tracer._closed(self)
            self._tracer._record(self)
        return self._duration_ms

    # -- scoping ----------------------------------------------------------
    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None and "error" not in self.attrs:
            self.attrs["error"] = f"{type(exc).__name__}: {exc}"
        self.end()

    @contextlib.contextmanager
    def activate(self):
        """Make this span the calling context's active span, so nested
        ``span()`` calls and outbound RPCs parent under it."""
        token = _CURRENT.set(self.context())
        try:
            yield self
        finally:
            _CURRENT.reset(token)


class _NullSpan:
    """Disabled-tracer span: no ids, no sink, no context propagation —
    but it still MEASURES, because span durations are authoritative for
    lineage fields (RoundMetadata aggregation/phase timings) that the
    pre-telemetry code always recorded. Opting telemetry out must not
    zero ``experiment.json`` timings."""

    __slots__ = ("_t0", "_duration_ms")
    name = ""
    trace_id = ""
    span_id = ""
    parent_id = ""
    attrs: Dict[str, Any] = {}

    def __init__(self):
        self._t0 = time.perf_counter()
        self._duration_ms: Optional[float] = None

    @property
    def duration_ms(self) -> float:
        if self._duration_ms is not None:
            return self._duration_ms
        return (time.perf_counter() - self._t0) * 1e3

    def context(self) -> None:
        return None

    def set_attr(self, key: str, value: Any) -> None:
        pass

    def end(self) -> float:
        if self._duration_ms is None:
            self._duration_ms = (time.perf_counter() - self._t0) * 1e3
        return self._duration_ms

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.end()

    @contextlib.contextmanager
    def activate(self):
        yield self


class _Tracer:
    def __init__(self):
        self.enabled = True
        self.service = ""
        self.dir = ""
        self._path = ""
        self._fh = None
        self._lock = threading.Lock()
        # live (un-ended) spans, weakly held so an abandoned span can
        # still be collected: the flight recorder's "what was open when
        # the process died" snapshot (telemetry/postmortem.py)
        self._open: "Dict[int, Any]" = {}
        # finished-span ring with a process-monotonic seq per record:
        # the fleet fabric's cursor-pull source (telemetry/fabric.py).
        # None (the default) = ring disabled — processes that never arm
        # the fabric (apply_config / fabric.configure, or lazily on the
        # first CollectTelemetry pull) keep the pre-fabric record cost:
        # one attribute check when there is no sink either.
        self._ring: Optional["collections.deque"] = None
        self._ring_seq = 0

    def _opened(self, span: "Span") -> None:
        import weakref
        with self._lock:
            # Bound dead-ref growth from spans abandoned without end():
            # no weakref GC callback (it could re-enter this non-reentrant
            # lock from a collection triggered while holding it), so prune
            # lazily once the map grows past a generous live-span count.
            if len(self._open) > 512:
                self._open = {k: r for k, r in self._open.items()
                              if r() is not None}
            self._open[id(span)] = weakref.ref(span)

    def _closed(self, span: "Span") -> None:
        with self._lock:
            self._open.pop(id(span), None)

    def open_spans(self) -> list:
        """Snapshot of live spans as records (ages keep ticking — the
        caller sees elapsed-so-far durations). Also prunes entries whose
        span was garbage-collected without ``end()``."""
        out = []
        with self._lock:
            dead = [k for k, r in self._open.items() if r() is None]
            for k in dead:
                del self._open[k]
            refs = list(self._open.values())
        for ref in refs:
            span = ref()
            if span is None or span._duration_ms is not None:
                continue
            record = {
                "trace": span.trace_id,
                "span": span.span_id,
                "parent": span.parent_id,
                "name": span.name,
                "service": self.service,
                "start": round(span.start, 6),
                "open_ms": round(span.duration_ms, 3),
            }
            if span.attrs:
                record["attrs"] = dict(span.attrs)
            out.append(record)
        out.sort(key=lambda r: r["start"])
        return out

    def configure(self, enabled: bool = True, service: str = "",
                  dir: str = "") -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:  # pragma: no cover - close never critical
                    pass
                self._fh = None
            self.enabled = bool(enabled)
            self.service = service or self.service or "proc"
            self.dir = dir
            self._path = ""
            self._open.clear()  # a reconfigure starts a fresh lifetime
            if self._ring is not None:
                # fresh lifetime for the fabric ring too: the seq counter
                # keeps running (cursors held by collectors stay
                # monotone within this process incarnation)
                self._ring.clear()
            if enabled and dir:
                try:
                    os.makedirs(dir, exist_ok=True)
                except OSError as exc:
                    # an uncreatable sink dir (remote learner with the
                    # driver's local path, read-only mount) must degrade
                    # to unpersisted spans, not kill the process
                    import logging
                    logging.getLogger("metisfl_tpu.telemetry").warning(
                        "trace sink dir %r not creatable (%s); spans "
                        "will not be persisted", dir, exc)
                    return
                self._path = os.path.join(
                    dir, f"{self.service}-{os.getpid()}.jsonl")

    def _record(self, span: Span) -> None:
        ring = self._ring
        if not self._path and ring is None:
            return
        record = {
            "trace": span.trace_id,
            "span": span.span_id,
            "parent": span.parent_id,
            "name": span.name,
            "service": self.service,
            "pid": os.getpid(),
            "start": round(span.start, 6),
            "dur_ms": round(span._duration_ms or 0.0, 3),
        }
        if span.attrs:
            record["attrs"] = dict(span.attrs)
        if ring is not None:
            with self._lock:
                self._ring_seq += 1
                ring.append({**record, "seq": self._ring_seq})
        if not self._path:
            return
        line = json.dumps(record, default=str) + "\n"
        with self._lock:
            try:
                if self._fh is None:
                    if not self._path:
                        return
                    self._fh = open(self._path, "a", buffering=1)
                self._fh.write(line)
            except OSError:
                # a torn sink (deleted dir, full disk) must never take a
                # traced code path down with it — stop persisting
                self._path = ""
                self._fh = None

    def configure_ring(self, size: int) -> None:
        """(Re)size the finished-span ring; 0 disables it (and with it
        fabric span pulls from this process). Existing records are kept
        on a resize, dropped on disable."""
        with self._lock:
            if size <= 0:
                self._ring = None
            elif self._ring is None or self._ring.maxlen != size:
                self._ring = collections.deque(self._ring or (),
                                               maxlen=int(size))

    def spans_since(self, cursor: int, limit: int = 0
                    ) -> Tuple[List[dict], int, int]:
        """``(records, new_cursor, lost)``: finished-span records with
        ``seq > cursor`` (oldest first), the new cursor, and how many
        records between the cursor and the ring tail were already
        EVICTED (bounded memory wins over total recall — but the loss is
        reported, never silent; the JSONL sink keeps the full history)."""
        with self._lock:
            if self._ring is None:
                return [], cursor, 0
            records = [r for r in self._ring if r["seq"] > cursor]
            new_cursor = self._ring_seq
            oldest = self._ring[0]["seq"] if self._ring else \
                self._ring_seq + 1
        lost = max(0, oldest - 1 - cursor) if cursor < oldest - 1 else 0
        if limit > 0:
            records = records[:limit]
            if records:
                new_cursor = records[-1]["seq"]
        return records, max(new_cursor, cursor), lost

    def flush(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.flush()


_TRACER = _Tracer()


def configure(enabled: bool = True, service: str = "", dir: str = "") -> None:
    """(Re)configure the process tracer. ``dir=""`` keeps spans in-memory
    only (ids and durations still work — instrumentation that feeds
    RoundMetadata does not need a sink)."""
    _TRACER.configure(enabled=enabled, service=service, dir=dir)


def set_enabled(value: bool) -> None:
    """Flip tracing on/off while keeping the configured service + sink
    dir (a disabled tracer remembers where it was writing)."""
    _TRACER.configure(enabled=value, service=_TRACER.service,
                      dir=_TRACER.dir)


def flush() -> None:
    _TRACER.flush()


def trace_path() -> str:
    """The JSONL file this process appends spans to ('' = no sink)."""
    return _TRACER._path


def open_spans() -> list:
    """Live (un-ended) spans as records — the flight recorder's
    "what was in flight" snapshot (telemetry/postmortem.py)."""
    return _TRACER.open_spans()


def configure_ring(size: int) -> None:
    """Size the finished-span ring backing fabric cursor pulls
    (0 disables; telemetry/fabric.py)."""
    _TRACER.configure_ring(size)


def spans_since(cursor: int, limit: int = 0) -> Tuple[List[dict], int, int]:
    """``(records, new_cursor, lost)`` — finished spans newer than
    ``cursor``, the new cursor, and the evicted-record count (the
    ``CollectTelemetry`` span source, telemetry/fabric.py)."""
    return _TRACER.spans_since(cursor, limit=limit)


def span(name: str, parent: Any = _USE_CURRENT,
         attrs: Optional[Dict[str, Any]] = None,
         trace_id: Optional[str] = None):
    """Open a span. ``parent``: omitted → the calling context's active
    span; ``None`` → a new root trace; a :class:`Span` or
    :class:`SpanContext` → explicit parent (the cross-thread form).
    ``trace_id`` names a root trace deterministically (ignored when a
    parent supplies one)."""
    if not _TRACER.enabled:
        return _NullSpan()
    if parent is _USE_CURRENT:
        parent = _CURRENT.get()
    elif isinstance(parent, (Span, _NullSpan)):
        parent = parent.context()
    sp = Span(_TRACER, name, parent, attrs, trace_id=trace_id)
    # only factory-made spans are tracked as open: event() spans below are
    # born already-finished and must never show up in open_spans()
    _TRACER._opened(sp)
    return sp


def event(name: str, duration_s: float, parent: Any = _USE_CURRENT,
          attrs: Optional[Dict[str, Any]] = None) -> None:
    """Record an already-measured interval as a completed span (for call
    sites that timed themselves, e.g. the codec hot path)."""
    if not _TRACER.enabled:
        return
    if parent is _USE_CURRENT:
        parent = _CURRENT.get()
    elif isinstance(parent, (Span, _NullSpan)):
        parent = parent.context()
    sp = Span(_TRACER, name, parent, attrs)
    sp.start = time.time() - duration_s
    sp._duration_ms = duration_s * 1e3
    _TRACER._record(sp)


def current_context() -> Optional[SpanContext]:
    if not _TRACER.enabled:
        return None
    return _CURRENT.get()


@contextlib.contextmanager
def use_context(ctx: Optional[SpanContext]):
    """Activate an explicit (e.g. wire-extracted) context."""
    token = _CURRENT.set(ctx)
    try:
        yield ctx
    finally:
        _CURRENT.reset(token)


def outbound_metadata() -> Optional[Tuple[Tuple[str, str], ...]]:
    """gRPC metadata carrying the active span context (None when there is
    nothing to propagate — grpc treats ``metadata=None`` as absent)."""
    ctx = current_context()
    if ctx is None:
        return None
    return ((METADATA_KEY, ctx.to_wire()),)


def extract(metadata: Optional[Iterable]) -> Optional[SpanContext]:
    """Span context from gRPC invocation metadata (None when absent)."""
    if not metadata:
        return None
    for item in metadata:
        key = getattr(item, "key", None) or (item[0] if item else None)
        if key == METADATA_KEY:
            value = getattr(item, "value", None) or item[1]
            return SpanContext.from_wire(str(value))
    return None
