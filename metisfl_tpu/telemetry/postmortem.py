"""Flight recorder: post-mortem bundles for crashed federation processes.

PR 2's failover recovers from a controller crash but leaves no record of
what the process was *doing* when it died — the round that was in
flight, the tasks that were dispatched, the spans that never closed.
This module dumps exactly that, as one JSON bundle per incident, into a
directory the driver defaults to ``<workdir>/postmortem/``:

- the event-journal ring tail (:mod:`metisfl_tpu.telemetry.events`) —
  the pre-crash timeline;
- the metrics registry's text exposition — a last scrape nobody got;
- the still-open trace spans (a round span with no end IS the smoking
  gun for "died mid-round");
- process identity (service, pid, reason, wall-clock) and the federation
  config hash, so bundles from different incarnations are tellable apart.

Bundles are written on: an unhandled exception (``sys.excepthook`` +
``threading.excepthook``, installed by :func:`configure`), a chaos
``kill`` (the injector dumps before ``os._exit``), and a driver-side
failover relaunch (the driver dumps its own bundle as it restarts the
controller). Render them with
``python -m metisfl_tpu.telemetry --postmortem <dir>``.

Everything here is best-effort by construction: a flight recorder that
can crash the plane is worse than none, so :func:`dump` never raises.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger("metisfl_tpu.telemetry")

SCHEMA_VERSION = 1


class _Recorder:
    def __init__(self):
        self.dir = ""
        self.service = ""
        self.config_hash = ""
        self._lock = threading.Lock()
        self._seq = 0
        self._hooks_installed = False
        self._dumping = False

    def configure(self, dir: str, service: str = "",
                  config_hash: str = "", install_hooks: bool = True) -> None:
        self.dir = dir or ""
        self.service = service or self.service or "proc"
        self.config_hash = config_hash or self.config_hash
        if self.dir:
            try:
                os.makedirs(self.dir, exist_ok=True)
            except OSError as exc:
                logger.warning("postmortem dir %r not creatable (%s); "
                               "flight recorder disabled", dir, exc)
                self.dir = ""
                return
            if install_hooks:
                self._install_hooks()

    def _install_hooks(self) -> None:
        """Wrap the unhandled-exception hooks (idempotent): dump a bundle,
        then delegate to whatever hook was installed before us."""
        with self._lock:
            if self._hooks_installed:
                return
            self._hooks_installed = True
        prev_sys = sys.excepthook
        prev_thread = threading.excepthook

        def _sys_hook(exc_type, exc, tb):
            self.dump(f"crash_{exc_type.__name__}",
                      extra={"error": f"{exc_type.__name__}: {exc}"})
            prev_sys(exc_type, exc, tb)

        def _thread_hook(args):
            if args.exc_type is not SystemExit:
                self.dump(f"crash_{args.exc_type.__name__}",
                          extra={"error": f"{args.exc_type.__name__}: "
                                          f"{args.exc_value}",
                                 "thread": getattr(args.thread, "name", "?")})
            prev_thread(args)

        sys.excepthook = _sys_hook
        threading.excepthook = _thread_hook

    def dump(self, reason: str, extra: Optional[Dict[str, Any]] = None
             ) -> Optional[str]:
        """Write one bundle; returns its path, or None when unconfigured
        or the write failed. Never raises; re-entrancy-guarded (a dump
        that crashes must not recurse through the excepthook)."""
        if not self.dir:
            return None
        with self._lock:
            if self._dumping:
                return None
            self._dumping = True
            self._seq += 1
            seq = self._seq
        try:
            return self._write(reason, seq, extra)
        except Exception:  # noqa: BLE001 - best-effort by contract
            logger.exception("postmortem dump failed")
            return None
        finally:
            with self._lock:
                self._dumping = False

    def _write(self, reason: str, seq: int,
               extra: Optional[Dict[str, Any]]) -> str:
        from metisfl_tpu.telemetry import events as _events
        from metisfl_tpu.telemetry import metrics as _metrics
        from metisfl_tpu.telemetry import trace as _trace

        bundle: Dict[str, Any] = {
            "schema": SCHEMA_VERSION,
            "service": self.service,
            "pid": os.getpid(),
            "reason": reason,
            "time": round(time.time(), 6),
            "config_hash": self.config_hash,
            "events": _events.tail(),
            "open_spans": _trace.open_spans(),
            "metrics": _metrics.registry().render(),
        }
        try:
            # latest RoundProfile tail (performance observatory): where
            # the last rounds' time and bytes went, readable post-crash
            from metisfl_tpu.telemetry import profile as _profile

            profiles = _profile.tail(3)
            if profiles:
                bundle["profiles"] = profiles
        except Exception:  # noqa: BLE001 - best-effort by contract
            pass
        try:
            # profiler at death (telemetry/prof.py): the top frames and
            # lock-contention rollup of the process's last moments —
            # what it was BURNING time on, next to the open spans that
            # say what it was waiting for
            from metisfl_tpu.telemetry import prof as _prof

            prof_snapshot = _prof.postmortem_snapshot()
            if prof_snapshot is not None:
                bundle["prof"] = prof_snapshot
        except Exception:  # noqa: BLE001 - best-effort by contract
            pass
        try:
            # accelerator runtime at death (telemetry/runtime.py): the
            # compile/recompile rollup + last memory sample — a crash
            # mid recompile-storm or post HBM-climb names itself here
            from metisfl_tpu.telemetry import runtime as _runtime

            runtime_snapshot = _runtime.postmortem_snapshot()
            if runtime_snapshot is not None:
                bundle["runtime"] = runtime_snapshot
        except Exception:  # noqa: BLE001 - best-effort by contract
            pass
        try:
            # alerts at death (telemetry/alerts.py): the firing page
            # nobody got — which rules were active, for how long
            from metisfl_tpu.telemetry import alerts as _alerts

            alert_summary = _alerts.active_summary()
            if alert_summary is not None:
                bundle["alerts"] = alert_summary
        except Exception:  # noqa: BLE001 - best-effort by contract
            pass
        if extra:
            bundle["extra"] = extra
        safe_reason = "".join(c if (c.isalnum() or c in "_-") else "_"
                              for c in reason)[:64]
        name = f"{self.service}-{os.getpid()}-{seq}-{safe_reason}.json"
        path = os.path.join(self.dir, name)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(bundle, f, default=str)
        os.replace(tmp, path)  # atomic: never a torn bundle
        # the bundle snapshots the ring; flush the sinks too so the JSONL
        # files agree with the last thing the recorder saw
        _events.flush()
        _trace.flush()
        logger.warning("post-mortem bundle written: %s (reason=%s)",
                       path, reason)
        return path


_RECORDER = _Recorder()


def configure(dir: str, service: str = "", config_hash: str = "",
              install_hooks: bool = True) -> None:
    """Arm the flight recorder for this process. ``install_hooks`` wraps
    ``sys.excepthook``/``threading.excepthook`` so unhandled crashes dump
    automatically; chaos-kill and failover call :func:`dump` directly."""
    _RECORDER.configure(dir, service=service, config_hash=config_hash,
                        install_hooks=install_hooks)


def dump(reason: str, extra: Optional[Dict[str, Any]] = None
         ) -> Optional[str]:
    return _RECORDER.dump(reason, extra=extra)


def armed() -> bool:
    return bool(_RECORDER.dir)


def recorder_dir() -> str:
    return _RECORDER.dir


def load_bundles(paths: List[str]) -> List[dict]:
    """Bundle dicts from explicit .json files and/or directories of them
    (unreadable/foreign files are skipped — a postmortem dir may hold a
    half-written .tmp from the crash itself)."""
    import glob as _glob

    bundles: List[dict] = []
    for path in paths:
        files = (sorted(_glob.glob(os.path.join(path, "*.json")))
                 if os.path.isdir(path) else [path])
        for name in files:
            try:
                with open(name) as f:
                    data = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
            if isinstance(data, dict) and data.get("schema"):
                data["_path"] = name
                bundles.append(data)
    bundles.sort(key=lambda b: b.get("time", 0.0))
    return bundles
