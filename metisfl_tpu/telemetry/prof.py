"""Continuous profiling plane: fleet-wide stack sampling + lock telemetry.

PR 6's round profiles tile wall-clock into *phases* and the fleet fabric
assembles *spans* across processes — but neither can say which frames,
locks, or queues the milliseconds inside a phase actually go to.
Warehouse-scale practice (Google-Wide Profiling, Ren et al., IEEE Micro
2010; Kanev et al., ISCA 2015) shows that an always-on, low-overhead
sampling layer across the fleet is what turns perf work from guessing
into diffing. This module is that layer, native to the existing planes:

- **Sampling profiler** — a daemon thread walks ``sys._current_frames()``
  at ``telemetry.prof.hz`` (default 67 Hz, deliberately off-harmonic so
  periodic workloads cannot hide between ticks) and folds every thread's
  stack into a bounded, mergeable folded-stack table: a
  :class:`~metisfl_tpu.telemetry.sketch.SpaceSaving` tracker over
  ``root;frame;...;leaf`` strings (top-``budget`` stacks keep exact
  labels, the crowd collapses into the eviction floor — PR 9's posture,
  so fleet profiles stay O(budget) like everything else).

- **Lock-contention telemetry** — :func:`lock`/:func:`rlock` return
  instrumented wrappers adopted by the hot locks that already exist
  (controller registry, store lineage/LRU, ingest pipeline, slice
  reducer, serving micro-batch queue, fleet collector): every contended
  acquire records its wait into the ``lock_wait_seconds{site}``
  histogram and ``lock_contention_total{site}``, plus a per-site
  acquisitions/wait rollup served with the profile. Uncontended acquires
  pay one non-blocking try; ``threading.Condition`` over a wrapped lock
  re-acquires through the untimed path (a batcher idling on ``wait()``
  is queue time, not lock contention).

- **Fleet transport** — the profile rides the existing
  ``CollectTelemetry`` reply as a ``prof`` section (epoch-consistent
  with the fabric cursors), so the :class:`FleetCollector` holds a
  per-peer folded profile and ``status --fleet`` can print each peer's
  top frame and hottest lock. Each :class:`RoundProfile` additionally
  carries the per-round folded-stack *delta*, making "which frames grew
  when rounds/s dropped" answerable per round.

Rendering lives in ``python -m metisfl_tpu.perf``: ``--flame`` exports
collapsed stacks (speedscope / FlameGraph compatible) plus a terminal
self/total table, and ``--flame-diff A B`` diffs two captures or rounds.

Opt-out ``telemetry.prof.enabled=false``: the sampler never starts, the
lock factories return raw ``threading.Lock``/``RLock`` objects (the hot
paths carry zero wrapper cost), and the ``CollectTelemetry`` section is
an ``{"enabled": false}`` stub. The profiler's own overhead is gated in
CI (``python -m metisfl_tpu.telemetry --prof-smoke``, wired into
scripts/chaos_smoke.sh): the bench round loop with profiling on must
stay within the pinned bound of the profiling-off run.
"""

from __future__ import annotations

import json
import logging
import statistics
import sys
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional

from metisfl_tpu.telemetry import metrics as _metrics
from metisfl_tpu.telemetry.sketch import SpaceSaving

logger = logging.getLogger("metisfl_tpu.telemetry.prof")

# defaults (config/federation.py ProfConfig mirrors them, test-pinned).
# 67 Hz is off-harmonic with the common 1/10/100 ms periods a federation
# round is built from, so periodic work cannot systematically dodge (or
# monopolize) the sampling ticks — the GWP posture.
DEFAULT_HZ = 67.0
DEFAULT_BUDGET = 512
# folded stacks keep at most this many frames (leaf-most survive; a
# deeper stack gets a "_deep" root marker) so one recursive workload
# cannot blow the table's per-key size
MAX_STACK_DEPTH = 64

# metric families (telemetry/__init__.py re-exports them as M_*
# constants; catalog rows in docs/OBSERVABILITY.md)
SAMPLES_TOTAL = "prof_samples_total"
LOCK_WAIT_SECONDS = "lock_wait_seconds"
LOCK_CONTENTION_TOTAL = "lock_contention_total"

_REG = _metrics.registry()
_M_SAMPLES = _REG.counter(
    SAMPLES_TOTAL,
    "Thread stacks folded by the sampling profiler (one per live "
    "thread per tick)")
_M_LOCK_WAIT = _REG.histogram(
    LOCK_WAIT_SECONDS,
    "Wait time of CONTENDED acquires on instrumented locks, by site "
    "(uncontended acquires are counted locally, never observed here)",
    ("site",))
_M_LOCK_CONTENTION = _REG.counter(
    LOCK_CONTENTION_TOTAL,
    "Contended acquires on instrumented locks, by site", ("site",))

_PREFIX = "metisfl_tpu."


def _frame_name(frame) -> str:
    mod = frame.f_globals.get("__name__", "?") or "?"
    if mod.startswith(_PREFIX):
        mod = mod[len(_PREFIX):]
    return f"{mod}.{frame.f_code.co_name}"


def fold_frame(frame, max_depth: int = MAX_STACK_DEPTH) -> str:
    """One thread's stack as a ``root;...;leaf`` folded string (the
    collapsed-stack format speedscope/FlameGraph ingest)."""
    parts: List[str] = []
    while frame is not None and len(parts) < max_depth:
        parts.append(_frame_name(frame))
        frame = frame.f_back
    if frame is not None:
        parts.append("_deep")
    parts.reverse()
    return ";".join(parts)


# --------------------------------------------------------------------- #
# lock-contention telemetry
# --------------------------------------------------------------------- #

class _SiteStats:
    """Per-site rollup. Plain (racy) increments by design: these are
    statistics, and a CAS loop on every hot-lock acquire would be the
    overhead this plane exists to measure."""

    __slots__ = ("site", "acquisitions", "contentions", "wait_s_total",
                 "wait_s_max")

    def __init__(self, site: str):
        self.site = site
        self.acquisitions = 0
        self.contentions = 0
        self.wait_s_total = 0.0
        self.wait_s_max = 0.0

    def row(self) -> Dict[str, Any]:
        return {"acquisitions": int(self.acquisitions),
                "contentions": int(self.contentions),
                "wait_s_total": round(self.wait_s_total, 6),
                "wait_s_max": round(self.wait_s_max, 6)}


_SITES_LOCK = threading.Lock()
_SITES: Dict[str, _SiteStats] = {}
# site -> weakref to the most recently constructed wrapper (a TEST HOOK:
# the acceptance tests inject a lock-hold by fetching and holding the
# real object; production code never reads this)
_SITE_LOCKS: Dict[str, Any] = {}


def _site_stats(site: str) -> _SiteStats:
    with _SITES_LOCK:
        stats = _SITES.get(site)
        if stats is None:
            stats = _SITES[site] = _SiteStats(site)
        return stats


class _TimedLockBase:
    """Shared acquire instrumentation. The fast path is one non-blocking
    try; only a *contended* acquire pays for timestamps and the metric
    observation (so the uncontended hot path stays within the CI-gated
    overhead bound)."""

    __slots__ = ("_lock", "site", "_stats", "__weakref__")

    def __init__(self, lock, site: str):
        self._lock = lock
        self.site = site
        self._stats = _site_stats(site)
        with _SITES_LOCK:
            _SITE_LOCKS[site] = weakref.ref(self)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        st = self._stats
        if self._lock.acquire(False):
            st.acquisitions += 1
            return True
        if not blocking:
            return False
        t0 = time.perf_counter()
        ok = self._lock.acquire(True, timeout)
        wait = time.perf_counter() - t0
        st.contentions += 1
        st.wait_s_total += wait
        if wait > st.wait_s_max:
            st.wait_s_max = wait
        if ok:
            st.acquisitions += 1
        _M_LOCK_WAIT.observe(wait, site=self.site)
        _M_LOCK_CONTENTION.inc(site=self.site)
        return ok

    def release(self) -> None:
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self._lock.release()

    # threading.Condition protocol: wait()'s release/re-acquire cycle
    # runs UNTIMED — the time a consumer spends parked on a condition is
    # queue occupancy, not lock contention, and folding it in would
    # drown the real contention signal for every condition-backed queue
    def _release_save(self):
        self._lock.release()

    def _acquire_restore(self, state) -> None:
        self._lock.acquire()

    def _is_owned(self) -> bool:
        if self._lock.acquire(False):
            self._lock.release()
            return False
        return True


class _TimedLock(_TimedLockBase):
    __slots__ = ()

    def __init__(self, site: str):
        super().__init__(threading.Lock(), site)

    def locked(self) -> bool:
        return self._lock.locked()


class _TimedRLock(_TimedLockBase):
    __slots__ = ()

    def __init__(self, site: str):
        super().__init__(threading.RLock(), site)

    # reentrant acquires by the owner succeed on the non-blocking try,
    # so they never count as contention — exactly right

    def _is_owned(self) -> bool:
        return self._lock._is_owned()

    def _release_save(self):
        return self._lock._release_save()

    def _acquire_restore(self, state) -> None:
        self._lock._acquire_restore(state)


def lock(site: str):
    """An instrumented ``threading.Lock`` for a named site — or, with
    profiling disabled, a raw ``threading.Lock`` (the opt-out leaves
    every hot path at zero wrapper cost; one attribute check here at
    construction is all that remains)."""
    if not _STATE.enabled:
        return threading.Lock()
    return _TimedLock(site)


def rlock(site: str):
    """Reentrant variant of :func:`lock` (the controller registry)."""
    if not _STATE.enabled:
        return threading.RLock()
    return _TimedRLock(site)


def lock_sites() -> Dict[str, Dict[str, Any]]:
    """Per-site contention rollup, acquisition-ordered by wait time."""
    with _SITES_LOCK:
        stats = list(_SITES.values())
    return {st.site: st.row()
            for st in sorted(stats, key=lambda s: -s.wait_s_total)}


def lock_object(site: str):
    """The most recently constructed wrapper for a site (None when the
    site never minted one or it was collected) — the lock-hold TEST HOOK
    the acceptance criteria name; never used by production code."""
    with _SITES_LOCK:
        ref = _SITE_LOCKS.get(site)
    return ref() if ref is not None else None


# --------------------------------------------------------------------- #
# the sampler
# --------------------------------------------------------------------- #

class _Sampler:
    def __init__(self):
        self.hz = DEFAULT_HZ
        self.budget = DEFAULT_BUDGET
        self._table = SpaceSaving(capacity=DEFAULT_BUDGET)
        self._lock = threading.Lock()   # raw: the sampler must never
        #                                 recurse into its own telemetry
        self.samples = 0
        self.ticks = 0
        self.started_ts = 0.0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # lifecycle lock: ensure_started() races between parallel
        # CollectTelemetry handlers (two collectors' first pulls land on
        # the RPC pool concurrently) — without it both spawn a sampler
        # and every count doubles
        self._lifecycle = threading.Lock()

    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> None:
        with self._lifecycle:
            if self.running():
                return
            self._stop.clear()
            self.started_ts = time.time()
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="prof-sampler")
            self._thread.start()

    def stop(self) -> None:
        with self._lifecycle:
            self._stop.set()
            thread = self._thread
            if thread is not None:
                thread.join(timeout=2.0)
            self._thread = None

    def _loop(self) -> None:
        period = 1.0 / max(self.hz, 0.1)
        while not self._stop.wait(period):
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 - a profiler that can
                # crash the process is worse than none
                logger.exception("stack sample failed; sampler continues")

    def sample_once(self) -> int:
        """One sampling tick: fold every live thread's stack (except our
        own) into the table. Returns the number of stacks folded."""
        me = threading.get_ident()
        folded = [fold_frame(frame)
                  for tid, frame in sys._current_frames().items()
                  if tid != me]
        with self._lock:
            for stack in folded:
                self._table.offer(stack, 1.0)
            self.samples += len(folded)
            self.ticks += 1
        _M_SAMPLES.inc(len(folded))
        for hook in tuple(_TICK_HOOKS):
            try:
                hook()
            except Exception:  # noqa: BLE001 - a hook must never take
                # the sampler thread down
                logger.exception("sampler tick hook failed")
        return len(folded)

    def counts(self) -> Dict[str, float]:
        with self._lock:
            return {key: count for key, count, _e, _l in self._table.top(0)}

    def state(self) -> Dict[str, Any]:
        with self._lock:
            table = self._table.to_dict()
            samples, ticks = self.samples, self.ticks
        return {"enabled": True, "hz": self.hz, "budget": self.budget,
                "samples": samples, "ticks": ticks,
                "started": round(self.started_ts, 3),
                "running": self.running(),
                "stacks": table, "locks": lock_sites()}

    def reset(self) -> None:
        self.stop()
        with self._lock:
            self._table = SpaceSaving(capacity=self.budget)
            self.samples = 0
            self.ticks = 0
            self.started_ts = 0.0


class _State:
    def __init__(self):
        self.enabled = True   # always-on posture; apply_config re-arms


_STATE = _State()
_SAMPLER = _Sampler()
# other telemetry planes riding the sampler cadence (runtime.py's
# memory accounting); each hook self-gates its own frequency
_TICK_HOOKS: List[Callable[[], None]] = []


def register_tick_hook(fn: Callable[[], None]) -> None:
    """Piggyback ``fn`` on every sampler tick (~hz calls/s while the
    sampler runs). Idempotent per function; hooks must be cheap and
    exception-safe — a raising hook is logged and skipped, never fatal."""
    if fn not in _TICK_HOOKS:
        _TICK_HOOKS.append(fn)


def enabled() -> bool:
    return _STATE.enabled


def sampling() -> bool:
    """True while the sampler thread is live (the per-round delta hook
    gates on this so an unarmed process pays one call)."""
    return _SAMPLER.running()


def configure(enabled: bool = True, hz: float = 0.0,
              budget: int = 0) -> None:
    """(Re)arm the process profiler from ``telemetry.prof``: flips the
    lock factories, sizes the folded-stack table, and starts (or stops)
    the sampling thread. ``hz``/``budget`` of 0 keep the defaults."""
    _STATE.enabled = bool(enabled)
    if not enabled:
        _SAMPLER.stop()
        return
    hz = float(hz or 0.0) or DEFAULT_HZ
    budget = int(budget or 0) or DEFAULT_BUDGET
    restart = (_SAMPLER.running()
               and (hz != _SAMPLER.hz or budget != _SAMPLER.budget))
    if restart:
        _SAMPLER.stop()
    _SAMPLER.hz = hz
    if budget != _SAMPLER.budget:
        _SAMPLER.budget = budget
        with _SAMPLER._lock:
            fresh = SpaceSaving(capacity=budget)
            fresh.merge(_SAMPLER._table)
            _SAMPLER._table = fresh
    _SAMPLER.start()


def ensure_started() -> None:
    """Lazy arming (the span-ring posture): a process nobody configured
    starts sampling only once a collector actually pulls it."""
    if _STATE.enabled and not _SAMPLER.running():
        _SAMPLER.start()


def sample_once() -> int:
    """One synchronous sampling tick (tests and the smoke gate)."""
    return _SAMPLER.sample_once()


def reset() -> None:
    """Tests: stop the sampler, clear the table and every site rollup,
    restore defaults (enabled, not running)."""
    _SAMPLER.reset()
    _SAMPLER.hz = DEFAULT_HZ
    _SAMPLER.budget = DEFAULT_BUDGET
    with _SAMPLER._lock:
        _SAMPLER._table = SpaceSaving(capacity=DEFAULT_BUDGET)
    with _SITES_LOCK:
        _SITES.clear()
        _SITE_LOCKS.clear()
    _STATE.enabled = True


def collect_state() -> Dict[str, Any]:
    """The ``prof`` section of a ``CollectTelemetry`` reply: the
    cumulative folded-stack table (O(budget)), sampler counters, and the
    lock-site rollup. ``{"enabled": false}`` stub when opted out."""
    if not _STATE.enabled:
        return {"enabled": False}
    return _SAMPLER.state()


def counts_snapshot() -> Dict[str, float]:
    """Tracked stack counts right now (the per-round delta baseline)."""
    return _SAMPLER.counts()


def delta(prev: Dict[str, float], now: Optional[Dict[str, float]] = None,
          top: int = 10) -> Dict[str, Any]:
    """Folded-stack growth between two :func:`counts_snapshot` maps —
    the RoundProfile's per-round profile. Eviction can shrink a tracked
    count; negative deltas clamp to 0 (a stack cannot un-run)."""
    if now is None:
        now = counts_snapshot()
    grown = [[stack, count - prev.get(stack, 0.0)]
             for stack, count in now.items()
             if count - prev.get(stack, 0.0) > 0.0]
    grown.sort(key=lambda row: (-row[1], row[0]))
    return {"samples": round(sum(d for _s, d in grown), 1),
            "stacks": [[stack, round(d, 1)] for stack, d in grown[:top]]}


# --------------------------------------------------------------------- #
# folded-table analytics (perf --flame / status --fleet share these)
# --------------------------------------------------------------------- #

def folded_counts(state: Dict[str, Any]) -> Dict[str, float]:
    """``{folded_stack: count}`` from a ``collect_state()`` dict."""
    stacks = state.get("stacks") or {}
    if isinstance(stacks, dict) and "rows" in stacks:
        return {str(key): float(count)
                for key, count, _e, _l in SpaceSaving.from_dict(
                    stacks).top(0)}
    # already-flat map (per-round deltas, merged fleet dumps)
    return {str(k): float(v) for k, v in dict(stacks).items()}


def frame_table(folded: Dict[str, float]) -> List[Dict[str, Any]]:
    """Per-frame self/total sample rows from a folded-stack map (self =
    samples where the frame is the leaf; total = samples in any stack
    containing it), self-descending — the terminal top-table."""
    self_n: Dict[str, float] = {}
    total_n: Dict[str, float] = {}
    grand = 0.0
    for stack, count in folded.items():
        frames = [f for f in stack.split(";") if f]
        if not frames:
            continue
        grand += count
        self_n[frames[-1]] = self_n.get(frames[-1], 0.0) + count
        for frame in set(frames):
            total_n[frame] = total_n.get(frame, 0.0) + count
    rows = [{"frame": frame,
             "self": self_n.get(frame, 0.0),
             "total": total,
             "self_pct": (100.0 * self_n.get(frame, 0.0) / grand
                          if grand else 0.0),
             "total_pct": 100.0 * total / grand if grand else 0.0}
            for frame, total in total_n.items()]
    rows.sort(key=lambda r: (-r["self"], -r["total"], r["frame"]))
    return rows


def summarize_state(state: Dict[str, Any]) -> Dict[str, Any]:
    """One-line summary of a peer's profile for ``status --fleet``: the
    hottest frame by self time and the most contended lock site."""
    out: Dict[str, Any] = {
        "enabled": bool(state.get("enabled", False)),
        "samples": int(state.get("samples", 0) or 0),
        "hz": float(state.get("hz", 0.0) or 0.0),
    }
    rows = frame_table(folded_counts(state))
    if rows:
        out["top_frame"] = rows[0]["frame"]
        out["top_frame_pct"] = round(rows[0]["self_pct"], 1)
    locks = state.get("locks") or {}
    if locks:
        site = max(locks, key=lambda s: locks[s].get("wait_s_total", 0.0))
        row = locks[site]
        if row.get("contentions"):
            out["top_lock"] = site
            out["top_lock_wait_ms"] = round(
                1e3 * float(row.get("wait_s_total", 0.0)), 3)
            out["contentions"] = int(row.get("contentions", 0))
    return out


# --------------------------------------------------------------------- #
# post-mortem snapshot (telemetry/postmortem.py bundles this)
# --------------------------------------------------------------------- #

def postmortem_snapshot(top: int = 10) -> Optional[Dict[str, Any]]:
    """The profiler's view at death: top-table rows + the lock-site
    rollup (None when disabled or nothing was ever sampled AND no lock
    ever contended — a silent bundle key beats an empty section)."""
    if not _STATE.enabled:
        return None
    state = _SAMPLER.state()
    locks = state["locks"]
    if not state["samples"] and not any(
            row.get("acquisitions") for row in locks.values()):
        return None
    rows = frame_table(folded_counts(state))[:top]
    return {"samples": state["samples"], "ticks": state["ticks"],
            "hz": state["hz"],
            "top": [{k: (round(v, 2) if isinstance(v, float) else v)
                     for k, v in row.items()} for row in rows],
            "locks": locks}


# --------------------------------------------------------------------- #
# CI overhead gate (scripts/chaos_smoke.sh --prof-smoke stanza)
# --------------------------------------------------------------------- #

def _smoke_round_loop(nlock, blocks: int = 1000) -> float:
    """One bench-shaped aggregation round: stride-blocked stacked scaled
    adds over synthetic models, each block under a (possibly
    instrumented) lock — the controller fold loop's shape. Sized to run
    a few hundred ms, long enough that the 67 Hz sampler ticks dozens of
    times inside one trial. Returns the wall seconds."""
    import numpy as np

    from metisfl_tpu.aggregation.base import np_stacked_scaled_add

    rng = np.random.default_rng(5)
    model = {"w": rng.standard_normal((2048, 1024)).astype(np.float32),
             "b": rng.standard_normal((1024,)).astype(np.float32)}
    block = [model, model, model, model]
    scales = [0.25, 0.25, 0.25, 0.25]
    t0 = time.perf_counter()
    acc = None
    for _ in range(blocks):
        with nlock:
            acc = np_stacked_scaled_add(acc, block, scales)
    return time.perf_counter() - t0


def _smoke(bound_pct: float = 3.0, trials: int = 7) -> int:
    """The CI overhead gate: the bench round loop with profiling ON
    (sampler at the default 67 Hz + an instrumented lock on the fold
    path) vs OFF, ``trials`` interleaved runs each, MINIMA judged.
    Fails (exit 1) when the ON minimum exceeds the OFF minimum by more
    than ``bound_pct`` percent, when the sampler collected nothing, or
    when the fold kernel's frame never showed up — an overhead gate
    that can pass while the profiler is blind would gate nothing."""
    reset()
    failures: List[str] = []
    # warm-up outside the measurement (numpy allocator, code paths)
    _smoke_round_loop(threading.Lock())

    off_s: List[float] = []
    on_s: List[float] = []
    for _ in range(trials):
        configure(enabled=False)
        off_s.append(_smoke_round_loop(lock("prof.smoke")))
        configure(enabled=True)  # default 67 Hz — the gated config
        on_s.append(_smoke_round_loop(lock("prof.smoke")))
    state = collect_state()
    configure(enabled=False)

    # judge the MINIMA: the profiler's cost is constant per trial, so it
    # survives in the min, while scheduler/BLAS noise only inflates
    # individual trials — medians on this gVisor-class host swing ±5%
    # run-to-run, which would flap a 3% gate (reported for context)
    off_ms = min(off_s) * 1e3
    on_ms = min(on_s) * 1e3
    overhead_pct = (100.0 * (on_ms - off_ms) / off_ms) if off_ms else 0.0
    if overhead_pct > bound_pct:
        failures.append(
            f"profiling overhead {overhead_pct:.2f}% exceeds the "
            f"{bound_pct:.1f}% bound (off {off_ms:.1f}ms, on "
            f"{on_ms:.1f}ms)")
    if not state.get("samples"):
        failures.append("sampler collected no stacks during the ON runs")
    table = frame_table(folded_counts(state))
    if not any("np_stacked_scaled_add" in row["frame"] for row in table):
        failures.append("fold kernel frame missing from the profile "
                        "(sampler ran blind)")
    summary = {
        "trials": trials,
        "off_ms_min": round(off_ms, 2),
        "on_ms_min": round(on_ms, 2),
        "off_ms_median": round(statistics.median(off_s) * 1e3, 2),
        "on_ms_median": round(statistics.median(on_s) * 1e3, 2),
        "overhead_pct": round(overhead_pct, 2),
        "bound_pct": bound_pct,
        "samples": state.get("samples", 0),
        "ticks": state.get("ticks", 0),
        "stacks_tracked": len(folded_counts(state)),
        "top_frame": table[0]["frame"] if table else "",
        "failures": failures,
    }
    print(json.dumps(summary, indent=2))
    return 1 if failures else 0


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        "metisfl_tpu.telemetry.prof",
        description="continuous-profiling utilities")
    parser.add_argument("--smoke", action="store_true",
                        help="run the CI overhead gate (bench round loop "
                             "prof on vs off; exit 1 past the bound)")
    parser.add_argument("--bound-pct", type=float, default=3.0,
                        help="smoke: maximum tolerated overhead percent")
    parser.add_argument("--trials", type=int, default=7,
                        help="smoke: interleaved trials per side "
                             "(minima judged; medians reported)")
    args = parser.parse_args(argv)
    if args.smoke:
        return _smoke(bound_pct=args.bound_pct, trials=args.trials)
    parser.print_usage()
    return 2


if __name__ == "__main__":
    sys.exit(main())
