"""Bounded in-process time series: the alert engine's working memory.

The metrics registry answers "what is the value *now*"; alert rules
need "how has it moved" — a rate over a window, a threshold held for a
duration. This module keeps a small ring of (ts, value) samples per
named series, bounded on both axes (``capacity`` points per series,
``max_series`` series total), so a controller that runs for a month
holds exactly as much history as one that ran for an hour.

The same rings feed the ``status --watch`` sparklines: ``snapshot()``
ships the recent points of every series in the ``DescribeFederation``
payload (bounded: max_series × points, independent of fleet size), and
:func:`sparkline` renders them as one block-character line.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


class TimeSeriesRing:
    """``record()`` appends, ``window()``/``rate()`` read back. Thread-
    safe; series past ``max_series`` are dropped (counted, never raised
    — telemetry must not fail the caller)."""

    def __init__(self, capacity: int = 240, max_series: int = 64):
        self.capacity = max(2, int(capacity))
        self.max_series = max(1, int(max_series))
        self._lock = threading.Lock()
        self._series: "Dict[str, collections.deque]" = {}
        self.dropped_series = 0

    def record(self, name: str, value: float,
               ts: Optional[float] = None) -> None:
        ts = time.time() if ts is None else float(ts)
        with self._lock:
            ring = self._series.get(name)
            if ring is None:
                if len(self._series) >= self.max_series:
                    self.dropped_series += 1
                    return
                ring = self._series[name] = collections.deque(
                    maxlen=self.capacity)
            ring.append((ts, float(value)))

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def latest(self, name: str) -> Optional[Tuple[float, float]]:
        with self._lock:
            ring = self._series.get(name)
            return ring[-1] if ring else None

    def window(self, name: str, seconds: float,
               now: Optional[float] = None) -> List[Tuple[float, float]]:
        """Samples within the trailing ``seconds`` (oldest first)."""
        now = time.time() if now is None else float(now)
        cutoff = now - max(0.0, float(seconds))
        with self._lock:
            ring = self._series.get(name)
            if not ring:
                return []
            return [(ts, v) for ts, v in ring if ts >= cutoff]

    def rate(self, name: str, seconds: float,
             now: Optional[float] = None) -> float:
        """Per-second increase over the trailing window — counter
        semantics: (last - first) / elapsed, clamped at 0 so a registry
        reset never reports a negative rate. 0.0 with fewer than two
        samples in the window (no rate is attributable yet)."""
        points = self.window(name, seconds, now=now)
        if len(points) < 2:
            return 0.0
        (t0, v0), (t1, v1) = points[0], points[-1]
        if t1 <= t0:
            return 0.0
        return max(0.0, (v1 - v0) / (t1 - t0))

    def points(self, name: str, n: int = 0) -> List[float]:
        """The last ``n`` sample values (0 = everything retained)."""
        with self._lock:
            ring = self._series.get(name)
            values = [v for _, v in ring] if ring else []
        return values[-n:] if n > 0 else values

    def snapshot(self, points: int = 30) -> Dict[str, Any]:
        """Bounded wire shape for DescribeFederation: the last
        ``points`` values per series plus the newest timestamp."""
        out: Dict[str, Any] = {}
        with self._lock:
            for name, ring in self._series.items():
                if not ring:
                    continue
                values = [round(v, 6) for _, v in ring]
                out[name] = {"points": values[-points:],
                             "last_ts": round(ring[-1][0], 3)}
        return out

    def reset(self) -> None:
        with self._lock:
            self._series.clear()
            self.dropped_series = 0


def sparkline(values: List[float], width: int = 24) -> str:
    """One unicode block-character line for a value series (the status
    CLI's live time-series cell). Scales min→max; a flat series renders
    as the lowest block so movement is what draws the eye."""
    if not values:
        return ""
    values = [float(v) for v in values[-width:]]
    lo, hi = min(values), max(values)
    if hi <= lo:
        return SPARK_BLOCKS[0] * len(values)
    span = hi - lo
    return "".join(
        SPARK_BLOCKS[min(len(SPARK_BLOCKS) - 1,
                         int((v - lo) / span * len(SPARK_BLOCKS)))]
        for v in values)
