"""Accelerator runtime observability: XLA compiles + device memory.

The causal-tracing (PR 14) and continuous-profiling (PR 12) planes
attribute Python frames and RPC edges — but the runtime layer *beneath*
them is blind: an XLA recompile storm or an HBM leak shows up only as
unexplained wall-clock. Jit-heavy stacks are exactly where silent
recompiles turn a 12 ms round into a multi-second one (the PR 13 slot
decoder compiles per exact prompt length, ``models/ops.py`` jits the
round kernels, learners jit train steps). This module is the runtime
layer's telemetry, native to the existing planes:

- **Compile tracking** — ``jax.monitoring`` fires a
  ``/jax/core/compile/backend_compile_duration`` duration event exactly
  once per real XLA compile, but carries NO function attribution. The
  attribution contract here is :func:`monitored_jit`: a wrapper around
  the jit entrypoints we own that names the function in a thread-local
  context for the duration of the call — the registered listener
  attributes any compile that fires inside that window. The fast path
  (steady-state call, nothing compiling) costs one attribute check plus
  a thread-local set/restore; the abstract shape signature is computed
  ONLY when a compile actually fired during the call. Compiles outside
  any wrapper record as ``(unattributed)``. When ``jax.monitoring`` is
  unavailable the wrapper falls back to per-call signature tracking
  (a new signature for a wrapped function = one compile, duration = the
  call's wall time — an upper bound).

- **Classification** — the first compile for a function name is
  ``cold``; every later compile of the same name is a **recompile**
  (same function, new abstract signature — including an LRU-evicted
  one). A function recompiling ``storm_threshold`` times inside
  ``storm_window_s`` emits a ``jax_recompile_storm`` journal event
  (once per window per function).

- **Bounded mergeable state** — per-function rows (cold/recompile
  counts, total/max compile seconds, last signature) keep exact labels
  up to ``budget``; the crowd folds into ``_other`` (PR 9's posture).
  A small ring of recent compile events backs the offenders table.

- **Memory accounting** — :func:`memory_snapshot` prefers per-device
  ``memory_stats()['bytes_in_use']`` (TPU/GPU), falls back to
  ``jax.live_arrays()`` nbytes, and always reports host RSS (the CPU
  story). Sampled on the PR 12 sampler cadence (a prof tick hook,
  time-gated by ``mem_every_s``) and refreshed on every
  ``collect_state()`` pull; attributed per plane (learner train /
  controller fold / serving decode) via the service name
  :func:`metisfl_tpu.telemetry.apply_config` passes down.

Every surface ships it: a ``runtime`` section rides ``CollectTelemetry``
(merged fleet-wide by the FleetCollector), the
``jax_compiles_total{fn,kind}`` / ``jax_compile_seconds`` /
``jax_device_memory_bytes{plane}`` families are alertable, ``status
--fleet`` prints a ``runtime:`` line, each compile lands in the span
timeline as a ``jax.compile`` event (so ``perf --critical-path`` can
name a mid-round recompile), and ``perf --compile-report`` renders the
per-fn table + offenders from a live run dir.

Opt-out ``telemetry.runtime.enabled=false``: no listener is ever
installed, wrapped jits pass straight through (one attribute check),
and the ``CollectTelemetry`` section is an ``{"enabled": false}`` stub.
The CI gate ``python -m metisfl_tpu.telemetry --runtime-smoke``
(scripts/chaos_smoke.sh) runs the bench round loop plus a
continuous-batching decode burst and fails the build if steady-state
(post-warmup) compiles are nonzero, if a deliberately shape-shifting
control run does NOT trip the detector, or if wrapper overhead exceeds
the pinned budget.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from metisfl_tpu.telemetry import events as _events
from metisfl_tpu.telemetry import metrics as _metrics
from metisfl_tpu.telemetry import trace as _trace

logger = logging.getLogger("metisfl_tpu.telemetry.runtime")

# defaults (config/federation.py RuntimeConfig mirrors them, test-pinned)
DEFAULT_BUDGET = 256          # exact per-fn rows kept; the crowd → _other
DEFAULT_MEM_EVERY_S = 1.0     # memory-sample gate on the prof tick cadence
DEFAULT_STORM_WINDOW_S = 10.0
DEFAULT_STORM_THRESHOLD = 4   # recompiles of ONE fn inside the window

# the one duration event that fires exactly once per real XLA compile
# (jaxpr trace / MLIR lowering fire their own events; counting those
# would triple every compile)
_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

UNATTRIBUTED = "(unattributed)"
OTHER = "_other"

# metric families (telemetry/__init__.py re-exports them as M_*
# constants; catalog rows in docs/OBSERVABILITY.md)
JAX_COMPILES_TOTAL = "jax_compiles_total"
JAX_COMPILE_SECONDS = "jax_compile_seconds"
JAX_DEVICE_MEMORY_BYTES = "jax_device_memory_bytes"

_REG = _metrics.registry()
_M_COMPILES = _REG.counter(
    JAX_COMPILES_TOTAL,
    "XLA compilations by wrapped-function name and kind (cold = first "
    "compile of the fn, recompile = any later one — a new abstract "
    "signature or an LRU-evicted program)", ("fn", "kind"),
    budget_label="fn")
_M_COMPILE_SECONDS = _REG.histogram(
    JAX_COMPILE_SECONDS,
    "Backend (XLA) compile duration per compilation")
_M_DEVICE_MEMORY = _REG.gauge(
    JAX_DEVICE_MEMORY_BYTES,
    "Accelerator memory in use by plane (device memory_stats where the "
    "backend reports it, live-array bytes else, host RSS on CPU)",
    ("plane",))


# --------------------------------------------------------------------- #
# state
# --------------------------------------------------------------------- #

class _State:
    def __init__(self):
        self.enabled = True       # always-on posture; apply_config re-arms
        self.budget = DEFAULT_BUDGET
        self.mem_every_s = DEFAULT_MEM_EVERY_S
        self.storm_window_s = DEFAULT_STORM_WINDOW_S
        self.storm_threshold = DEFAULT_STORM_THRESHOLD
        self.plane = "host"
        self.lock = threading.Lock()
        # fn -> {"cold", "recompiles", "total_s", "max_s", "last_sig"}
        self.fns: Dict[str, Dict[str, Any]] = {}
        self.compiles = 0
        self.recompiles = 0
        self.unattributed = 0
        self.storms = 0
        self.recent: deque = deque(maxlen=64)
        self.recompile_ts: deque = deque()    # (ts, fn) inside the window
        self.storm_mute: Dict[str, float] = {}
        self.memory: Dict[str, Any] = {}
        self.mem_sampled_ts = 0.0
        self.started_ts = 0.0


_STATE = _State()
_TLS = threading.local()
# "none" (never armed) | "monitoring" | "fallback"
_LISTENER_MODE = "none"
_LISTENER_LOCK = threading.Lock()


def enabled() -> bool:
    return _STATE.enabled


def listener_mode() -> str:
    """How compiles are observed: ``monitoring`` (jax.monitoring duration
    listener), ``fallback`` (per-call signature tracking), or ``none``
    (never armed — the opt-out pin)."""
    return _LISTENER_MODE


def plane() -> str:
    return _STATE.plane


def set_plane(service: str) -> None:
    """Derive the memory-attribution plane from a process's service name
    (apply_config passes it): learner train / controller fold / serving
    decode, ``host`` for anything else (bench, tests, CLIs)."""
    s = (service or "").lower()
    if s.startswith("controller") or s.startswith("standby"):
        _STATE.plane = "controller"
    elif s.startswith("learner"):
        _STATE.plane = "learner"
    elif s.startswith("serving") or s.startswith("gateway") \
            or s.startswith("replica") or s.startswith("router"):
        _STATE.plane = "serving"
    else:
        _STATE.plane = "host"


def _install_listener() -> None:
    """Arm the jax.monitoring duration listener exactly once per process
    (jax.monitoring has no unregister; the listener itself gates on
    ``_STATE.enabled``, so a later opt-out costs one call per compile —
    and compiles are the rare event this plane exists to catch)."""
    global _LISTENER_MODE
    with _LISTENER_LOCK:
        if _LISTENER_MODE != "none":
            return
        try:
            from jax import monitoring as _monitoring

            _monitoring.register_event_duration_secs_listener(_on_duration)
            _LISTENER_MODE = "monitoring"
        except Exception:  # noqa: BLE001 - no jax / an older jax without
            # monitoring: the wrapper-based signature fallback takes over
            _LISTENER_MODE = "fallback"
            logger.info("jax.monitoring unavailable; compile tracking "
                        "falls back to per-call signature detection")


def _on_duration(event: str, duration: float, **kwargs) -> None:
    """The registered jax.monitoring listener. Fires in the thread that
    triggered the compile; attribution comes from the thread-local
    context a :func:`monitored_jit` wrapper set around its call."""
    if not _STATE.enabled or event != _BACKEND_COMPILE_EVENT:
        return
    pending = getattr(_TLS, "pending", None)
    if pending is not None:
        # inside a monitored call window: the wrapper records it (with
        # the signature it only computes because this fired)
        pending.append(float(duration))
    else:
        _record_compile(UNATTRIBUTED, "", float(duration))


def configure(enabled: bool = True, budget: int = 0,
              mem_every_s: float = 0.0, storm_window_s: float = 0.0,
              storm_threshold: int = 0) -> None:
    """(Re)arm the runtime plane from ``telemetry.runtime``: installs the
    compile listener (once) and sizes the bounded state. Zero values keep
    the defaults. ``enabled=False`` installs nothing — wrapped jits pass
    straight through at one attribute check."""
    _STATE.enabled = bool(enabled)
    if not enabled:
        return
    _STATE.budget = int(budget or 0) or DEFAULT_BUDGET
    _STATE.mem_every_s = float(mem_every_s or 0.0) or DEFAULT_MEM_EVERY_S
    _STATE.storm_window_s = (float(storm_window_s or 0.0)
                             or DEFAULT_STORM_WINDOW_S)
    _STATE.storm_threshold = (int(storm_threshold or 0)
                              or DEFAULT_STORM_THRESHOLD)
    if not _STATE.started_ts:
        _STATE.started_ts = time.time()
    _install_listener()
    # memory sampling rides the PR 12 sampler cadence (time-gated here)
    from metisfl_tpu.telemetry import prof as _prof

    _prof.register_tick_hook(_tick)


def ensure_started() -> None:
    """Lazy arming (the span-ring/prof posture): a process nobody
    configured arms the listener once a collector actually pulls it."""
    if _STATE.enabled and _LISTENER_MODE == "none":
        configure(enabled=True)


def reset() -> None:
    """Tests: clear every table/counter and restore defaults. The
    process-level listener stays installed (jax.monitoring has no
    unregister) but re-arms against the fresh state."""
    st = _STATE
    with st.lock:
        st.fns.clear()
        st.recent.clear()
        st.recompile_ts.clear()
        st.storm_mute.clear()
        st.compiles = st.recompiles = st.unattributed = st.storms = 0
        st.memory = {}
        st.mem_sampled_ts = 0.0
        st.started_ts = 0.0
    st.enabled = True
    st.budget = DEFAULT_BUDGET
    st.mem_every_s = DEFAULT_MEM_EVERY_S
    st.storm_window_s = DEFAULT_STORM_WINDOW_S
    st.storm_threshold = DEFAULT_STORM_THRESHOLD
    st.plane = "host"


# --------------------------------------------------------------------- #
# compile recording
# --------------------------------------------------------------------- #

def _fn_row(fn: str) -> Dict[str, Any]:
    """The (locked) per-fn row, folding past-budget names into _other."""
    st = _STATE
    row = st.fns.get(fn)
    if row is None:
        if len(st.fns) >= st.budget and fn not in (OTHER,):
            fn = OTHER
            row = st.fns.get(OTHER)
        if row is None:
            row = st.fns[fn] = {"cold": 0, "recompiles": 0,
                                "total_s": 0.0, "max_s": 0.0,
                                "last_sig": ""}
    return row


def _record_compile(fn: str, sig: str, duration_s: float) -> None:
    st = _STATE
    now = time.time()
    with st.lock:
        known = fn in st.fns or (len(st.fns) >= st.budget
                                 and OTHER in st.fns and fn != UNATTRIBUTED)
        row = _fn_row(fn)
        # an unattributed compile is never a "recompile": the label is a
        # bucket of many unrelated functions (jnp internals, model init),
        # not one function compiling twice
        kind = ("recompile"
                if (known and row["cold"] and fn != UNATTRIBUTED)
                else "cold")
        if kind == "cold":
            row["cold"] += 1
        else:
            row["recompiles"] += 1
            st.recompiles += 1
        row["total_s"] += duration_s
        row["max_s"] = max(row["max_s"], duration_s)
        row["last_sig"] = sig
        st.compiles += 1
        if fn == UNATTRIBUTED:
            st.unattributed += 1
        st.recent.append([round(now, 3), fn, kind,
                          round(duration_s, 6), sig])
        storm = None
        if kind == "recompile":
            window = st.storm_window_s
            st.recompile_ts.append((now, fn))
            while st.recompile_ts and st.recompile_ts[0][0] < now - window:
                st.recompile_ts.popleft()
            count = sum(1 for _ts, name in st.recompile_ts if name == fn)
            if (count >= st.storm_threshold
                    and now - st.storm_mute.get(fn, 0.0) > window):
                st.storm_mute[fn] = now
                st.storms += 1
                storm = count
    _M_COMPILES.inc(fn=fn, kind=kind)
    _M_COMPILE_SECONDS.observe(duration_s)
    # the span-timeline record: a mid-round compile becomes a child of
    # whatever span is active in this thread, so perf --critical-path
    # can name it as the dominant edge
    attrs = {"fn": fn, "kind": kind}
    if sig:
        attrs["sig"] = sig
    _trace.event("jax.compile", duration_s, attrs=attrs)
    if storm is not None:
        _events.emit(_events.RecompileStorm, fn=fn, count=storm,
                     window_s=round(st.storm_window_s, 1),
                     last_sig=sig)


def _abstract_sig(args, kwargs) -> str:
    """Abstract (shape, dtype) signature of a call's array leaves —
    computed only when a compile actually fired during the call."""
    try:
        import jax

        leaves = jax.tree_util.tree_leaves((args, kwargs))
    except Exception:  # noqa: BLE001 - a signature is diagnostic sugar
        return "?"
    parts: List[str] = []
    for leaf in leaves[:64]:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            parts.append(f"{dtype}[{','.join(str(d) for d in shape)}]")
        else:
            parts.append(type(leaf).__name__)
    if len(leaves) > 64:
        parts.append(f"+{len(leaves) - 64}")
    return ";".join(parts)


def monitored_jit(fn: Callable, *, name: str = "", **jit_kwargs):
    """``jax.jit`` with compile attribution: any XLA compile that fires
    during a call is recorded under ``name`` (default: the function's
    ``__name__``) with the call's abstract shape signature. Steady-state
    calls (nothing compiling) pay one attribute check plus a
    thread-local set/restore; with the plane disabled, the check alone.
    """
    import jax

    compiled = jax.jit(fn, **jit_kwargs)
    label = name or getattr(fn, "__name__", "jit_fn")
    # lazy arming: a process that jits through us observes its own
    # compiles even before any collector pull (no-op when opted out)
    ensure_started()

    def wrapper(*args, **kwargs):
        if not _STATE.enabled:
            return compiled(*args, **kwargs)
        if _LISTENER_MODE == "fallback":
            return _call_fallback(label, wrapper, compiled, args, kwargs)
        prev_pending = getattr(_TLS, "pending", None)
        _TLS.pending = []
        try:
            out = compiled(*args, **kwargs)
        finally:
            fired, _TLS.pending = _TLS.pending, prev_pending
            if fired:
                sig = _abstract_sig(args, kwargs)
                for duration in fired:
                    _record_compile(label, sig, duration)
        return out

    wrapper.__name__ = label
    wrapper.__wrapped__ = compiled
    return wrapper


def _call_fallback(label: str, wrapper, compiled, args, kwargs):
    """No jax.monitoring: a new abstract signature for a wrapped fn IS a
    compile; its duration reports as the call's wall time (upper bound,
    flagged via listener_mode()=='fallback')."""
    sig = _abstract_sig(args, kwargs)
    seen = getattr(wrapper, "_sigs_seen", None)
    if seen is None:
        seen = wrapper._sigs_seen = set()
    fresh = sig not in seen
    t0 = time.perf_counter() if fresh else 0.0
    out = compiled(*args, **kwargs)
    if fresh:
        seen.add(sig)
        _record_compile(label, sig, time.perf_counter() - t0)
    return out


# --------------------------------------------------------------------- #
# memory accounting
# --------------------------------------------------------------------- #

def _host_rss_bytes() -> int:
    try:
        with open("/proc/self/statm") as fh:
            pages = int(fh.read().split()[1])
        import resource

        return pages * resource.getpagesize()
    except (OSError, ValueError, IndexError, ImportError):
        try:
            import resource

            return resource.getrusage(
                resource.RUSAGE_SELF).ru_maxrss * 1024
        except Exception:  # noqa: BLE001
            return 0


def memory_snapshot() -> Dict[str, Any]:
    """One memory sample: device bytes-in-use where the backend reports
    them (TPU/GPU ``memory_stats``), live-array nbytes else, host RSS
    always. ``source`` names what ``device_bytes`` came from."""
    device_bytes = 0
    live_bytes = 0
    live_n = 0
    backend = ""
    source = "rss"
    try:
        import jax

        backend = jax.default_backend()
        stats_bytes = 0
        for dev in jax.local_devices():
            stats = None
            try:
                stats = dev.memory_stats()
            except Exception:  # noqa: BLE001 - per-device support varies
                stats = None
            if stats:
                stats_bytes += int(stats.get("bytes_in_use", 0) or 0)
        arrays = jax.live_arrays()
        live_n = len(arrays)
        live_bytes = sum(int(getattr(a, "nbytes", 0) or 0) for a in arrays)
        if stats_bytes:
            device_bytes, source = stats_bytes, "device_stats"
        elif live_bytes:
            device_bytes, source = live_bytes, "live_arrays"
    except Exception:  # noqa: BLE001 - no jax: RSS is the whole story
        pass
    rss = _host_rss_bytes()
    if not device_bytes:
        device_bytes = rss
        source = "rss"
    return {"ts": round(time.time(), 3), "plane": _STATE.plane,
            "backend": backend, "source": source,
            "device_bytes": int(device_bytes),
            "live_arrays": live_n, "live_array_bytes": int(live_bytes),
            "host_rss_bytes": int(rss)}


def sample_memory(force: bool = False) -> Optional[Dict[str, Any]]:
    """Refresh the memory sample when the ``mem_every_s`` gate allows
    (``force`` skips the gate) and export the per-plane gauge. Returns
    the sample taken, or None when gated off / disabled."""
    if not _STATE.enabled:
        return None
    now = time.time()
    if not force and now - _STATE.mem_sampled_ts < _STATE.mem_every_s:
        return None
    snap = memory_snapshot()
    with _STATE.lock:
        _STATE.memory = snap
        _STATE.mem_sampled_ts = now
    _M_DEVICE_MEMORY.set(float(snap["device_bytes"]), plane=snap["plane"])
    return snap


def _tick() -> None:
    """The prof-sampler tick hook (PR 12 cadence), time-gated by
    ``mem_every_s`` so a 67 Hz sampler costs one memory walk per
    second, not 67."""
    try:
        sample_memory()
    except Exception:  # noqa: BLE001 - telemetry must never take the
        # sampler thread down
        logger.exception("runtime memory sample failed")


# --------------------------------------------------------------------- #
# the CollectTelemetry section + fleet merge + analytics
# --------------------------------------------------------------------- #

def collect_state() -> Dict[str, Any]:
    """The ``runtime`` section of a ``CollectTelemetry`` reply: bounded
    per-fn compile rows, totals, the recent-compile ring, and the latest
    memory sample. ``{"enabled": false}`` stub when opted out."""
    if not _STATE.enabled:
        return {"enabled": False}
    sample_memory()
    st = _STATE
    with st.lock:
        return {
            "enabled": True,
            "listener": _LISTENER_MODE,
            "plane": st.plane,
            "budget": st.budget,
            "compiles": st.compiles,
            "recompiles": st.recompiles,
            "unattributed": st.unattributed,
            "storms": st.storms,
            "fns": {fn: dict(row) for fn, row in st.fns.items()},
            "recent": [list(r) for r in st.recent],
            "memory": dict(st.memory),
        }


def merge_states(states: List[Dict[str, Any]],
                 budget: int = 0) -> Dict[str, Any]:
    """Fold several peers' ``collect_state`` dicts into one (key-wise
    sums, max of maxima, budget + ``_other`` rollup preserved) — the
    FleetCollector's merged ``runtime`` view. Disabled stubs pass
    through without contributing."""
    budget = int(budget or 0) or DEFAULT_BUDGET
    out: Dict[str, Any] = {"enabled": True, "compiles": 0,
                           "recompiles": 0, "unattributed": 0,
                           "storms": 0, "fns": {}, "memory": {}}
    fns: Dict[str, Dict[str, Any]] = out["fns"]
    any_enabled = False
    for state in states:
        if not state or not state.get("enabled"):
            continue
        any_enabled = True
        for key in ("compiles", "recompiles", "unattributed", "storms"):
            out[key] += int(state.get(key, 0) or 0)
        for fn, row in (state.get("fns") or {}).items():
            if fn not in fns and len(fns) >= budget and fn != OTHER:
                fn = OTHER
            dst = fns.setdefault(fn, {"cold": 0, "recompiles": 0,
                                      "total_s": 0.0, "max_s": 0.0,
                                      "last_sig": ""})
            dst["cold"] += int(row.get("cold", 0) or 0)
            dst["recompiles"] += int(row.get("recompiles", 0) or 0)
            dst["total_s"] += float(row.get("total_s", 0.0) or 0.0)
            dst["max_s"] = max(dst["max_s"],
                               float(row.get("max_s", 0.0) or 0.0))
            dst["last_sig"] = dst["last_sig"] or str(
                row.get("last_sig", ""))
        mem = state.get("memory") or {}
        if mem.get("device_bytes"):
            mem_plane = str(mem.get("plane", "host"))
            out["memory"][mem_plane] = max(
                int(out["memory"].get(mem_plane, 0)),
                int(mem.get("device_bytes", 0)))
    out["enabled"] = any_enabled
    return out


def compile_rows(state: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Per-fn report rows from a ``collect_state``/``merge_states``
    dict, recompile-count descending then total-time descending — the
    ``perf --compile-report`` table."""
    rows = []
    for fn, row in (state.get("fns") or {}).items():
        rows.append({
            "fn": fn,
            "compiles": int(row.get("cold", 0)) + int(
                row.get("recompiles", 0)),
            "cold": int(row.get("cold", 0)),
            "recompiles": int(row.get("recompiles", 0)),
            "total_s": round(float(row.get("total_s", 0.0)), 4),
            "max_s": round(float(row.get("max_s", 0.0)), 4),
            "last_sig": str(row.get("last_sig", "")),
        })
    rows.sort(key=lambda r: (-r["recompiles"], -r["total_s"], r["fn"]))
    return rows


def summarize_state(state: Dict[str, Any]) -> Dict[str, Any]:
    """One peer's runtime plane in one line's worth of fields for
    ``status --fleet``: compile totals, the worst recompile offender,
    and the latest memory sample."""
    out: Dict[str, Any] = {
        "enabled": bool(state.get("enabled", False)),
        "compiles": int(state.get("compiles", 0) or 0),
        "recompiles": int(state.get("recompiles", 0) or 0),
        "storms": int(state.get("storms", 0) or 0),
    }
    rows = compile_rows(state)
    offenders = [r for r in rows if r["recompiles"]]
    if offenders:
        out["top_offender"] = offenders[0]["fn"]
        out["top_offender_recompiles"] = offenders[0]["recompiles"]
    mem = state.get("memory") or {}
    if mem.get("device_bytes"):
        out["mem_bytes"] = int(mem["device_bytes"])
        out["mem_source"] = str(mem.get("source", ""))
    return out


def postmortem_snapshot() -> Optional[Dict[str, Any]]:
    """The runtime plane's view at death (None when disabled or nothing
    ever compiled — a silent bundle key beats an empty section)."""
    if not _STATE.enabled:
        return None
    state = collect_state()
    if not state.get("compiles"):
        return None
    return {"compiles": state["compiles"],
            "recompiles": state["recompiles"],
            "storms": state["storms"],
            "top": compile_rows(state)[:10],
            "memory": state.get("memory") or {}}


# --------------------------------------------------------------------- #
# CI gate (scripts/chaos_smoke.sh --runtime-smoke stanza)
# --------------------------------------------------------------------- #

def _smoke_round_kernel():
    """A bench-shaped jitted round kernel: one monitored train-ish step
    over a synthetic two-tensor model (the models/ops.py posture)."""
    import jax
    import jax.numpy as jnp

    def step(params, x):
        h = jnp.tanh(x @ params["w"] + params["b"])
        loss = jnp.mean(jnp.square(h))
        grads = jax.grad(
            lambda p: jnp.mean(jnp.square(
                jnp.tanh(x @ p["w"] + p["b"]))))(params)
        params = {k: v - 0.01 * grads[k] for k, v in params.items()}
        return params, loss

    return monitored_jit(step, name="runtime.smoke_step")


def _smoke_decoder(vocab: int = 97):
    """A tiny slot decoder + its variables (the PR 13 decode path)."""
    import numpy as np

    from metisfl_tpu.models.ops import FlaxModelOps
    from metisfl_tpu.models.zoo.transformer import LlamaLite

    ops = FlaxModelOps(LlamaLite(vocab_size=vocab, dim=32, depth=1,
                                 heads=4),
                       np.zeros((1, 8), np.int32), rng_seed=7)
    return ops, ops.get_variables()


def _smoke(overhead_budget_ns: float = 50_000.0, trials: int = 5,
           steady_iters: int = 30) -> int:
    """The CI gate: (1) the bench round loop + a continuous-batching
    decode burst must report ZERO post-warmup compiles; (2) a
    deliberately shape-shifting control run must report NONZERO
    recompiles (the detector provably fires, storm event included);
    (3) steady-state wrapper overhead must stay under
    ``overhead_budget_ns`` per call (minima judged, the prof-smoke
    posture). Exit 0 = gate passed, 1 = failed."""
    import numpy as np

    reset()
    configure(enabled=True, storm_threshold=3, storm_window_s=60.0)
    _events.configure(enabled=True, service="runtime-smoke", dir="")
    failures: List[str] = []

    # --- bench round loop: warmup compiles, then steady shapes -------- #
    step = _smoke_round_kernel()
    rng = np.random.default_rng(5)
    params = {"w": rng.standard_normal((128, 64)).astype(np.float32),
              "b": rng.standard_normal((64,)).astype(np.float32)}
    x = rng.standard_normal((32, 128)).astype(np.float32)
    params, _ = step(params, x)      # warmup (the one cold compile)
    warm_state = collect_state()
    warm_compiles = warm_state["compiles"]
    for _ in range(steady_iters):
        params, _ = step(params, x)
    steady_state = collect_state()
    round_steady = steady_state["compiles"] - warm_compiles
    if round_steady:
        failures.append(f"round loop compiled {round_steady}x "
                        "post-warmup (expected 0)")
    if not warm_compiles:
        failures.append("round-loop warmup compile was never observed "
                        "(listener blind)")

    # --- continuous-batching decode burst ----------------------------- #
    decode_steady = -1
    try:
        from metisfl_tpu.serving.decode import ContinuousBatcher

        ops, variables = _smoke_decoder()
        batcher = ContinuousBatcher(ops, version=1, variables=variables,
                                    slots=2, max_len=64)
        try:
            prompt = np.arange(1, 9, dtype=np.int32)  # fixed length 8
            # warmup burst: prefill@8 + the step program compile
            for fut in [batcher.submit(prompt, 4) for _ in range(2)]:
                fut.result(timeout=60)
            warm = collect_state()["compiles"]
            for fut in [batcher.submit(prompt, 4) for _ in range(6)]:
                fut.result(timeout=60)
            decode_steady = collect_state()["compiles"] - warm
        finally:
            batcher.close()
        if decode_steady:
            failures.append(f"decode burst compiled {decode_steady}x "
                            "post-warmup (expected 0)")
    except Exception as exc:  # noqa: BLE001 - the decode path must run
        failures.append(f"decode burst crashed: {exc}")

    # --- shape-shifting control: the detector must FIRE --------------- #
    control = _smoke_round_kernel()
    pre = collect_state()["recompiles"]
    pre_storms = collect_state()["storms"]
    for width in (8, 16, 24, 40, 48):
        xs = rng.standard_normal((width, 128)).astype(np.float32)
        control(params, xs)
    control_recompiles = collect_state()["recompiles"] - pre
    control_storms = collect_state()["storms"] - pre_storms
    if not control_recompiles:
        failures.append("shape-shifting control run reported zero "
                        "recompiles (the detector never fired)")
    if not control_storms:
        failures.append("recompile storm never detected for the "
                        "shape-shifting control run")

    # --- wrapper overhead: monitored vs raw, minima judged ------------ #
    import jax

    def tiny(v):
        return v * 2.0 + 1.0

    raw = jax.jit(tiny)
    mon = monitored_jit(tiny, name="runtime.smoke_tiny")
    v = np.ones((16,), np.float32)
    raw(v), mon(v)  # both compiled before timing
    iters = 2000

    def _per_call_ns(fn) -> float:
        t0 = time.perf_counter()
        for _ in range(iters):
            fn(v)
        return (time.perf_counter() - t0) / iters * 1e9

    raw_ns = min(_per_call_ns(raw) for _ in range(trials))
    mon_ns = min(_per_call_ns(mon) for _ in range(trials))
    overhead_ns = max(0.0, mon_ns - raw_ns)
    if overhead_ns > overhead_budget_ns:
        failures.append(f"wrapper overhead {overhead_ns:.0f}ns/call over "
                        f"the {overhead_budget_ns:.0f}ns budget")

    state = collect_state()
    summary = {
        "listener": listener_mode(),
        "warmup_compiles": warm_compiles,
        "round_steady_compiles": round_steady,
        "decode_steady_compiles": decode_steady,
        "control_recompiles": control_recompiles,
        "control_storms": control_storms,
        "overhead_ns_per_call": round(overhead_ns, 1),
        "overhead_budget_ns": overhead_budget_ns,
        "compiles_total": state["compiles"],
        "recompiles_total": state["recompiles"],
        "memory": state.get("memory") or {},
        "failures": failures,
    }
    print(json.dumps(summary, indent=2))
    return 1 if failures else 0


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import sys

    parser = argparse.ArgumentParser(
        "metisfl_tpu.telemetry.runtime",
        description="accelerator runtime observability utilities")
    parser.add_argument("--smoke", action="store_true",
                        help="run the CI gate (zero steady-state "
                             "recompiles + detector fires + overhead "
                             "budget; exit 1 on failure)")
    parser.add_argument("--overhead-budget-ns", type=float,
                        default=50_000.0,
                        help="smoke: max tolerated wrapper overhead per "
                             "steady-state call")
    parser.add_argument("--trials", type=int, default=5,
                        help="smoke: overhead timing trials (minima "
                             "judged)")
    args = parser.parse_args(argv)
    if args.smoke:
        return _smoke(overhead_budget_ns=args.overhead_budget_ns,
                      trials=args.trials)
    parser.print_usage()
    return 2


if __name__ == "__main__":
    import sys

    sys.exit(main())
