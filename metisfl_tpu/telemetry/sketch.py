"""Mergeable streaming sketches for telemetry at cross-device scale.

The per-learner metric families (straggler / churn / divergence scores,
uplink and downlink bytes, codec attribution, device stats) mint one
series per learner — O(clients) cardinality that makes Prometheus
exposition, ``DescribeFederation`` payloads, the ``status`` CLI, and
checkpoint persistence all scale linearly with the fleet. At the
ROADMAP's 100k+ cross-device target that is the wall, and the standard
production answer (t-digest-style quantile digests plus space-saving
heavy hitters, the pairing high-cardinality metric systems converge on)
is what this module provides, zero-dependency:

- :class:`QuantileDigest` — a t-digest-style quantile sketch with a
  bounded centroid count: ``add()`` streams observations, ``merge()``
  combines digests from independent streams (order-insensitive up to
  re-clustering), ``quantile(q)`` interpolates. The k1-style size bound
  (per-centroid capacity ``4·n·q·(1-q)/compression``) concentrates
  resolution at the tails, so p99 stays usable where a uniform-bucket
  sketch would smear it. Error contract (pinned by
  ``tests/test_scaletel.py`` on seeded fleets): quantile *rank* error is
  O(1/compression); at the default compression 128 the p50/p90/p99
  estimates of a 100k-sample stream land within ~2% relative of exact.
- :class:`SpaceSaving` — the Metwally et al. space-saving top-K heavy
  hitter tracker: bounded key table, minimum-count eviction, per-key
  overestimation error bound (``error <= count``), ``merge()`` for
  fan-in. Tracks the *offender* series a collapsed family still exposes
  by name.

Both serialize to plain dicts (``to_dict``/``from_dict``) small enough
to ride in the controller checkpoint — a digest is O(compression), a
tracker O(capacity) — which is how the collapsed metric families in
:mod:`metisfl_tpu.telemetry.metrics` survive ``--resume`` failover.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Iterable, List, Optional, Tuple


class QuantileDigest:
    """Streaming quantile sketch with a bounded centroid count.

    Centroids are (mean, weight) pairs kept sorted by mean; an insert
    buffers, and a compression pass greedily merges sorted neighbors
    while the merged weight stays under the k1-style capacity
    ``4·n·q·(1-q)/compression`` at the centroid's quantile position.
    Exact min/max are tracked separately so ``quantile(0)``/``(1)``
    never interpolate past an observed value.
    """

    def __init__(self, compression: int = 128):
        if compression < 8:
            raise ValueError("compression must be >= 8")
        self.compression = int(compression)
        self._means: List[float] = []
        self._weights: List[float] = []
        self._buffer: List[Tuple[float, float]] = []
        self._count = 0.0
        self._min = math.inf
        self._max = -math.inf

    # -- ingest ----------------------------------------------------------

    def add(self, value: float, weight: float = 1.0) -> None:
        if weight <= 0.0:
            return
        value = float(value)
        if math.isnan(value):
            return
        self._buffer.append((value, float(weight)))
        self._count += weight
        if value < self._min:
            self._min = value
        if value > self._max:
            self._max = value
        if len(self._buffer) >= 4 * self.compression:
            self._compress()

    def merge(self, other: "QuantileDigest") -> None:
        """Fold another digest in (both streams' observations count)."""
        other._compress()  # drains other's buffer into its centroids
        for mean, weight in zip(other._means, other._weights):
            self._buffer.append((mean, weight))
            self._count += weight
        if other._min < self._min:
            self._min = other._min
        if other._max > self._max:
            self._max = other._max
        self._compress()

    def _capacity(self, q: float) -> float:
        """Per-centroid weight cap at quantile position q (k1 scale)."""
        q = min(max(q, 1e-9), 1.0 - 1e-9)
        return max(1.0, 4.0 * self._count * q * (1.0 - q) / self.compression)

    def _compress(self) -> None:
        if not self._buffer:
            return  # centroids are already a compression-pass output
        pairs = sorted(list(zip(self._means, self._weights)) + self._buffer)
        self._buffer = []
        if not pairs:
            return
        means: List[float] = []
        weights: List[float] = []
        cum = 0.0
        cur_mean, cur_weight = pairs[0]
        for mean, weight in pairs[1:]:
            midpoint_q = (cum + (cur_weight + weight) / 2.0) / max(
                self._count, 1.0)
            if cur_weight + weight <= self._capacity(midpoint_q):
                total = cur_weight + weight
                cur_mean += (mean - cur_mean) * (weight / total)
                cur_weight = total
            else:
                means.append(cur_mean)
                weights.append(cur_weight)
                cum += cur_weight
                cur_mean, cur_weight = mean, weight
        means.append(cur_mean)
        weights.append(cur_weight)
        self._means = means
        self._weights = weights

    # -- queries ---------------------------------------------------------

    @property
    def count(self) -> float:
        return self._count

    @property
    def centroids(self) -> int:
        self._compress()
        return len(self._means)

    def quantile(self, q: float) -> float:
        """Estimated value at quantile ``q`` in [0, 1] (0.0 when empty)."""
        self._compress()
        if not self._means or self._count <= 0:
            return 0.0
        q = min(max(float(q), 0.0), 1.0)
        if q <= 0.0:
            return self._min
        if q >= 1.0:
            return self._max
        target = q * self._count
        # centroid i spans [cum_i - w_i/2, cum_i + w_i/2] in rank space
        cum = 0.0
        prev_mean, prev_cum = self._min, 0.0
        for mean, weight in zip(self._means, self._weights):
            center = cum + weight / 2.0
            if target <= center:
                span = center - prev_cum
                frac = (target - prev_cum) / span if span > 0 else 1.0
                value = prev_mean + (mean - prev_mean) * frac
                return min(max(value, self._min), self._max)
            prev_mean, prev_cum = mean, center
            cum += weight
        span = self._count - prev_cum
        frac = (target - prev_cum) / span if span > 0 else 1.0
        value = prev_mean + (self._max - prev_mean) * frac
        return min(max(value, self._min), self._max)

    def quantiles(self, qs: Iterable[float]) -> Dict[str, float]:
        """``{str(q): value}`` for several quantiles in one pass."""
        return {f"{q:g}": self.quantile(q) for q in qs}

    # -- serialization ---------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        self._compress()
        return {
            "compression": self.compression,
            "means": list(self._means),
            "weights": list(self._weights),
            "count": self._count,
            "min": None if math.isinf(self._min) else self._min,
            "max": None if math.isinf(self._max) else self._max,
        }

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "QuantileDigest":
        digest = cls(compression=int(data.get("compression", 128)))
        digest._means = [float(v) for v in data.get("means", [])]
        digest._weights = [float(v) for v in data.get("weights", [])]
        digest._count = float(data.get("count", sum(digest._weights)))
        digest._min = (math.inf if data.get("min") is None
                       else float(data["min"]))
        digest._max = (-math.inf if data.get("max") is None
                       else float(data["max"]))
        return digest


class SpaceSaving:
    """Space-saving top-K heavy hitters (Metwally et al. 2005).

    Bounded table of ``capacity`` keys. ``offer(key, amount)`` adds to a
    tracked key's count; an untracked key past capacity evicts the
    current minimum and inherits its count as ``error`` (the classic
    overestimation bound: ``true_count >= count - error``). ``last``
    keeps the most recent raw observation per key so gauge-shaped
    families can expose the offender's current value, not its running
    sum.
    """

    def __init__(self, capacity: int = 48):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self._counts: Dict[str, float] = {}
        self._errors: Dict[str, float] = {}
        self._last: Dict[str, float] = {}

    def offer(self, key: str, amount: float = 1.0,
              value: Optional[float] = None) -> None:
        if amount < 0.0:
            amount = 0.0
        if key in self._counts:
            self._counts[key] += amount
        elif len(self._counts) < self.capacity:
            self._counts[key] = amount
            self._errors[key] = 0.0
        else:
            victim = min(self._counts, key=self._counts.get)
            floor = self._counts.pop(victim)
            self._errors.pop(victim, None)
            self._last.pop(victim, None)
            self._counts[key] = floor + amount
            self._errors[key] = floor
        self._last[key] = float(value if value is not None
                                else self._counts[key])

    def update(self, key: str, value: float) -> None:
        """Gauge-shaped tracking: rank by CURRENT value, not cumulative
        sum — ``offer()`` would let a frequent low-value reporter
        accumulate past a rarely-reporting true offender (slow learners
        report rarely by definition). Tracked keys follow their latest
        value down as well as up; an untracked key enters only by
        beating the current minimum (no error inheritance — there is no
        count semantics to bound)."""
        value = float(value)
        if key in self._counts:
            self._counts[key] = value
        elif len(self._counts) < self.capacity:
            self._counts[key] = value
            self._errors[key] = 0.0
        else:
            victim = min(self._counts, key=self._counts.get)
            if value <= self._counts[victim]:
                return
            self.drop(victim)
            self._counts[key] = value
            self._errors[key] = 0.0
        self._last[key] = value

    def drop(self, key: str) -> None:
        """Forget one key (a departed learner's offender slot)."""
        self._counts.pop(key, None)
        self._errors.pop(key, None)
        self._last.pop(key, None)

    def merge(self, other: "SpaceSaving") -> None:
        """Fold another tracker in: counts and errors add for shared
        keys; the union is then trimmed back to capacity by evicting the
        smallest counts (their mass is dropped — the usual space-saving
        merge approximation, still within the summed error bounds for
        the survivors)."""
        for key, count in other._counts.items():
            if key in self._counts:
                self._counts[key] += count
                self._errors[key] = (self._errors.get(key, 0.0)
                                     + other._errors.get(key, 0.0))
            else:
                self._counts[key] = count
                self._errors[key] = other._errors.get(key, 0.0)
            self._last[key] = other._last.get(key, self._last.get(key, 0.0))
        while len(self._counts) > self.capacity:
            victim = min(self._counts, key=self._counts.get)
            self.drop(victim)

    def top(self, k: int = 0) -> List[Tuple[str, float, float, float]]:
        """``(key, count, error, last_value)`` rows, largest count first
        (``k=0`` returns the whole table)."""
        rows = sorted(((key, count, self._errors.get(key, 0.0),
                        self._last.get(key, 0.0))
                       for key, count in self._counts.items()),
                      key=lambda r: (-r[1], r[0]))
        return rows[:k] if k > 0 else rows

    def __len__(self) -> int:
        return len(self._counts)

    def __contains__(self, key: str) -> bool:
        return key in self._counts

    # -- serialization ---------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        return {
            "capacity": self.capacity,
            "rows": [[key, count, self._errors.get(key, 0.0),
                      self._last.get(key, 0.0)]
                     for key, count in self._counts.items()],
        }

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "SpaceSaving":
        tracker = cls(capacity=int(data.get("capacity", 48)))
        for row in data.get("rows", []):
            key, count, error, last = (list(row) + [0.0, 0.0, 0.0])[:4]
            tracker._counts[str(key)] = float(count)
            tracker._errors[str(key)] = float(error)
            tracker._last[str(key)] = float(last)
        return tracker
