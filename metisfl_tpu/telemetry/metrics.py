"""Thread-safe metrics registry with Prometheus text exposition.

Zero-dependency counterpart of ``prometheus_client``: counters, gauges and
histograms keyed by label values, rendered in the text exposition format
(version 0.0.4) that Prometheus, victoriametrics and ``promtool`` ingest:

    https://prometheus.io/docs/instrumenting/exposition_formats/

One process-wide registry (``registry()``) backs every instrumented
subsystem — RPC transport, controller rounds, learner training, stores —
and is served over the ``GetMetrics`` RPC and the optional plain-HTTP
``/metrics`` listener (:mod:`metisfl_tpu.telemetry.httpd`). The whole
registry can be disabled (federation config ``telemetry.enabled=false``);
disabled instruments return before taking the lock, so the opt-out path
costs one attribute read per call site.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# latency-shaped default buckets (seconds): sub-ms RPC acks up through
# multi-second cold-jit training rounds
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def _escape(value: str) -> str:
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _format_value(value: float) -> str:
    if value == math.inf:
        return "+Inf"
    if value == -math.inf:
        return "-Inf"
    if float(value).is_integer() and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


class _Metric:
    """One named family; concrete series are keyed by label-value tuples."""

    kind = "untyped"

    def __init__(self, registry: "Registry", name: str, help: str,
                 labelnames: Sequence[str]):
        self._registry = registry
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, object]) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(labels)}")
        return tuple(str(labels[k]) for k in self.labelnames)

    def _series(self, key: Tuple[str, ...]) -> str:
        if not key:
            return self.name
        pairs = ",".join(f'{k}="{_escape(v)}"'
                         for k, v in zip(self.labelnames, key))
        return f"{self.name}{{{pairs}}}"


class Counter(_Metric):
    kind = "counter"

    def __init__(self, registry, name, help, labelnames):
        super().__init__(registry, name, help, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        if not self._registry.enabled:
            return
        if amount < 0:
            raise ValueError(f"{self.name}: counters only go up")
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def remove(self, **labels) -> None:
        """Drop one series (bounded cardinality under churn: e.g. a
        departed learner's per-learner series must not live forever)."""
        with self._lock:
            self._values.pop(self._key(labels), None)

    def _render(self, out: List[str]) -> None:
        with self._lock:
            items = sorted(self._values.items())
        for key, value in items:
            out.append(f"{self._series(key)} {_format_value(value)}")

    def _reset(self) -> None:
        with self._lock:
            self._values.clear()


class Gauge(Counter):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        if not self._registry.enabled:
            return
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        if not self._registry.enabled:
            return
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, registry, name, help, labelnames,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(registry, name, help, labelnames)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        # key -> [per-bucket counts..., +Inf count, sum]
        self._values: Dict[Tuple[str, ...], List[float]] = {}

    def observe(self, value: float, **labels) -> None:
        if not self._registry.enabled:
            return
        key = self._key(labels)
        value = float(value)
        with self._lock:
            cells = self._values.get(key)
            if cells is None:
                cells = self._values[key] = [0.0] * (len(self.buckets) + 2)
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    cells[i] += 1
            cells[-2] += 1  # +Inf
            cells[-1] += value

    def count(self, **labels) -> float:
        with self._lock:
            cells = self._values.get(self._key(labels))
            return cells[-2] if cells else 0.0

    def sum(self, **labels) -> float:
        with self._lock:
            cells = self._values.get(self._key(labels))
            return cells[-1] if cells else 0.0

    def _render(self, out: List[str]) -> None:
        with self._lock:
            items = sorted((k, list(v)) for k, v in self._values.items())
        for key, cells in items:
            for bound, count in zip(self.buckets, cells):
                series = self._series_with(key, ("le", _format_value(bound)))
                out.append(f"{self.name}_bucket{series} "
                           f"{_format_value(count)}")
            series = self._series_with(key, ("le", "+Inf"))
            out.append(f"{self.name}_bucket{series} "
                       f"{_format_value(cells[-2])}")
            base = self._series(key)[len(self.name):]
            out.append(f"{self.name}_sum{base} {_format_value(cells[-1])}")
            out.append(f"{self.name}_count{base} {_format_value(cells[-2])}")

    def _series_with(self, key: Tuple[str, ...],
                     extra: Tuple[str, str]) -> str:
        pairs = [f'{k}="{_escape(v)}"' for k, v in zip(self.labelnames, key)]
        pairs.append(f'{extra[0]}="{_escape(extra[1])}"')
        return "{" + ",".join(pairs) + "}"

    def _reset(self) -> None:
        with self._lock:
            self._values.clear()


class Registry:
    """Named metric families; idempotent registration (a second
    ``counter()`` call with the same name returns the first family, so
    module-level instrumentation never double-registers)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: "Dict[str, _Metric]" = {}
        self.enabled = True

    def _get_or_create(self, cls, name: str, help: str,
                       labelnames: Sequence[str], **kwargs) -> _Metric:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls) or (
                        existing.labelnames != tuple(labelnames)):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind} with labels {existing.labelnames}")
                return existing
            metric = cls(self, name, help, labelnames, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def render(self) -> str:
        """Prometheus text exposition (format version 0.0.4)."""
        out: List[str] = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for metric in metrics:
            body: List[str] = []
            metric._render(body)
            if not body:
                continue
            if metric.help:
                out.append(f"# HELP {metric.name} "
                           f"{metric.help.replace(chr(10), ' ')}")
            out.append(f"# TYPE {metric.name} {metric.kind}")
            out.extend(body)
        return "\n".join(out) + ("\n" if out else "")

    def reset(self) -> None:
        """Zero every series (tests); families stay registered so
        module-level instrument handles keep working."""
        with self._lock:
            metrics = list(self._metrics.values())
        for metric in metrics:
            metric._reset()


_REGISTRY = Registry()


def registry() -> Registry:
    return _REGISTRY


def enabled() -> bool:
    return _REGISTRY.enabled


def set_enabled(value: bool) -> None:
    _REGISTRY.enabled = bool(value)


def parse_exposition(text: str) -> Dict[str, Dict[Tuple[Tuple[str, str], ...], float]]:
    """Parse a text exposition into ``{series_name: {labels: value}}``
    (labels as a sorted tuple of (key, value) pairs). Raises ValueError
    on malformed lines — the scrape-compatibility check tests lean on.
    """
    series: Dict[str, Dict[Tuple[Tuple[str, str], ...], float]] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name, labels, rest = _parse_series(line, lineno)
        parts = rest.split()
        if not parts:
            raise ValueError(f"line {lineno}: missing value: {line!r}")
        raw = parts[0]
        if raw == "+Inf":
            value = math.inf
        elif raw == "-Inf":
            value = -math.inf
        else:
            try:
                value = float(raw)
            except ValueError:
                raise ValueError(
                    f"line {lineno}: bad value {raw!r}") from None
        series.setdefault(name, {})[labels] = value
    return series


def _parse_series(line: str, lineno: int):
    brace = line.find("{")
    if brace < 0:
        name, _, rest = line.partition(" ")
        if not name or not rest:
            raise ValueError(f"line {lineno}: malformed sample: {line!r}")
        return name, (), rest
    name = line[:brace]
    end = line.find("}", brace)
    if end < 0 or not name:
        raise ValueError(f"line {lineno}: malformed labels: {line!r}")
    labels: List[Tuple[str, str]] = []
    body = line[brace + 1:end]
    pos = 0
    while pos < len(body):
        eq = body.find("=", pos)
        if eq < 0 or body[eq + 1:eq + 2] != '"':
            raise ValueError(f"line {lineno}: malformed labels: {line!r}")
        key = body[pos:eq].strip().lstrip(",").strip()
        pos = eq + 2
        value = []
        while pos < len(body):
            ch = body[pos]
            if ch == "\\":
                esc = body[pos + 1:pos + 2]
                value.append({"n": "\n", '"': '"', "\\": "\\"}.get(esc, esc))
                pos += 2
                continue
            if ch == '"':
                pos += 1
                break
            value.append(ch)
            pos += 1
        else:
            raise ValueError(f"line {lineno}: unterminated label: {line!r}")
        labels.append((key, "".join(value)))
    return name, tuple(sorted(labels)), line[end + 1:].strip()
