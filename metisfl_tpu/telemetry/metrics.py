"""Thread-safe metrics registry with Prometheus text exposition.

Zero-dependency counterpart of ``prometheus_client``: counters, gauges and
histograms keyed by label values, rendered in the text exposition format
(version 0.0.4) that Prometheus, victoriametrics and ``promtool`` ingest:

    https://prometheus.io/docs/instrumenting/exposition_formats/

One process-wide registry (``registry()``) backs every instrumented
subsystem — RPC transport, controller rounds, learner training, stores —
and is served over the ``GetMetrics`` RPC and the optional plain-HTTP
``/metrics`` listener (:mod:`metisfl_tpu.telemetry.httpd`). The whole
registry can be disabled (federation config ``telemetry.enabled=false``);
disabled instruments return before taking the lock, so the opt-out path
costs one attribute read per call site.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# latency-shaped default buckets (seconds): sub-ms RPC acks up through
# multi-second cold-jit training rounds
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def _escape(value: str) -> str:
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


# --------------------------------------------------------------------- #
# cardinality budgets (docs/OBSERVABILITY.md "Telemetry at scale")
# --------------------------------------------------------------------- #

# Companion families minted when a budgeted family collapses. Name
# strings live HERE (telemetry/__init__.py re-exports them as M_*
# constants — this module cannot import the package back).
SERIES_OVERFLOW_TOTAL = "metrics_series_overflow_total"
FAMILY_SERIES = "metrics_family_series"

# quantile series a collapsed gauge family exposes, and how many top-K
# offender series keep their original labels
SKETCH_QUANTILES = (0.5, 0.9, 0.99)
SKETCH_OFFENDERS = 10


def exact_quantile(ordered: Sequence[float], q: float) -> float:
    """Interpolated quantile of an already-sorted value list (0.0 when
    empty) — the ONE implementation behind exact-mode family quantiles
    and the controller's describe() digest columns."""
    if not ordered:
        return 0.0
    q = min(max(float(q), 0.0), 1.0)
    pos = q * (len(ordered) - 1)
    lo = int(math.floor(pos))
    hi = min(lo + 1, len(ordered) - 1)
    return ordered[lo] + (ordered[hi] - ordered[lo]) * (pos - lo)


class _FamilySketch:
    """Collapsed-family state: a quantile digest over observations
    (gauge families), a top-K offender tracker (gauges rank by current
    value, counters by accumulated space-saving count), per-rest-label
    totals (counter families — one remainder per non-budget label
    combination, so ``sum by (op)`` stays exact), and distinct-series
    accounting. O(compression + capacity + label-combos) memory and
    checkpoint bytes regardless of fleet size — manipulated only under
    the owning family's lock."""

    def __init__(self, kind: str, budget: int):
        from metisfl_tpu.telemetry.sketch import QuantileDigest, SpaceSaving

        self.kind = kind
        self.digest = QuantileDigest()
        self.topk = SpaceSaving(capacity=max(16, min(int(budget), 64)))
        self.seen: set = set()
        # restored distinct-series count: the checkpoint persists sketches
        # and the count, never the key list (that would be O(fleet) again)
        self.seen_floor = 0
        # counter families: sum across series, keyed by the non-budget
        # label values (bounded by the family's label-value combos)
        self.totals: Dict[Tuple[str, ...], float] = {}

    def total(self) -> float:
        return sum(self.totals.values())

    def distinct(self) -> int:
        return max(len(self.seen), self.seen_floor)

    def to_dict(self) -> Dict[str, object]:
        return {"kind": self.kind, "digest": self.digest.to_dict(),
                "topk": self.topk.to_dict(), "distinct": self.distinct(),
                "totals": [[list(rest), value]
                           for rest, value in self.totals.items()]}

    @classmethod
    def from_dict(cls, data: Dict[str, object]) -> "_FamilySketch":
        from metisfl_tpu.telemetry.sketch import QuantileDigest, SpaceSaving

        sketch = cls(str(data.get("kind", "gauge")), 48)
        sketch.digest = QuantileDigest.from_dict(data.get("digest") or {})
        sketch.topk = SpaceSaving.from_dict(data.get("topk") or {})
        sketch.seen_floor = int(data.get("distinct", 0) or 0)
        for rest, value in data.get("totals", []) or []:
            sketch.totals[tuple(str(v) for v in rest)] = float(value)
        if "total" in data:  # pre-rest-label state shape
            sketch.totals[()] = float(data.get("total") or 0.0)
        return sketch


def _format_value(value: float) -> str:
    if value == math.inf:
        return "+Inf"
    if value == -math.inf:
        return "-Inf"
    if float(value).is_integer() and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


class _Metric:
    """One named family; concrete series are keyed by label-value tuples."""

    kind = "untyped"

    def __init__(self, registry: "Registry", name: str, help: str,
                 labelnames: Sequence[str]):
        self._registry = registry
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        # cardinality budget (docs/OBSERVABILITY.md "Telemetry at
        # scale"): families registered with ``budget_label`` (the label
        # whose distinct values scale with the fleet — "learner",
        # "peer") collapse to sketches once the registry's budget is
        # armed and exceeded. 0 = exact behavior, one attribute check.
        self.budget_label = ""
        self._budget = 0
        self._sketch: Optional["_FamilySketch"] = None
        # companion-family handles, resolved once at first overflow (a
        # registry _get_or_create per hot-path observation would
        # serialize every budgeted family on the registry lock)
        self._overflow_handle: Optional["Counter"] = None
        self._series_handle: Optional["Gauge"] = None

    def _key(self, labels: Dict[str, object]) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(labels)}")
        return tuple(str(labels[k]) for k in self.labelnames)

    def _series(self, key: Tuple[str, ...]) -> str:
        if not key:
            return self.name
        pairs = ",".join(f'{k}="{_escape(v)}"'
                         for k, v in zip(self.labelnames, key))
        return f"{self.name}{{{pairs}}}"

    # -- cardinality budget (Counter/Gauge only; call sites hold _lock) --

    @staticmethod
    def _topk_key(key: Tuple[str, ...]) -> str:
        return "\x00".join(key)

    @staticmethod
    def _from_topk_key(tkey: str) -> Tuple[str, ...]:
        return tuple(tkey.split("\x00"))

    def _budget_index(self) -> int:
        return self.labelnames.index(self.budget_label)

    def _rest_key(self, key: Tuple[str, ...]) -> Tuple[str, ...]:
        """The non-budget label values of a series key (counter
        remainders are kept per combination of these, so per-label
        Prometheus sums stay exact past the budget)."""
        idx = self._budget_index()
        return tuple(v for i, v in enumerate(key) if i != idx)

    def _other_key(self, rest: Tuple[str, ...]) -> Tuple[str, ...]:
        """Full series key for a remainder: the budget label reads
        ``_other``, every other label keeps its real value — the family
        exposes one consistent label set across all its series."""
        values = list(rest)
        values.insert(self._budget_index(), "_other")
        return tuple(values)

    def _collapse_locked(self) -> None:
        """Exact → sketch transition: fold every existing series into
        the digest/top-K, drop the per-series map. Called under _lock
        the moment the budget is first exceeded."""
        sketch = _FamilySketch(self.kind, self._budget)
        for key, value in self._values.items():
            v = float(value)
            sketch.digest.add(v)
            if self.kind == "gauge":
                sketch.topk.update(self._topk_key(key), v)
            else:
                sketch.topk.offer(self._topk_key(key), v, value=v)
                rest = self._rest_key(key)
                sketch.totals[rest] = sketch.totals.get(rest, 0.0) + v
            sketch.seen.add(key)
        self._values.clear()
        self._sketch = sketch
        self._note_overflow(1)  # the series that tipped the budget
        self._note_family_series(sketch.distinct())

    def _observe_collapsed(self, key: Tuple[str, ...], value: float,
                           cumulative: bool) -> None:
        """One observation into the collapsed state. ``cumulative`` is
        the counter shape (value = increment, totals accumulate, top-K
        ranks by accumulated count); gauges feed the digest with the
        set value itself and rank offenders by CURRENT value."""
        sketch = self._sketch
        if key not in sketch.seen:
            sketch.seen.add(key)
            self._note_overflow(1)
            self._note_family_series(sketch.distinct())
        tkey = self._topk_key(key)
        if cumulative:
            rest = self._rest_key(key)
            sketch.totals[rest] = sketch.totals.get(rest, 0.0) + value
            sketch.topk.offer(tkey, value)
        else:
            sketch.digest.add(value)
            sketch.topk.update(tkey, value)

    def _note_overflow(self, n: int) -> None:
        handle = self._overflow_handle
        if handle is None:
            handle = self._overflow_handle = self._registry.counter(
                SERIES_OVERFLOW_TOTAL,
                "Series observed past a family's "
                "telemetry.cardinality_budget (the family is serving "
                "sketches, not exact series)", ("family",))
        handle.inc(n, family=self.name)

    def _note_family_series(self, distinct: int) -> None:
        handle = self._series_handle
        if handle is None:
            handle = self._series_handle = self._registry.gauge(
                FAMILY_SERIES,
                "Distinct series tracked by a budget-collapsed family "
                "(exact families expose their series instead)",
                ("family",))
        handle.set(distinct, family=self.name)

    def _render_collapsed(self, out: List[str]) -> None:
        """Collapsed exposition: O(budget) lines however large the
        fleet. Gauge families expose quantile series + top-K offenders
        (current value); counter families expose top-K offenders
        (accumulated count) + one ``<budget_label>="_other"`` remainder
        per non-budget label combination, so ``sum()`` — including
        ``sum by (<other label>)`` — over the family stays exact."""
        sketch = self._sketch
        if sketch.kind == "gauge":
            for q in SKETCH_QUANTILES:
                out.append(f'{self.name}{{quantile="{q:g}"}} '
                           f"{_format_value(sketch.digest.quantile(q))}")
        top = sketch.topk.top(SKETCH_OFFENDERS)
        shown: Dict[Tuple[str, ...], float] = {}
        for tkey, count, _err, last in top:
            key = self._from_topk_key(tkey)
            value = last if sketch.kind == "gauge" else count
            if sketch.kind != "gauge":
                rest = self._rest_key(key)
                shown[rest] = shown.get(rest, 0.0) + count
            out.append(f"{self._series(key)} {_format_value(value)}")
        if sketch.kind != "gauge":
            for rest in sorted(sketch.totals):
                remainder = max(0.0, sketch.totals[rest]
                                - shown.get(rest, 0.0))
                out.append(f"{self._series(self._other_key(rest))} "
                           f"{_format_value(remainder)}")

    # -- budget-aware queries (safe in exact mode too) -------------------

    def collapsed(self) -> bool:
        with self._lock:
            return self._sketch is not None

    def series_count(self) -> int:
        with self._lock:
            if self._sketch is not None:
                return self._sketch.distinct()
            return len(getattr(self, "_values", {}))

    def quantile(self, q: float) -> float:
        """Quantile across the family's series: exact (sorted values)
        below budget, digest estimate once a GAUGE family collapsed.
        Alert rules and the describe() digest columns read through
        this. A collapsed COUNTER family returns 0.0 — its running
        per-series totals cannot be digested (only the top-K offenders
        survive, whose counts are biased by eviction error), and a
        garbage quantile would false-fire alerts; use value/rate rules
        for counter families past the budget. Histogram families (list
        cells) report 0.0 — an alert rule over one is inert, never a
        poll-crashing TypeError."""
        with self._lock:
            if self._sketch is not None:
                if self._sketch.kind == "gauge":
                    return self._sketch.digest.quantile(q)
                return 0.0
            values = sorted(float(v) for v in self._values.values()
                            if isinstance(v, (int, float)))
        return exact_quantile(values, q)

    def total(self) -> float:
        """Sum across all series (counter semantics survive collapse
        exactly; a collapsed gauge sums its tracked offenders only).
        Histogram families (list cells) report 0.0 — see quantile()."""
        with self._lock:
            if self._sketch is not None:
                if self._sketch.kind != "gauge":
                    return self._sketch.total()
                return sum(last for _, _c, _e, last in
                           self._sketch.topk.top(0))
            return sum(v for v in self._values.values()
                       if isinstance(v, (int, float)))

    def sketch_summary(self, offenders: int = 5):
        """Compact collapsed-state view for RoundMetadata / status:
        distinct-series count, quantiles (gauge families), total
        (counter families), top offenders. None while exact."""
        with self._lock:
            if self._sketch is None:
                return None
            sketch = self._sketch
            out: Dict[str, object] = {"series": sketch.distinct()}
            if sketch.kind == "gauge":
                out["quantiles"] = {
                    f"{q:g}": round(sketch.digest.quantile(q), 6)
                    for q in SKETCH_QUANTILES}
            else:
                out["total"] = sketch.total()
            out["top"] = [
                [list(self._from_topk_key(tkey)),
                 round(last if sketch.kind == "gauge" else count, 6)]
                for tkey, count, _e, last in sketch.topk.top(offenders)]
            return out

    def prune_label_value(self, value: str) -> None:
        """Drop every series whose budget label equals ``value`` (the
        central leave()-time prune). The digest keeps its history —
        observations cannot be unobserved — but the key leaves the
        distinct set and the offender table."""
        if not self.budget_label:
            return
        idx = self._budget_index()
        with self._lock:
            if self._sketch is not None:
                sketch = self._sketch
                for key in [k for k in sketch.seen if k[idx] == value]:
                    sketch.seen.discard(key)
                for tkey, count, _e, _l in sketch.topk.top(0):
                    key = self._from_topk_key(tkey)
                    if len(key) > idx and key[idx] == value:
                        if sketch.kind != "gauge":
                            rest = self._rest_key(key)
                            sketch.totals[rest] = max(
                                0.0, sketch.totals.get(rest, 0.0) - count)
                        sketch.topk.drop(tkey)
                self._note_family_series(sketch.distinct())
                return
            for key in [k for k in self._values if k[idx] == value]:
                self._values.pop(key, None)

    def collect_state(self) -> Dict[str, object]:
        """Serializable family state for the fleet fabric's
        ``CollectTelemetry`` pull (telemetry/fabric.py): identity
        (name/kind/help/labels) plus either exact series values or, for
        a budget-collapsed family, the mergeable sketch dict. O(series)
        below budget, O(budget) past it — exactly the exposition's
        size posture."""
        state: Dict[str, object] = {
            "name": self.name, "kind": self.kind, "help": self.help,
            "labels": list(self.labelnames),
            "budget_label": self.budget_label,
        }
        with self._lock:
            if getattr(self, "_sketch", None) is not None:
                state["sketch"] = self._sketch.to_dict()
            elif self.kind == "histogram":
                state["buckets"] = list(self.buckets)
                state["cells"] = [[list(k), list(v)]
                                  for k, v in sorted(self._values.items())]
            else:
                state["series"] = [[list(k), float(v)]
                                   for k, v in sorted(self._values.items())]
        return state

    def budget_state(self):
        with self._lock:
            return (self._sketch.to_dict()
                    if self._sketch is not None else None)

    def restore_budget_state(self, state: Dict[str, object]) -> None:
        """Rehydrate collapsed state from a checkpoint: the family is
        collapsed from here on (pre-crash observations live only in the
        digest — exact series cannot be reconstructed from it)."""
        sketch = _FamilySketch.from_dict(state)
        with self._lock:
            for key, value in getattr(self, "_values", {}).items():
                v = float(value)
                sketch.digest.add(v)
                if sketch.kind == "gauge":
                    sketch.topk.update(self._topk_key(key), v)
                else:
                    sketch.topk.offer(self._topk_key(key), v, value=v)
                    rest = self._rest_key(key)
                    sketch.totals[rest] = sketch.totals.get(rest, 0.0) + v
                sketch.seen.add(key)
            self._values.clear()
            self._sketch = sketch
            self._note_family_series(sketch.distinct())


class Counter(_Metric):
    kind = "counter"

    def __init__(self, registry, name, help, labelnames):
        super().__init__(registry, name, help, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        if not self._registry.enabled:
            return
        if amount < 0:
            raise ValueError(f"{self.name}: counters only go up")
        key = self._key(labels)
        with self._lock:
            if self._sketch is not None:
                self._observe_collapsed(key, amount, cumulative=True)
                return
            self._values[key] = self._values.get(key, 0.0) + amount
            if self._budget and len(self._values) > self._budget:
                self._collapse_locked()

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            if self._sketch is not None:
                # best effort past the budget: an offender's tracked
                # count (gauges: last observed value), 0 for the crowd
                for tkey, count, _e, last in self._sketch.topk.top(0):
                    if self._from_topk_key(tkey) == key:
                        return last if self.kind == "gauge" else count
                return 0.0
            return self._values.get(key, 0.0)

    def remove(self, **labels) -> None:
        """Drop one series (bounded cardinality under churn: e.g. a
        departed learner's per-learner series must not live forever)."""
        key = self._key(labels)
        with self._lock:
            if self._sketch is not None:
                sketch = self._sketch
                sketch.seen.discard(key)
                tkey = self._topk_key(key)
                if tkey in sketch.topk:
                    if sketch.kind != "gauge":
                        count = dict((k, c) for k, c, _e, _l in
                                     sketch.topk.top(0)).get(tkey, 0.0)
                        rest = self._rest_key(key)
                        sketch.totals[rest] = max(
                            0.0, sketch.totals.get(rest, 0.0) - count)
                    sketch.topk.drop(tkey)
                self._note_family_series(sketch.distinct())
                return
            self._values.pop(key, None)

    def _render(self, out: List[str]) -> None:
        with self._lock:
            if self._sketch is not None:
                self._render_collapsed(out)
                return
            items = sorted(self._values.items())
        for key, value in items:
            out.append(f"{self._series(key)} {_format_value(value)}")

    def _reset(self) -> None:
        with self._lock:
            self._values.clear()
            self._sketch = None


class Gauge(Counter):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        if not self._registry.enabled:
            return
        key = self._key(labels)
        with self._lock:
            if self._sketch is not None:
                self._observe_collapsed(key, float(value), cumulative=False)
                return
            self._values[key] = float(value)
            if self._budget and len(self._values) > self._budget:
                self._collapse_locked()

    def inc(self, amount: float = 1.0, **labels) -> None:
        if not self._registry.enabled:
            return
        key = self._key(labels)
        with self._lock:
            if self._sketch is not None:
                # no exact current value to read back past the budget:
                # treat the delta as the observation (no budgeted family
                # in this repo uses gauge inc/dec)
                self._observe_collapsed(key, float(amount),
                                        cumulative=False)
                return
            self._values[key] = self._values.get(key, 0.0) + amount
            if self._budget and len(self._values) > self._budget:
                self._collapse_locked()

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, registry, name, help, labelnames,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(registry, name, help, labelnames)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        # key -> [per-bucket counts..., +Inf count, sum]
        self._values: Dict[Tuple[str, ...], List[float]] = {}

    def observe(self, value: float, **labels) -> None:
        if not self._registry.enabled:
            return
        key = self._key(labels)
        value = float(value)
        with self._lock:
            cells = self._values.get(key)
            if cells is None:
                cells = self._values[key] = [0.0] * (len(self.buckets) + 2)
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    cells[i] += 1
            cells[-2] += 1  # +Inf
            cells[-1] += value

    def count(self, **labels) -> float:
        with self._lock:
            cells = self._values.get(self._key(labels))
            return cells[-2] if cells else 0.0

    def sum(self, **labels) -> float:
        with self._lock:
            cells = self._values.get(self._key(labels))
            return cells[-1] if cells else 0.0

    def _render(self, out: List[str]) -> None:
        with self._lock:
            items = sorted((k, list(v)) for k, v in self._values.items())
        for key, cells in items:
            for bound, count in zip(self.buckets, cells):
                series = self._series_with(key, ("le", _format_value(bound)))
                out.append(f"{self.name}_bucket{series} "
                           f"{_format_value(count)}")
            series = self._series_with(key, ("le", "+Inf"))
            out.append(f"{self.name}_bucket{series} "
                       f"{_format_value(cells[-2])}")
            base = self._series(key)[len(self.name):]
            out.append(f"{self.name}_sum{base} {_format_value(cells[-1])}")
            out.append(f"{self.name}_count{base} {_format_value(cells[-2])}")

    def add_cells(self, key: Sequence[str], cells: Sequence[float]) -> None:
        """Element-wise fold of one series' raw bucket cells (fleet
        fabric merge, telemetry/fabric.py): histogram cells are counts +
        a sum, so cross-process merge is plain addition."""
        key = tuple(str(v) for v in key)
        if len(key) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected {len(self.labelnames)} label "
                f"values, got {len(key)}")
        want = len(self.buckets) + 2
        if len(cells) != want:
            raise ValueError(
                f"{self.name}: expected {want} cells, got {len(cells)}")
        with self._lock:
            mine = self._values.get(key)
            if mine is None:
                mine = self._values[key] = [0.0] * want
            for i, v in enumerate(cells):
                mine[i] += float(v)

    def _series_with(self, key: Tuple[str, ...],
                     extra: Tuple[str, str]) -> str:
        pairs = [f'{k}="{_escape(v)}"' for k, v in zip(self.labelnames, key)]
        pairs.append(f'{extra[0]}="{_escape(extra[1])}"')
        return "{" + ",".join(pairs) + "}"

    def _reset(self) -> None:
        with self._lock:
            self._values.clear()


class Registry:
    """Named metric families; idempotent registration (a second
    ``counter()`` call with the same name returns the first family, so
    module-level instrumentation never double-registers)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: "Dict[str, _Metric]" = {}
        self.enabled = True
        self._budget = 0

    def _get_or_create(self, cls, name: str, help: str,
                       labelnames: Sequence[str], budget_label: str = "",
                       **kwargs) -> _Metric:
        if budget_label and budget_label not in labelnames:
            raise ValueError(
                f"{name}: budget_label {budget_label!r} is not one of the "
                f"labels {tuple(labelnames)}")
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls) or (
                        existing.labelnames != tuple(labelnames)):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind} with labels {existing.labelnames}")
                if budget_label and not existing.budget_label:
                    existing.budget_label = budget_label
                    existing._budget = self._budget
                return existing
            metric = cls(self, name, help, labelnames, **kwargs)
            if budget_label:
                metric.budget_label = budget_label
                metric._budget = self._budget
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = (),
                budget_label: str = "") -> Counter:
        return self._get_or_create(Counter, name, help, labelnames,
                                   budget_label=budget_label)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = (),
              budget_label: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames,
                                   budget_label=budget_label)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        """An already-registered family by name (alert rules and the
        describe() digest columns read through this), or None."""
        with self._lock:
            return self._metrics.get(name)

    # -- cardinality budget (docs/OBSERVABILITY.md "Telemetry at scale") --

    def set_cardinality_budget(self, budget: int) -> None:
        """Arm (or re-arm) the per-family series budget on every family
        registered with a ``budget_label``. 0 disarms — but an already-
        collapsed family stays collapsed (exact series cannot be
        reconstructed from a sketch; ``reset()`` clears it)."""
        budget = max(0, int(budget))
        with self._lock:
            self._budget = budget
            families = [m for m in self._metrics.values() if m.budget_label]
        for family in families:
            with family._lock:
                family._budget = budget
                over = (budget and family._sketch is None
                        and len(family._values) > budget)
                if over:
                    family._collapse_locked()

    def cardinality_budget(self) -> int:
        return self._budget

    def budget_families(self) -> List[_Metric]:
        """Every family registered with a budget label (the per-learner
        set the central ``telemetry.prune_learner`` helper prunes)."""
        with self._lock:
            return [m for m in self._metrics.values() if m.budget_label]

    def prune_label_value(self, value: str) -> None:
        """Drop every series carrying ``value`` in its budget label
        across all budgeted families — the one call leave() needs."""
        for family in self.budget_families():
            family.prune_label_value(value)

    def collect_state(self) -> List[Dict[str, object]]:
        """Every family's :meth:`_Metric.collect_state`, name-sorted —
        the metrics section of a ``CollectTelemetry`` reply. Families
        with no series yet are skipped (the exposition skips them too,
        keeping the single-peer fleet merge bit-identical)."""
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        out: List[Dict[str, object]] = []
        for metric in metrics:
            state = metric.collect_state()
            if state.get("series") or state.get("cells") \
                    or state.get("sketch"):
                out.append(state)
        return out

    def budget_state(self) -> Dict[str, Dict]:
        """Serialized sketches of every collapsed family (checkpoint
        payload: O(budget) bytes however large the fleet; empty dict
        when nothing has collapsed)."""
        state: Dict[str, Dict] = {}
        for family in self.budget_families():
            data = family.budget_state()
            if data is not None:
                state[family.name] = data
        return state

    def restore_budget_state(self, state: Dict[str, Dict]) -> None:
        """Rehydrate collapsed families from a checkpoint (``--resume``:
        digests survive a controller crash). Families not registered in
        this process are skipped."""
        for name, data in (state or {}).items():
            family = self.get(name)
            if family is not None and family.budget_label:
                family.restore_budget_state(data)

    def render(self) -> str:
        """Prometheus text exposition (format version 0.0.4)."""
        out: List[str] = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for metric in metrics:
            body: List[str] = []
            metric._render(body)
            if not body:
                continue
            if metric.help:
                out.append(f"# HELP {metric.name} "
                           f"{metric.help.replace(chr(10), ' ')}")
            out.append(f"# TYPE {metric.name} {metric.kind}")
            out.extend(body)
        return "\n".join(out) + ("\n" if out else "")

    def reset(self) -> None:
        """Zero every series and disarm the cardinality budget (tests);
        families stay registered so module-level instrument handles
        keep working."""
        with self._lock:
            self._budget = 0
            metrics = list(self._metrics.values())
        for metric in metrics:
            metric._reset()
            if metric.budget_label:
                with metric._lock:
                    metric._budget = 0


_REGISTRY = Registry()


def registry() -> Registry:
    return _REGISTRY


def enabled() -> bool:
    return _REGISTRY.enabled


def set_enabled(value: bool) -> None:
    _REGISTRY.enabled = bool(value)


def parse_exposition(text: str) -> Dict[str, Dict[Tuple[Tuple[str, str], ...], float]]:
    """Parse a text exposition into ``{series_name: {labels: value}}``
    (labels as a sorted tuple of (key, value) pairs). Raises ValueError
    on malformed lines — the scrape-compatibility check tests lean on.
    """
    series: Dict[str, Dict[Tuple[Tuple[str, str], ...], float]] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name, labels, rest = _parse_series(line, lineno)
        parts = rest.split()
        if not parts:
            raise ValueError(f"line {lineno}: missing value: {line!r}")
        raw = parts[0]
        if raw == "+Inf":
            value = math.inf
        elif raw == "-Inf":
            value = -math.inf
        else:
            try:
                value = float(raw)
            except ValueError:
                raise ValueError(
                    f"line {lineno}: bad value {raw!r}") from None
        series.setdefault(name, {})[labels] = value
    return series


def _parse_series(line: str, lineno: int):
    brace = line.find("{")
    if brace < 0:
        name, _, rest = line.partition(" ")
        if not name or not rest:
            raise ValueError(f"line {lineno}: malformed sample: {line!r}")
        return name, (), rest
    name = line[:brace]
    end = line.find("}", brace)
    if end < 0 or not name:
        raise ValueError(f"line {lineno}: malformed labels: {line!r}")
    labels: List[Tuple[str, str]] = []
    body = line[brace + 1:end]
    pos = 0
    while pos < len(body):
        eq = body.find("=", pos)
        if eq < 0 or body[eq + 1:eq + 2] != '"':
            raise ValueError(f"line {lineno}: malformed labels: {line!r}")
        key = body[pos:eq].strip().lstrip(",").strip()
        pos = eq + 2
        value = []
        while pos < len(body):
            ch = body[pos]
            if ch == "\\":
                esc = body[pos + 1:pos + 2]
                value.append({"n": "\n", '"': '"', "\\": "\\"}.get(esc, esc))
                pos += 2
                continue
            if ch == '"':
                pos += 1
                break
            value.append(ch)
            pos += 1
        else:
            raise ValueError(f"line {lineno}: unterminated label: {line!r}")
        labels.append((key, "".join(value)))
    return name, tuple(sorted(labels)), line[end + 1:].strip()
