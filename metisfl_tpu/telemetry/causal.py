"""Causal analysis over collected trace spans: critical paths + lints.

The trace layer (telemetry/trace.py) already records every hop of a
federation round — controller dispatch, learner train, uplink ingest,
slice fold, finalize — and of a serving request (router forward, replica
predict/generate, decode slots) as spans stitched by wire-propagated
trace ids. This module turns one trace's spans into *attribution*:

- :func:`critical_path` walks the span tree and returns the longest
  causal chain with per-edge self-time — "round 7: 83% = learner_3
  train → uplink RTT → slice_1 fold". The walk is hierarchical and
  fork-join aware: at each node it greedily covers the node's window
  backwards from its end with the children whose *subtrees* finish
  latest (a child's subtree can outlive the child itself — the learner
  task span ends after the dispatch span that caused it), recursing into
  each selected child with the remaining window. Time a node's window
  not covered by selected children is the node's *self* time (e.g. the
  uplink RTT gap between a train span ending and its ingest landing).
  Self-times telescope: they sum to the root's duration exactly.
- Spans flagged ``attrs.passive`` (the controller's barrier wait) are
  *skipped* as chain candidates: a wait explains nothing — the thing it
  waited on does.
- :func:`orphan_spans` is the causality lint: spans whose parent id
  resolves to no collected span. Outside the fabric's reported
  ring-eviction budget (``spans_lost``), an orphan is a propagation bug
  (a hop that dropped the context), not a rendering detail.

``python -m metisfl_tpu.telemetry --causal-smoke`` runs the CI gate:
context propagation over real gRPC with a deliberately slowed learner
must name that learner as the dominant edge (and a control run must
not), the orphan lint must pass, and per-RPC propagation overhead must
stay within budget.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from metisfl_tpu.telemetry import trace as _trace

# spans with this attribute are never chain candidates: their duration
# measures waiting, and the critical path wants the cause of the wait
PASSIVE_ATTR = "passive"

# edge labels prefer the per-role identity when one is attached
_IDENTITY_ATTRS = ("learner", "slice", "replica")


def _end_s(span: Dict[str, Any]) -> float:
    return float(span.get("start", 0.0)) + float(span.get("dur_ms",
                                                          0.0)) / 1e3


def _is_passive(span: Dict[str, Any]) -> bool:
    return bool((span.get("attrs") or {}).get(PASSIVE_ATTR))


def edge_label(span: Dict[str, Any]) -> str:
    """``who/what`` for one chain edge: the fabric's peer name when the
    record was fleet-collected, else a role identity attribute (learner /
    slice / replica), else the recording process's service name."""
    attrs = span.get("attrs") or {}
    who = span.get("peer") or ""
    if not who:
        for key in _IDENTITY_ATTRS:
            if attrs.get(key):
                who = str(attrs[key])
                break
    who = who or str(span.get("service") or "?")
    return f"{who}/{span.get('name', '?')}"


def dedupe_spans(spans: Iterable[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """One record per span id. The driver can legitimately collect a span
    twice (live fabric pull + shutdown file merge); keep the richer
    record (a fleet-collected one carries ``peer`` and a skew-corrected
    ``start``)."""
    by_id: Dict[str, Dict[str, Any]] = {}
    for span in spans:
        sid = span.get("span")
        if not sid:
            continue
        held = by_id.get(sid)
        if held is None or (span.get("peer") and not held.get("peer")):
            by_id[sid] = span
    return list(by_id.values())


def orphan_spans(spans: Iterable[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Spans whose parent id resolves to no collected span — causality
    gaps. A clean traced run has none; a run that reported ring
    evictions (``spans_lost``) may have up to that many."""
    records = dedupe_spans(spans)
    ids = {s["span"] for s in records}
    return [s for s in records
            if s.get("parent") and s["parent"] not in ids]


def round_roots(spans: Iterable[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """The controller round root spans, oldest first."""
    roots = [s for s in spans
             if s.get("name") == "round"
             and "round" in (s.get("attrs") or {})]
    roots.sort(key=lambda s: (s.get("start", 0.0)))
    return roots


def critical_path(spans: Iterable[Dict[str, Any]],
                  root_span_id: Optional[str] = None,
                  trace_id: Optional[str] = None
                  ) -> Optional[Dict[str, Any]]:
    """The longest causal chain through one trace.

    ``spans`` may hold many traces; ``trace_id`` (or the chosen root's
    trace) selects one. The root defaults to the no-parent span with the
    largest window in that trace. Same-trace spans whose parent was
    never collected (single-process analysis of a multi-process round,
    ring eviction) attach under the root as *detached* subtrees so their
    time still attributes. Returns None when no root exists.
    """
    records = dedupe_spans(spans)
    if trace_id is not None:
        records = [s for s in records if s.get("trace") == trace_id]
    if not records:
        return None
    by_id = {s["span"]: s for s in records}
    root: Optional[Dict[str, Any]] = None
    if root_span_id is not None:
        root = by_id.get(root_span_id)
        if root is None:
            return None
        records = [s for s in records
                   if s.get("trace") == root.get("trace")]
        by_id = {s["span"]: s for s in records}
    else:
        tops = [s for s in records
                if not s.get("parent") or s["parent"] not in by_id]
        if trace_id is None and tops:
            # widest top-level window wins; then keep only its trace
            root = max(tops, key=lambda s: float(s.get("dur_ms", 0.0)))
            records = [s for s in records
                       if s.get("trace") == root.get("trace")]
            by_id = {s["span"]: s for s in records}
        elif tops:
            root = max(tops, key=lambda s: float(s.get("dur_ms", 0.0)))
    if root is None:
        return None

    children: Dict[str, List[str]] = {}
    detached = 0
    for s in records:
        if s is root:
            continue
        parent = s.get("parent") or ""
        if parent in by_id and parent != s["span"]:
            children.setdefault(parent, []).append(s["span"])
        else:
            # same trace, parent never collected: attach under the root
            # so its subtree still attributes (flagged in the result)
            children.setdefault(root["span"], []).append(s["span"])
            detached += 1

    # subtree end: a span's own end or its latest descendant's — async
    # children (a train span outliving the dispatch that caused it)
    # extend the parent's causal reach
    sub_end: Dict[str, float] = {}

    def _subtree_end(sid: str) -> float:
        stack = [(sid, False)]
        while stack:
            cur, expanded = stack.pop()
            if cur in sub_end:
                continue
            kids = children.get(cur, ())
            if expanded or not kids:
                end = _end_s(by_id[cur])
                for k in kids:
                    end = max(end, sub_end.get(k, 0.0))
                sub_end[cur] = end
            else:
                stack.append((cur, True))
                stack.extend((k, False) for k in kids
                             if k not in sub_end)
        return sub_end[sid]

    _subtree_end(root["span"])

    edges: List[Dict[str, Any]] = []
    root_lo = float(root.get("start", 0.0))
    root_hi = max(_end_s(root), root_lo)
    # (span id, window lo, window hi) — pre-order, children pushed in
    # reverse chronological order so the chain pops chronologically
    walk: List[Tuple[str, float, float]] = [(root["span"], root_lo,
                                             root_hi)]
    while walk:
        sid, lo, hi = walk.pop()
        node = by_id[sid]
        kids = sorted(
            (k for k in children.get(sid, ())
             if not _is_passive(by_id[k])),
            key=lambda k: sub_end.get(k, 0.0), reverse=True)
        cursor = hi
        picked: List[Tuple[str, float, float]] = []
        for k in kids:
            k_lo = float(by_id[k].get("start", 0.0))
            k_hi = min(sub_end.get(k, 0.0), cursor)
            if k_hi <= lo or k_lo >= cursor or k_hi <= max(k_lo, lo):
                continue
            picked.append((k, max(k_lo, lo), k_hi))
            cursor = max(k_lo, lo)
            if cursor <= lo:
                break
        covered = sum(k_hi - k_lo for _, k_lo, k_hi in picked)
        self_ms = max(0.0, ((hi - lo) - covered) * 1e3)
        edges.append({
            "name": node.get("name", "?"),
            "label": edge_label(node),
            "service": node.get("peer") or node.get("service") or "?",
            "span": sid,
            "start": round(lo, 6),
            "self_ms": round(self_ms, 3),
        })
        walk.extend(reversed(picked))

    total_ms = max((root_hi - root_lo) * 1e3, 1e-9)
    for edge in edges:
        edge["share"] = round(edge["self_ms"] / total_ms, 4)
    dominant = max(edges, key=lambda e: e["self_ms"]) if edges else None
    root_self = edges[0]["self_ms"] if edges else 0.0
    result = {
        "trace": root.get("trace", ""),
        "root": root.get("name", "?"),
        "root_span": root["span"],
        "start": root_lo,
        "total_ms": round(total_ms, 3),
        # share of the root window the chain attributes BELOW the root
        # (the root's own self-time is unexplained gap)
        "coverage": round(max(0.0, 1.0 - root_self / total_ms), 4),
        "edges": edges,
        "dominant": dominant["label"] if dominant else "",
        "spans": len(records),
        "detached": detached,
    }
    attrs = root.get("attrs") or {}
    if "round" in attrs:
        try:
            result["round"] = int(attrs["round"])
        except (TypeError, ValueError):
            pass
    if "request_id" in attrs:
        result["request_id"] = str(attrs["request_id"])
    return result


def round_critical_path(spans: Iterable[Dict[str, Any]],
                        round_no: Optional[int] = None
                        ) -> Optional[Dict[str, Any]]:
    """Critical path of one federation round (the latest completed one
    when ``round_no`` is omitted). Selects the round's trace by its root
    span, so co-collected serving traces never leak in."""
    records = dedupe_spans(spans)
    roots = round_roots(records)
    if round_no is not None:
        roots = [r for r in roots
                 if str((r.get("attrs") or {}).get("round"))
                 == str(round_no)]
    if not roots:
        return None
    root = roots[-1]
    return critical_path(records, root_span_id=root["span"])


def _fmt_ms(ms: float) -> str:
    return f"{ms / 1e3:.2f}s" if ms >= 1e3 else f"{ms:.0f}ms"


def render(cp: Dict[str, Any], min_share: float = 0.05,
           max_edges: int = 6) -> str:
    """One-line summary: ``round 7: 83% = learner_3/learner.train 1.2s
    -> controller/round.aggregate 0.3s`` — the chain's heaviest edges in
    causal order."""
    if "round" in cp:
        subject = f"round {cp['round']}"
    elif cp.get("request_id"):
        subject = f"request {cp['request_id'][:12]}"
    else:
        subject = f"trace {str(cp.get('trace', ''))[:8]}"
    heavy = [e for e in cp.get("edges", ())[1:]
             if e.get("share", 0.0) >= min_share]
    heavy.sort(key=lambda e: e["start"])
    heavy = heavy[:max_edges]
    if not heavy:
        return (f"{subject}: no attributable chain "
                f"({_fmt_ms(cp.get('total_ms', 0.0))} total)")
    chain = " -> ".join(
        f"{e['label']} {_fmt_ms(e['self_ms'])}" for e in heavy)
    return (f"{subject}: {cp.get('coverage', 0.0) * 100:.0f}% = {chain}"
            f"  [{_fmt_ms(cp.get('total_ms', 0.0))} total]")


def render_edges(cp: Dict[str, Any]) -> str:
    """Full chain, one edge per line, causal (walk) order."""
    lines = [render(cp)]
    for e in cp.get("edges", ()):
        lines.append(f"  {e['share'] * 100:5.1f}%  "
                     f"{_fmt_ms(e['self_ms']):>8}  {e['label']}")
    if cp.get("detached"):
        lines.append(f"  ({cp['detached']} detached subtree(s) attached "
                     "at the root: parents not collected here)")
    return "\n".join(lines)


def summarize(cp: Dict[str, Any], top: int = 5) -> Dict[str, Any]:
    """Compact per-round summary (RoundProfile.critical_path, the fleet
    snapshot's ``crit`` entry): heaviest edges only."""
    edges = sorted(cp.get("edges", ()), key=lambda e: -e["self_ms"])[:top]
    out = {
        "trace": cp.get("trace", ""),
        "total_ms": cp.get("total_ms", 0.0),
        "coverage": cp.get("coverage", 0.0),
        "dominant": cp.get("dominant", ""),
        "edges": [{"label": e["label"], "self_ms": e["self_ms"],
                   "share": e["share"]} for e in edges],
        "detached": cp.get("detached", 0),
    }
    if "round" in cp:
        out["round"] = cp["round"]
    return out


# --------------------------------------------------------------------- #
# CI smoke gate (scripts/chaos_smoke.sh)
# --------------------------------------------------------------------- #


def _propagation_overhead_ns(iters: int = 20000) -> float:
    """Mean cost of one RPC's worth of context propagation: inject on
    the client (outbound_metadata) + extract on the server."""
    with _trace.span("causal.smoke.bench", parent=None) as sp:
        with sp.activate():
            t0 = time.perf_counter()
            for _ in range(iters):
                md = _trace.outbound_metadata()
                _trace.extract(md)
            elapsed = time.perf_counter() - t0
    return elapsed / iters * 1e9


def _smoke_round(slow_factor: float, serial: int,
                 base_s: float = 0.05) -> List[Dict[str, Any]]:
    """One in-process federation round over REAL gRPC: controller
    dispatches to two learner servers (learner_1 slowed by
    ``slow_factor``), each learner reports its uplink back to the
    controller server, and the controller folds through a slice server —
    every hop context-propagated through comm/rpc.py. Returns the
    collected finished-span records."""
    from metisfl_tpu.comm.rpc import BytesService, RpcClient, RpcServer

    _trace.configure(enabled=True, service="causal-smoke", dir="")
    _trace.configure_ring(4096)
    cursor_start = _trace.spans_since(0)[1]

    uplinks: List[str] = []

    def _train(name: str, factor: float):
        def handler(payload: bytes) -> bytes:
            with _trace.span("learner.train",
                             attrs={"learner": name}) as sp:
                with sp.activate():
                    time.sleep(base_s * factor)
                    ctrl_client.call("TrainDone",
                                     name.encode("utf-8"))
            return b"ok"
        return handler

    def _train_done(payload: bytes) -> bytes:
        with _trace.span("round.store_insert",
                         attrs={"learner": payload.decode("utf-8")}):
            time.sleep(0.002)
        uplinks.append(payload.decode("utf-8"))
        return b"ok"

    def _fold(payload: bytes) -> bytes:
        # longer than an unslowed train with margin: the CONTROL run's
        # dominant edge is deterministically the fold, never a learner
        with _trace.span("slice.fold", attrs={"slice": "slice_0"}):
            time.sleep(base_s * 2.4)
        return b"ok"

    ctrl = RpcServer("127.0.0.1", 0)
    ctrl.add_service(BytesService("smoke.Controller",
                                  {"TrainDone": _train_done}))
    ctrl_port = ctrl.start()
    ctrl_client = RpcClient("127.0.0.1", ctrl_port, "smoke.Controller")

    learners = {}
    for i in range(2):
        name = f"learner_{i}"
        server = RpcServer("127.0.0.1", 0)
        factor = slow_factor if name == "learner_1" else 1.0
        server.add_service(BytesService("smoke.Learner",
                                        {"RunTask": _train(name, factor)}))
        port = server.start()
        learners[name] = (server,
                          RpcClient("127.0.0.1", port, "smoke.Learner"))

    slice_srv = RpcServer("127.0.0.1", 0)
    slice_srv.add_service(BytesService("smoke.Slice", {"FoldPartial":
                                                       _fold}))
    slice_port = slice_srv.start()
    slice_client = RpcClient("127.0.0.1", slice_port, "smoke.Slice")

    try:
        root = _trace.span("round", parent=None,
                           trace_id=_trace.round_trace_id(serial),
                           attrs={"round": serial})
        with root.activate():
            dispatch = _trace.span("round.dispatch")
            with dispatch, dispatch.activate():
                ctx = _trace.current_context()

                def _dispatch_one(client):
                    with _trace.use_context(ctx):
                        client.call("RunTask", b"go", timeout=30.0)

                threads = [threading.Thread(target=_dispatch_one,
                                            args=(client,))
                           for _, client in learners.values()]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
            with _trace.span("round.aggregate") as agg:
                with agg.activate():
                    slice_client.call("FoldPartial", b"fold",
                                      timeout=30.0)
        root.end()
    finally:
        for server, client in learners.values():
            client.close()
            server.stop(grace=0.2)
        slice_client.close()
        ctrl_client.close()
        ctrl.stop(grace=0.2)
    if sorted(uplinks) != ["learner_0", "learner_1"]:
        raise RuntimeError(f"uplinks incomplete: {uplinks}")
    records, _cursor, lost = _trace.spans_since(cursor_start)
    if lost:
        raise RuntimeError(f"span ring evicted {lost} records mid-smoke")
    return [r for r in records
            if r.get("trace") == _trace.round_trace_id(serial)]


def _smoke(overhead_budget_ns: float = 50000.0) -> int:
    """Exit 0 when every gate passes: single-trace propagation across
    dispatch → train → uplink → fold, orphan lint clean, the slowed
    learner dominant (and NOT dominant in the control run), propagation
    overhead within budget."""
    failures: List[str] = []

    slow = _smoke_round(slow_factor=8.0, serial=7)
    control = _smoke_round(slow_factor=1.0, serial=8)

    for tag, records in (("slow", slow), ("control", control)):
        names = {r.get("name") for r in records}
        need = {"round", "round.dispatch", "rpc.server/RunTask",
                "learner.train", "rpc.server/TrainDone",
                "round.store_insert", "round.aggregate",
                "rpc.server/FoldPartial", "slice.fold"}
        missing = need - names
        if missing:
            failures.append(f"{tag}: hops missing from the trace: "
                            f"{sorted(missing)}")
        if len({r.get("trace") for r in records}) != 1:
            failures.append(f"{tag}: expected ONE trace id, got "
                            f"{len({r.get('trace') for r in records})}")
        orphans = orphan_spans(records)
        if orphans:
            failures.append(
                f"{tag}: orphan lint: {len(orphans)} span(s) with "
                f"uncollected parents outside a zero spans_lost budget: "
                f"{[o.get('name') for o in orphans]}")

    cp_slow = round_critical_path(slow, round_no=7)
    cp_control = round_critical_path(control, round_no=8)
    if cp_slow is None or cp_control is None:
        failures.append("critical path could not be computed")
    else:
        print("slow:    " + render(cp_slow))
        print("control: " + render(cp_control))
        if "learner_1" not in cp_slow["dominant"]:
            failures.append("slow run: dominant edge is "
                            f"{cp_slow['dominant']!r}, expected the "
                            "slowed learner_1")
        if "learner_1" in cp_control["dominant"]:
            failures.append("control run: dominant edge "
                            f"{cp_control['dominant']!r} names the "
                            "learner that was NOT slowed")
        if cp_slow["coverage"] < 0.9:
            failures.append(f"slow run: chain coverage "
                            f"{cp_slow['coverage']:.2f} < 0.90")

    overhead = _propagation_overhead_ns()
    print(f"propagation overhead: {overhead:.0f}ns/RPC "
          f"(budget {overhead_budget_ns:.0f}ns)")
    if overhead > overhead_budget_ns:
        failures.append(f"propagation overhead {overhead:.0f}ns/RPC "
                        f"over budget {overhead_budget_ns:.0f}ns")

    if failures:
        for failure in failures:
            print(f"FAIL: {failure}")
        return 1
    print("causal-smoke: all gates passed")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        "metisfl_tpu.telemetry.causal",
        description="causal trace analysis utilities")
    parser.add_argument("--smoke", action="store_true",
                        help="run the CI causal-tracing gate (in-process "
                             "real-gRPC hops; exit 1 on failure)")
    parser.add_argument("--overhead-budget-ns", type=float,
                        default=50000.0,
                        help="smoke: per-RPC propagation overhead bound")
    args = parser.parse_args(argv)
    if args.smoke:
        return _smoke(overhead_budget_ns=args.overhead_budget_ns)
    parser.print_usage()
    return 2


if __name__ == "__main__":
    import sys

    sys.exit(main())
