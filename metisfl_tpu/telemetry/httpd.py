"""Optional plain-HTTP ``/metrics`` listener.

gRPC-native scrapers can use the ``GetMetrics`` RPC; a stock Prometheus
server speaks plain HTTP, so controller and learner can additionally bind
this tiny stdlib listener (federation config ``telemetry.http_port`` /
learner ``--metrics-port``). Serves the process registry's text
exposition at ``/metrics`` (and ``/``); anything else is 404.
"""

from __future__ import annotations

import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from metisfl_tpu.telemetry import metrics as _metrics

logger = logging.getLogger("metisfl_tpu.telemetry")

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsHTTPServer:
    """A daemon-threaded scrape endpoint; ``close()`` unbinds the port."""

    def __init__(self, port: int, host: str = "0.0.0.0", registry=None):
        registry = registry or _metrics.registry()

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - stdlib API
                if self.path.split("?")[0] not in ("/", "/metrics"):
                    self.send_error(404)
                    return
                body = registry.render().encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):  # scrapes are not app logs
                logger.debug("metrics http: " + fmt, *args)

        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="metrics-http", daemon=True)
        self._thread.start()
        logger.info("metrics http listener on %s:%d", host, self.port)

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def start_metrics_http(port: int, host: str = "0.0.0.0",
                       registry=None) -> Optional[MetricsHTTPServer]:
    """Bind a /metrics listener; port 0 or failure → None (metrics stay
    reachable over the GetMetrics RPC — a taken port must not kill the
    federation process)."""
    if port <= 0:
        return None
    try:
        return MetricsHTTPServer(port, host=host, registry=registry)
    except OSError as exc:
        logger.warning("metrics http listener on port %d failed: %s",
                       port, exc)
        return None
