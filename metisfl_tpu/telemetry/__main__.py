"""Trace-viewer CLI: ``python -m metisfl_tpu.telemetry <dir-or-jsonl>...``.

Renders the span trees recorded in one or more JSONL trace sinks
(:mod:`metisfl_tpu.telemetry.trace`) — typically the ``telemetry/``
directory a driver run leaves in its workdir, where controller and
learner files stitch into one tree per federation round via the
wire-propagated trace ids.

    python -m metisfl_tpu.telemetry /tmp/metisfl_tpu_x/telemetry
    python -m metisfl_tpu.telemetry traces.jsonl --round 3
    python -m metisfl_tpu.telemetry traces.jsonl --trace 01ab... --attrs

``--postmortem`` switches to flight-recorder mode: the arguments are
post-mortem bundle files (or directories of them — typically the
``postmortem/`` dir a driver run leaves in its workdir) and the output
is each crashed process's pre-crash timeline — its event-journal tail,
the spans that were still open when it died, and its last metrics
snapshot:

    python -m metisfl_tpu.telemetry --postmortem <workdir>/postmortem
"""

from __future__ import annotations

import glob
import json
import os
import sys
import time
from typing import Any, Dict, Iterable, List, Optional


def load_spans(paths: Iterable[str]) -> List[dict]:
    """All span records from JSONL files / directories of them. Unreadable
    lines are skipped (a crashed process can leave a torn tail line)."""
    spans: List[dict] = []
    for path in paths:
        files = (sorted(glob.glob(os.path.join(path, "*.jsonl")))
                 if os.path.isdir(path) else [path])
        for name in files:
            with open(name) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        record = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if isinstance(record, dict) and record.get("span"):
                        spans.append(record)
    return spans


def _fmt_dur(ms: float) -> str:
    return f"{ms / 1e3:.2f}s" if ms >= 1e3 else f"{ms:.1f}ms"


def render_trace(spans: List[dict], show_attrs: bool = False) -> str:
    """One trace's spans (same trace id) as an indented tree, children
    ordered by start time. Spans whose parent never landed in the sink
    (e.g. a process killed mid-round) render as roots."""
    by_id: Dict[str, dict] = {s["span"]: s for s in spans}
    children: Dict[str, List[dict]] = {}
    roots: List[dict] = []
    for s in spans:
        parent = s.get("parent", "")
        if parent and parent in by_id:
            children.setdefault(parent, []).append(s)
        else:
            roots.append(s)
    for kids in children.values():
        kids.sort(key=lambda s: s.get("start", 0.0))
    roots.sort(key=lambda s: s.get("start", 0.0))

    lines: List[str] = []

    def _walk(span: dict, prefix: str, tail: bool,
              root: bool = False) -> None:
        connector = "" if root else ("└─ " if tail else "├─ ")
        label = (f"{span.get('name', '?')} "
                 f"({_fmt_dur(float(span.get('dur_ms', 0.0)))}) "
                 f"[{span.get('service', '?')}]")
        if show_attrs and span.get("attrs"):
            attrs = " ".join(f"{k}={v}" for k, v in span["attrs"].items())
            label += f"  {{{attrs}}}"
        lines.append(prefix + connector + label)
        kids = children.get(span["span"], [])
        child_prefix = prefix if root else (
            prefix + ("   " if tail else "│  "))
        for i, kid in enumerate(kids):
            _walk(kid, child_prefix, i == len(kids) - 1)

    for root in roots:
        _walk(root, "", True, root=True)
    return "\n".join(lines)


def _root_round(spans: List[dict]) -> Optional[int]:
    for s in spans:
        if not s.get("parent") and "round" in (s.get("attrs") or {}):
            try:
                return int(s["attrs"]["round"])
            except (TypeError, ValueError):
                return None
    return None


def render_postmortem(bundle: dict, show_metrics: bool = False) -> str:
    """One flight-recorder bundle (telemetry/postmortem.py) as text: the
    incident header, the pre-crash event timeline, and the spans that
    never closed."""
    from metisfl_tpu.telemetry import events as _events

    lines: List[str] = []
    when = time.strftime("%Y-%m-%d %H:%M:%S",
                         time.localtime(float(bundle.get("time", 0.0))))
    lines.append(
        f"bundle {os.path.basename(bundle.get('_path', '?'))}  "
        f"service={bundle.get('service', '?')} pid={bundle.get('pid', '?')} "
        f"reason={bundle.get('reason', '?')} time={when}"
        + (f" config={bundle['config_hash']}"
           if bundle.get("config_hash") else ""))
    extra = bundle.get("extra") or {}
    if extra:
        lines.append("  " + " ".join(f"{k}={v}" for k, v in extra.items()))
    records = bundle.get("events", [])
    if records:
        t0 = float(records[0].get("ts", 0.0))
        lines.append(f"  events ({len(records)}, "
                     f"seq {records[0].get('seq', '?')}"
                     f"..{records[-1].get('seq', '?')}):")
        for record in records:
            lines.append("    " + _events.format_record(record, t0=t0))
    else:
        lines.append("  events: (journal empty or disabled)")
    open_spans = bundle.get("open_spans", [])
    if open_spans:
        lines.append(f"  open spans at death ({len(open_spans)}):")
        for sp in open_spans:
            attrs = sp.get("attrs") or {}
            attr_s = ("  {" + " ".join(f"{k}={v}" for k, v in attrs.items())
                      + "}") if attrs else ""
            lines.append(
                f"    {sp.get('name', '?')} "
                f"(open {_fmt_dur(float(sp.get('open_ms', 0.0)))}) "
                f"trace={str(sp.get('trace', ''))[:8]}{attr_s}")
    profiles = bundle.get("profiles", [])
    if profiles:
        lines.append(f"  round cost profiles at death ({len(profiles)}):")
        for prof in profiles:
            phases = prof.get("phases") or {}
            top = max(phases, key=phases.get) if phases else "-"
            totals = prof.get("totals") or {}
            lines.append(
                f"    round {prof.get('round', '?')}: "
                f"wall {_fmt_dur(float(prof.get('wall_ms', 0.0)))} "
                f"coverage {float(prof.get('coverage', 0.0)) * 100:.0f}% "
                f"top={top} {_fmt_dur(float(phases.get(top, 0.0)))} "
                f"uplink {int(totals.get('uplink_bytes', 0))}B "
                f"downlink {int(totals.get('downlink_bytes', 0))}B")
    prof = bundle.get("prof") or {}
    if prof:
        top = prof.get("top") or []
        lines.append(
            f"  profiler at death ({prof.get('samples', 0)} stacks @ "
            f"{prof.get('hz', 0.0):g}Hz, top {len(top)} frames):")
        for row in top[:5]:
            lines.append(
                f"    {row.get('frame', '?'):<44} "
                f"self {row.get('self_pct', 0.0):5.1f}%  "
                f"total {row.get('total_pct', 0.0):5.1f}%")
        locks = prof.get("locks") or {}
        contended = [(site, row) for site, row in locks.items()
                     if row.get("contentions")]
        contended.sort(key=lambda kv: -kv[1].get("wait_s_total", 0.0))
        if contended:
            lines.append(f"  lock contention at death "
                         f"({len(contended)} site(s)):")
            for site, row in contended[:5]:
                lines.append(
                    f"    {site:<28} waits={row.get('contentions', 0)} "
                    f"total={row.get('wait_s_total', 0.0) * 1e3:.1f}ms "
                    f"max={row.get('wait_s_max', 0.0) * 1e3:.1f}ms "
                    f"acquires={row.get('acquisitions', 0)}")
    alerts = bundle.get("alerts") or {}
    if alerts:
        active = alerts.get("active") or []
        if active:
            lines.append(f"  alerts at death ({len(active)} firing, "
                         f"{alerts.get('fired_total', 0)} fired / "
                         f"{alerts.get('resolved_total', 0)} resolved "
                         "this run):")
            for alert in active:
                lines.append(
                    f"    FIRING {alert.get('name', '?')} "
                    f"[{alert.get('severity', '?')}] "
                    f"{alert.get('expr', '')} value="
                    f"{alert.get('value', 0.0):g} for "
                    f"{alert.get('active_s', 0.0):.1f}s")
        else:
            lines.append(
                f"  alerts at death: none firing "
                f"({alerts.get('rules', 0)} rule(s), "
                f"{alerts.get('fired_total', 0)} fired / "
                f"{alerts.get('resolved_total', 0)} resolved this run)")
    metrics_text = bundle.get("metrics", "")
    n_series = sum(1 for line in metrics_text.splitlines()
                   if line and not line.startswith("#"))
    lines.append(f"  metrics snapshot: {n_series} series"
                 + ("" if show_metrics else
                    " (re-run with --metrics to print)"))
    if show_metrics and metrics_text:
        lines.extend("    " + line for line in metrics_text.splitlines())
    return "\n".join(lines)


def _postmortem_main(argv: List[str]) -> int:
    from metisfl_tpu.telemetry import postmortem as _postmortem

    show_metrics = "--metrics" in argv
    argv = [a for a in argv if a != "--metrics"]
    if not argv:
        print("usage: python -m metisfl_tpu.telemetry --postmortem "
              "<bundle.json | postmortem-dir>... [--metrics]",
              file=sys.stderr)
        return 2
    bundles = _postmortem.load_bundles(argv)
    if not bundles:
        print("no post-mortem bundles found", file=sys.stderr)
        return 1
    for bundle in bundles:
        print(render_postmortem(bundle, show_metrics=show_metrics))
        print()
    return 0


def main(argv: List[str]) -> int:
    if "--postmortem" in argv:
        return _postmortem_main([a for a in argv if a != "--postmortem"])
    if "--fabric-smoke" in argv:
        # the fleet-fabric CI gate (scripts/chaos_smoke.sh); lives here
        # so runpy never re-executes an already-imported submodule
        from metisfl_tpu.telemetry import fabric as _fabric
        return _fabric.main(
            ["--smoke"] + [a for a in argv if a != "--fabric-smoke"])
    if "--prof-smoke" in argv:
        # the continuous-profiling overhead gate (scripts/chaos_smoke.sh)
        from metisfl_tpu.telemetry import prof as _prof
        return _prof.main(
            ["--smoke"] + [a for a in argv if a != "--prof-smoke"])
    if "--runtime-smoke" in argv:
        # the accelerator-runtime CI gate (scripts/chaos_smoke.sh):
        # zero steady-state recompiles + detector fires + overhead
        from metisfl_tpu.telemetry import runtime as _runtime
        return _runtime.main(
            ["--smoke"] + [a for a in argv if a != "--runtime-smoke"])
    if "--causal-smoke" in argv:
        # the causal-tracing CI gate (scripts/chaos_smoke.sh): slowed-
        # learner attribution + orphan lint + propagation overhead
        from metisfl_tpu.telemetry import causal as _causal
        return _causal.main(
            ["--smoke"] + [a for a in argv if a != "--causal-smoke"])
    show_attrs = "--attrs" in argv
    argv = [a for a in argv if a != "--attrs"]
    want_trace = want_round = None
    for flag in ("--trace", "--round"):
        if flag in argv:
            i = argv.index(flag)
            try:
                value = argv[i + 1]
            except IndexError:
                print(f"{flag} requires a value", file=sys.stderr)
                return 2
            if flag == "--trace":
                want_trace = value
            else:
                try:
                    want_round = int(value)
                except ValueError:
                    print("--round requires an integer", file=sys.stderr)
                    return 2
            argv = argv[:i] + argv[i + 2:]
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: python -m metisfl_tpu.telemetry <dir-or-jsonl>... "
              "[--trace ID] [--round N] [--attrs]", file=sys.stderr)
        return 2

    try:
        spans = load_spans(argv)
    except OSError as exc:
        print(f"cannot read traces: {exc}", file=sys.stderr)
        return 1
    if not spans:
        print("no spans found", file=sys.stderr)
        return 1

    by_trace: Dict[str, List[dict]] = {}
    for s in spans:
        by_trace.setdefault(s.get("trace", "?"), []).append(s)
    # stable order: by each trace's earliest span
    ordered = sorted(by_trace.items(),
                     key=lambda kv: min(s.get("start", 0.0)
                                        for s in kv[1]))
    shown = 0
    for trace_id, group in ordered:
        if want_trace and not trace_id.startswith(want_trace):
            continue
        if want_round is not None and _root_round(group) != want_round:
            continue
        round_no = _root_round(group)
        tag = f" round={round_no}" if round_no is not None else ""
        print(f"trace {trace_id}{tag} ({len(group)} spans)")
        print(render_trace(group, show_attrs=show_attrs))
        print()
        shown += 1
    if not shown:
        print("no matching traces", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
