"""Trace-viewer CLI: ``python -m metisfl_tpu.telemetry <dir-or-jsonl>...``.

Renders the span trees recorded in one or more JSONL trace sinks
(:mod:`metisfl_tpu.telemetry.trace`) — typically the ``telemetry/``
directory a driver run leaves in its workdir, where controller and
learner files stitch into one tree per federation round via the
wire-propagated trace ids.

    python -m metisfl_tpu.telemetry /tmp/metisfl_tpu_x/telemetry
    python -m metisfl_tpu.telemetry traces.jsonl --round 3
    python -m metisfl_tpu.telemetry traces.jsonl --trace 01ab... --attrs
"""

from __future__ import annotations

import glob
import json
import os
import sys
from typing import Any, Dict, Iterable, List, Optional


def load_spans(paths: Iterable[str]) -> List[dict]:
    """All span records from JSONL files / directories of them. Unreadable
    lines are skipped (a crashed process can leave a torn tail line)."""
    spans: List[dict] = []
    for path in paths:
        files = (sorted(glob.glob(os.path.join(path, "*.jsonl")))
                 if os.path.isdir(path) else [path])
        for name in files:
            with open(name) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        record = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if isinstance(record, dict) and record.get("span"):
                        spans.append(record)
    return spans


def _fmt_dur(ms: float) -> str:
    return f"{ms / 1e3:.2f}s" if ms >= 1e3 else f"{ms:.1f}ms"


def render_trace(spans: List[dict], show_attrs: bool = False) -> str:
    """One trace's spans (same trace id) as an indented tree, children
    ordered by start time. Spans whose parent never landed in the sink
    (e.g. a process killed mid-round) render as roots."""
    by_id: Dict[str, dict] = {s["span"]: s for s in spans}
    children: Dict[str, List[dict]] = {}
    roots: List[dict] = []
    for s in spans:
        parent = s.get("parent", "")
        if parent and parent in by_id:
            children.setdefault(parent, []).append(s)
        else:
            roots.append(s)
    for kids in children.values():
        kids.sort(key=lambda s: s.get("start", 0.0))
    roots.sort(key=lambda s: s.get("start", 0.0))

    lines: List[str] = []

    def _walk(span: dict, prefix: str, tail: bool,
              root: bool = False) -> None:
        connector = "" if root else ("└─ " if tail else "├─ ")
        label = (f"{span.get('name', '?')} "
                 f"({_fmt_dur(float(span.get('dur_ms', 0.0)))}) "
                 f"[{span.get('service', '?')}]")
        if show_attrs and span.get("attrs"):
            attrs = " ".join(f"{k}={v}" for k, v in span["attrs"].items())
            label += f"  {{{attrs}}}"
        lines.append(prefix + connector + label)
        kids = children.get(span["span"], [])
        child_prefix = prefix if root else (
            prefix + ("   " if tail else "│  "))
        for i, kid in enumerate(kids):
            _walk(kid, child_prefix, i == len(kids) - 1)

    for root in roots:
        _walk(root, "", True, root=True)
    return "\n".join(lines)


def _root_round(spans: List[dict]) -> Optional[int]:
    for s in spans:
        if not s.get("parent") and "round" in (s.get("attrs") or {}):
            try:
                return int(s["attrs"]["round"])
            except (TypeError, ValueError):
                return None
    return None


def main(argv: List[str]) -> int:
    show_attrs = "--attrs" in argv
    argv = [a for a in argv if a != "--attrs"]
    want_trace = want_round = None
    for flag in ("--trace", "--round"):
        if flag in argv:
            i = argv.index(flag)
            try:
                value = argv[i + 1]
            except IndexError:
                print(f"{flag} requires a value", file=sys.stderr)
                return 2
            if flag == "--trace":
                want_trace = value
            else:
                try:
                    want_round = int(value)
                except ValueError:
                    print("--round requires an integer", file=sys.stderr)
                    return 2
            argv = argv[:i] + argv[i + 2:]
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: python -m metisfl_tpu.telemetry <dir-or-jsonl>... "
              "[--trace ID] [--round N] [--attrs]", file=sys.stderr)
        return 2

    try:
        spans = load_spans(argv)
    except OSError as exc:
        print(f"cannot read traces: {exc}", file=sys.stderr)
        return 1
    if not spans:
        print("no spans found", file=sys.stderr)
        return 1

    by_trace: Dict[str, List[dict]] = {}
    for s in spans:
        by_trace.setdefault(s.get("trace", "?"), []).append(s)
    # stable order: by each trace's earliest span
    ordered = sorted(by_trace.items(),
                     key=lambda kv: min(s.get("start", 0.0)
                                        for s in kv[1]))
    shown = 0
    for trace_id, group in ordered:
        if want_trace and not trace_id.startswith(want_trace):
            continue
        if want_round is not None and _root_round(group) != want_round:
            continue
        round_no = _root_round(group)
        tag = f" round={round_no}" if round_no is not None else ""
        print(f"trace {trace_id}{tag} ({len(group)} spans)")
        print(render_trace(group, show_attrs=show_attrs))
        print()
        shown += 1
    if not shown:
        print("no matching traces", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
