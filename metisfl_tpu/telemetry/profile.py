"""Performance observatory: per-round cost profiles + device utilization.

The PR 1-5 planes explain the federation's *behavior* (spans, metrics,
events, learning health, lifecycle); this plane explains its *cost* —
where each round's time and bytes go, attributed per phase and per
learner, so every remaining ROADMAP item (ingest parallelization, MFU
tuning, fleet autoscaling) is measured through one instrument panel:

- :class:`ProfileCollector` — controller-side assembler: folds the
  round's span-sourced phase durations, per-learner uplink/downlink wire
  bytes, codec encode/decode attribution (:mod:`metisfl_tpu.comm.codec`),
  store insert/select time, and the learner-shipped device stats into a
  typed :class:`RoundProfile`, persisted into ``RoundMetadata.profile``
  (→ ``experiment.json``) and a JSONL sink next to the trace files
  (``<dir>/profiles-<pid>.jsonl``). A bounded tail rides in post-mortem
  bundles and ``DescribeFederation`` snapshots.
- :class:`DeviceMonitor` — learner-side utilization capture per train
  task: step-time EWMA, achieved-MFU estimate (model-ops FLOPs estimate
  over the chip's bf16 peak), and the HBM high-water mark from
  ``device.memory_stats()`` — shipped back in ``TaskResult.device_stats``
  so the controller profile is federation-wide.
- :func:`device_tracer` — the one reusable ``jax.profiler`` trace handle
  (exception-safe stop, unique per-session capture dirs) that
  ``models/ops.py`` drives instead of triple start/stop bookkeeping;
  ``telemetry.profile.trace_every_rounds`` arms it periodically via the
  dispatched ``TrainParams.profile_dir``.

``python -m metisfl_tpu.perf`` renders the phase waterfall and top-span
self-time table from a run directory, and diffs bench captures with
regression flags (``--compare`` / ``--trajectory``).

Opt-out: ``telemetry.profile.enabled=false`` leaves every hot path at
one attribute check (no collector constructed, no device stats shipped).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from metisfl_tpu import telemetry as _tel
from metisfl_tpu.telemetry import metrics as _tmetrics

logger = logging.getLogger("metisfl_tpu.telemetry")

SCHEMA_VERSION = 1

# Round phases whose durations compose the waterfall. store_insert
# overlaps wait_uplinks (inserts happen while the barrier is open), so it
# rides in the store section instead of the coverage sum. When the
# controller recorded all four phase-boundary timestamps (note_mark),
# the waterfall is computed as CONTIGUOUS segments between them — it
# tiles the round wall-clock exactly, instead of summing independent
# span durations whose inter-span gaps leak coverage on short rounds.
PHASES = ("dispatch", "wait_uplinks", "select", "aggregate", "close")

# boundary marks, in waterfall order (each ends the named phase)
_MARKS = ("dispatch_end", "wait_end", "select_end", "aggregate_end")

_REG = _tmetrics.registry()
_M_DOWNLINK = _REG.counter(
    _tel.M_DOWNLINK_BYTES_TOTAL,
    "Community-model bytes dispatched to each learner (train + eval "
    "downlink payloads)", ("learner",), budget_label="learner")
_M_MFU = _REG.gauge(
    _tel.M_LEARNER_ACHIEVED_MFU,
    "Achieved model FLOPs utilization per learner (estimated step FLOPs "
    "over the chip's bf16 peak; 0 where the peak is unknown, e.g. CPU)",
    ("learner",), budget_label="learner")
_M_STEP_EWMA = _REG.gauge(
    _tel.M_LEARNER_STEP_MS_EWMA,
    "EWMA steady-state optimizer-step time per learner (ms, from "
    "TaskResult.device_stats)", ("learner",), budget_label="learner")
_M_HBM = _REG.gauge(
    _tel.M_LEARNER_HBM_PEAK_BYTES,
    "Device-memory high-water mark per learner "
    "(device.memory_stats peak_bytes_in_use; 0 where unsupported)",
    ("learner",), budget_label="learner")

# bf16 peak FLOP/s per chip by device_kind substring (first match wins) —
# the MFU denominator. The ONE table: bench.py imports
# device_peak_flops from here rather than keeping its own copy.
CHIP_PEAKS = [
    ("v5 lite", 197e12), ("v5e", 197e12),
    ("v6 lite", 918e12), ("v6e", 918e12), ("trillium", 918e12),
    ("v5p", 459e12), ("v5", 459e12),
    ("v4", 275e12), ("v3", 123e12), ("v2", 46e12),
]


def device_peak_flops(device_kind: str) -> float:
    """bf16 peak FLOP/s for a jax device_kind string (0.0 = unknown)."""
    kind = (device_kind or "").lower()
    for key, peak in CHIP_PEAKS:
        if key in kind:
            return peak
    return 0.0


# --------------------------------------------------------------------- #
# reusable jax.profiler trace handle (models/ops.py drives this)
# --------------------------------------------------------------------- #

_TRACE_SEQ_LOCK = threading.Lock()
_TRACE_SEQ = 0


def _unique_session_dir(base_dir: str) -> str:
    """A capture dir no concurrent learner/process/call can collide with:
    jax.profiler session dirs are timestamped at second granularity, so
    same-host learners starting traces within the same second would
    otherwise clobber each other (learner/learner.py namespaces per
    learner id on top of this)."""
    global _TRACE_SEQ
    with _TRACE_SEQ_LOCK:
        _TRACE_SEQ += 1
        seq = _TRACE_SEQ
    stamp = time.strftime("%Y%m%d-%H%M%S")
    return os.path.join(base_dir, f"{stamp}-{os.getpid()}-{seq:03d}")


class DeviceTracer:
    """One jax.profiler capture lifecycle: ``start()`` opens a trace into
    a unique session dir under ``base_dir`` (at most one capture per
    handle), ``stop()`` is idempotent and exception-safe — a train loop
    can call it from a ``finally`` without tracking which of its several
    start sites fired. A handle with no ``base_dir`` is inert."""

    def __init__(self, base_dir: str = ""):
        self.base_dir = base_dir
        self.active = False
        self.captured = False
        self.session_dir = ""

    def start(self) -> bool:
        """Open the capture (False when inert, already active, or already
        captured once — one trace per handle, matching the one-capture
        contract of TrainParams.profile_dir)."""
        if not self.base_dir or self.active or self.captured:
            return False
        session = _unique_session_dir(self.base_dir)
        try:
            import jax

            os.makedirs(session, exist_ok=True)
            jax.profiler.start_trace(session)
        except Exception:  # noqa: BLE001 - profiling must never fail a task
            logger.exception("jax.profiler trace start failed")
            return False
        self.session_dir = session
        self.active = True
        self.captured = True
        return True

    def stop(self) -> None:
        if not self.active:
            return
        self.active = False
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001 - stop is best-effort by contract
            logger.exception("jax.profiler trace stop failed")


def device_tracer(base_dir: str = "") -> DeviceTracer:
    """A trace handle for one train task ('' → inert handle)."""
    return DeviceTracer(base_dir)


# --------------------------------------------------------------------- #
# learner-side device utilization
# --------------------------------------------------------------------- #

class DeviceMonitor:
    """Per-learner device-utilization capture across train tasks:
    step-time EWMA (same alpha posture as the straggler analytics),
    achieved-MFU estimate, and the HBM high-water mark. ``observe``
    returns the stats dict that ships in ``TaskResult.device_stats``;
    everything device-specific is guarded — on CPU (or a backend without
    memory_stats) the fields degrade to 0 instead of raising."""

    def __init__(self, alpha: float = 0.3):
        self.alpha = alpha
        self.step_ms_ewma = 0.0
        self._peak_flops: Optional[float] = None
        self._device_kind = ""

    def _resolve_device(self) -> None:
        if self._peak_flops is not None:
            return
        try:
            import jax

            dev = jax.local_devices()[0]
            self._device_kind = getattr(dev, "device_kind", "") or ""
        except Exception:  # noqa: BLE001 - no backend is a valid state
            self._device_kind = ""
        self._peak_flops = device_peak_flops(self._device_kind)

    def _hbm_peak_bytes(self) -> int:
        try:
            import jax

            stats = jax.local_devices()[0].memory_stats()
            if stats:
                return int(stats.get("peak_bytes_in_use", 0) or 0)
        except Exception:  # noqa: BLE001 - unsupported backends return 0
            pass
        return 0

    def observe(self, steps: int, ms_per_step: float,
                flops_per_step: float = 0.0) -> Dict[str, Any]:
        self._resolve_device()
        if ms_per_step > 0.0:
            if self.step_ms_ewma <= 0.0:
                self.step_ms_ewma = ms_per_step
            else:
                self.step_ms_ewma = (self.alpha * ms_per_step
                                     + (1.0 - self.alpha) * self.step_ms_ewma)
        mfu = 0.0
        if (self._peak_flops and flops_per_step > 0.0 and ms_per_step > 0.0):
            mfu = flops_per_step / (ms_per_step / 1e3) / self._peak_flops
        return {
            "steps": int(steps),
            "ms_per_step": round(float(ms_per_step), 4),
            "step_ms_ewma": round(self.step_ms_ewma, 4),
            "flops_per_step": float(flops_per_step),
            "mfu": round(float(mfu), 5),
            "hbm_peak_bytes": self._hbm_peak_bytes(),
            "device_kind": self._device_kind,
        }


# --------------------------------------------------------------------- #
# controller-side round profiles
# --------------------------------------------------------------------- #

@dataclass
class RoundProfile:
    """Typed per-round cost profile — the driver-collects-statistics role
    (PAPER.md §driver) extended from aggregate metadata to an
    attribution: where this round's wall-clock and wire bytes went."""

    round: int = 0
    wall_ms: float = 0.0
    # phase → milliseconds (PHASES above); coverage = sum/wall
    phases: Dict[str, float] = field(default_factory=dict)
    coverage: float = 0.0
    aggregation_ms: float = 0.0
    # store-layer time: per-model insert (overlaps wait_uplinks) and the
    # aggregation path's lineage selects
    store: Dict[str, float] = field(default_factory=dict)
    # overlay timings recorded via note_phase that OVERLAP the tiled
    # waterfall rather than extending it (stream_fold inside
    # wait_uplinks, ingest_drain/select inside aggregate) — kept out of
    # ``phases`` so its coverage invariant holds
    extras: Dict[str, float] = field(default_factory=dict)
    # learner → {uplink_bytes, downlink_bytes, codec_encode_s,
    #            codec_decode_s, insert_ms, device{...}}
    learners: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    totals: Dict[str, float] = field(default_factory=dict)
    serving: Dict[str, Any] = field(default_factory=dict)
    # per-round folded-stack delta from the continuous profiler
    # (telemetry/prof.py): which frames grew while this round ran —
    # {"samples": N, "stacks": [[folded_stack, delta], ...]}. Empty when
    # the sampler is off; perf --flame-diff run@A run@B diffs rounds.
    prof: Dict[str, Any] = field(default_factory=dict)
    # controller-local causal critical path (telemetry/causal.py
    # summarize()): the round's longest chain from the finished-span
    # ring — heaviest edges + the dominant one. Empty when the span ring
    # is off; the fleet collector's crit entry is the cross-process view.
    critical_path: Dict[str, Any] = field(default_factory=dict)
    # jax.profiler capture armed for this round (trace_every_rounds)
    trace_armed: bool = False
    schema: int = SCHEMA_VERSION

    def to_dict(self) -> dict:
        import dataclasses

        return dataclasses.asdict(self)


class ProfileCollector:
    """Controller-side cost accounting for the in-flight round. All note
    hooks are one call deep and cheap; the collector is only constructed
    when ``telemetry.profile.enabled`` — the disabled hot path in the
    controller is one attribute check (the health-monitor posture)."""

    def __init__(self, config: Any = None, telemetry_dir: str = "",
                 service: str = "controller"):
        self.trace_every_rounds = int(
            getattr(config, "trace_every_rounds", 0) or 0)
        self.dir = (getattr(config, "dir", "") or telemetry_dir or "")
        self.service = service
        self._lock = threading.Lock()
        # sink writes serialize on their own lock: persist() runs at
        # round close concurrently with note_* hooks called under the
        # controller lock, and disk I/O must not stall those
        self._sink_lock = threading.Lock()
        self._path = ""
        self._fh = None
        if self.dir:
            try:
                os.makedirs(self.dir, exist_ok=True)
                self._path = os.path.join(
                    self.dir, f"profiles-{os.getpid()}.jsonl")
            except OSError as exc:
                logger.warning("profile sink dir %r not creatable (%s); "
                               "round profiles will not be persisted",
                               self.dir, exc)
        # per-round accumulators (reset by assemble_round)
        self._downlink: Dict[str, int] = {}
        self._select_ms = 0.0
        self._insert_ms: Dict[str, float] = {}
        self._phase_extra: Dict[str, float] = {}
        # phase-boundary timestamps (epoch seconds, _MARKS order) — the
        # tiled-waterfall inputs; reset with the other accumulators
        self._marks: Dict[str, float] = {}
        # latest device stats per learner (persists across rounds — a
        # learner not sampled this round keeps its last observation)
        self._device: Dict[str, Dict[str, Any]] = {}
        # cumulative codec-attribution snapshot at the last round close
        # (comm/codec.py keeps the process totals; per-round = delta)
        self._codec_snapshot: Dict[Any, float] = {}
        # cumulative folded-stack snapshot at the last round close
        # (telemetry/prof.py sampler; per-round profile = delta)
        self._prof_snapshot: Optional[Dict[str, float]] = None
        # bounded recent-profile tail (post-mortem bundles, describe())
        self._tail: List[dict] = []
        self._tail_limit = 16
        # finished-span ring cursor + bounded record buffer for the
        # per-round critical path (attach_critical_path): the buffer
        # carries spans across pulls so an aggregation-failure retry's
        # early spans are still visible when the retry's round closes
        self._span_cursor = 0
        self._span_buf: List[dict] = []
        self._span_buf_limit = 4096
        # optional serving-occupancy probe (in-process gateway / tests):
        # a zero-arg callable returning a small dict snapshot
        self.serving_probe: Optional[Callable[[], Dict[str, Any]]] = None

    # -- trace arming ------------------------------------------------------
    def trace_target(self, round_no: int) -> str:
        """The jax.profiler capture dir to dispatch for this round (''
        when not due). Periodic: every ``trace_every_rounds`` rounds,
        rooted under the profile sink dir."""
        if (self.trace_every_rounds <= 0 or not self.dir
                or round_no % self.trace_every_rounds != 0):
            return ""
        return os.path.join(self.dir, "jaxprof", f"round{round_no}")

    # -- note hooks (scheduling executor / RPC threads) --------------------
    def note_downlink(self, learner_id: str, nbytes: int) -> None:
        with self._lock:
            self._downlink[learner_id] = (
                self._downlink.get(learner_id, 0) + int(nbytes))
        _M_DOWNLINK.inc(nbytes, learner=learner_id)

    def note_device(self, learner_id: str, stats: Dict[str, Any]) -> None:
        if not isinstance(stats, dict) or not stats:
            return
        with self._lock:
            self._device[learner_id] = dict(stats)
        try:
            _M_STEP_EWMA.set(float(stats.get("step_ms_ewma", 0.0) or 0.0),
                             learner=learner_id)
            _M_MFU.set(float(stats.get("mfu", 0.0) or 0.0),
                       learner=learner_id)
            _M_HBM.set(float(stats.get("hbm_peak_bytes", 0) or 0),
                       learner=learner_id)
        except (TypeError, ValueError):
            # learner-shipped dicts are never validated on the wire — a
            # garbage value must not take the completion path down
            logger.warning("unusable device stats from %s: %r",
                           learner_id, stats)

    def note_store_select(self, ms: float) -> None:
        with self._lock:
            self._select_ms += float(ms)

    def note_store_insert(self, learner_id: str, ms: float) -> None:
        with self._lock:
            self._insert_ms[learner_id] = (
                self._insert_ms.get(learner_id, 0.0) + float(ms))

    def note_phase(self, phase: str, ms: float) -> None:
        with self._lock:
            self._phase_extra[phase] = (
                self._phase_extra.get(phase, 0.0) + float(ms))

    def note_mark(self, name: str, first: bool = False) -> None:
        """Record a phase-boundary timestamp for the in-flight round.
        ``first=True`` keeps the earliest recording (a mid-round rejoin
        re-dispatch must not move ``dispatch_end`` into the wait window);
        otherwise the latest wins (an aggregation-failure retry moves the
        later boundaries forward with it, so the waterfall keeps
        tiling)."""
        now = time.time()
        with self._lock:
            if first and name in self._marks:
                return
            self._marks[name] = now

    def drop(self, learner_id: str) -> None:
        """Prune the collector's per-learner state for a learner that
        left. The downlink/MFU/step/HBM *series* themselves are pruned
        by the central ``telemetry.prune_learner`` registry helper
        (they carry the "learner" cardinality label) — this drops only
        the collector-internal attribution behind them."""
        with self._lock:
            self._downlink.pop(learner_id, None)
            self._insert_ms.pop(learner_id, None)
            self._device.pop(learner_id, None)
            # the codec process totals are pruned by
            # prune_attribution_series; without dropping the matching
            # snapshot keys too, a leave→rejoin between round closes
            # would diff a fresh (small) total against the stale (large)
            # snapshot and record a negative per-round cost
            for key in [k for k in self._codec_snapshot
                        if k[0] == learner_id]:
                del self._codec_snapshot[key]
        # NOT calling prune_attribution_series here: the central
        # telemetry.prune_learner already does, strictly before the
        # controller calls this (one prune per departure, not two)

    # -- round assembly ----------------------------------------------------
    def assemble_round(self, meta: Any, close_ms: float = 0.0) -> dict:
        """Fold the finished round's metadata + accumulators into a
        RoundProfile dict and reset the per-round state. Cheap (dict
        building only) — the controller calls it under its lock, then
        :meth:`persist` outside it."""
        try:
            codec_totals = self._codec_totals()
        except Exception:  # noqa: BLE001 - attribution is best-effort
            codec_totals = {}
        with self._lock:
            downlink, self._downlink = self._downlink, {}
            insert_ms, self._insert_ms = self._insert_ms, {}
            select_ms, self._select_ms = self._select_ms, 0.0
            extra, self._phase_extra = self._phase_extra, {}
            marks, self._marks = self._marks, {}
            device = {lid: dict(s) for lid, s in self._device.items()}
            codec_round = {
                key: total - self._codec_snapshot.get(key, 0.0)
                for key, total in codec_totals.items()}
            self._codec_snapshot = codec_totals

        started = float(getattr(meta, "started_at", 0.0))
        completed = float(getattr(meta, "completed_at", 0.0))
        wall_ms = 1e3 * max(0.0, completed - started)
        if wall_ms > 0 and all(m in marks for m in _MARKS):
            # tiled waterfall: contiguous segments between the recorded
            # boundaries (clamped into [started, completed] and kept
            # monotonic) — sums to the wall-clock by construction
            seq = [started]
            for name in _MARKS:
                seq.append(min(completed, max(seq[-1], marks[name])))
            seq.append(completed)
            phases = {phase: (seq[i + 1] - seq[i]) * 1e3
                      for i, phase in enumerate(PHASES)}
        else:
            # fallback (resumed/partial rounds): the independent span
            # durations — honest, but inter-span gaps leak coverage
            phases = {
                "dispatch": float(getattr(meta, "dispatch_duration_ms",
                                          0.0)),
                "wait_uplinks": float(getattr(meta, "wait_duration_ms",
                                              0.0)),
                "select": float(extra.get("select", 0.0)),
                "aggregate": float(getattr(meta, "aggregation_duration_ms",
                                           0.0)),
                "close": float(close_ms),
            }
        phases = {k: round(v, 3) for k, v in phases.items()}
        attributed = sum(phases.values())
        uplink = dict(getattr(meta, "uplink_bytes", {}) or {})
        learners: Dict[str, Dict[str, Any]] = {}
        for lid in sorted(set(uplink) | set(downlink)):
            entry: Dict[str, Any] = {
                "uplink_bytes": int(uplink.get(lid, 0)),
                "downlink_bytes": int(downlink.get(lid, 0)),
            }
            if lid in insert_ms:
                entry["insert_ms"] = round(insert_ms[lid], 3)
            enc = codec_round.get((lid, "encode"), 0.0)
            dec = codec_round.get((lid, "decode"), 0.0)
            if enc or dec:
                entry["codec_encode_s"] = round(enc, 6)
                entry["codec_decode_s"] = round(dec, 6)
            if lid in device:
                entry["device"] = device[lid]
            learners[lid] = entry
        profile = RoundProfile(
            round=int(getattr(meta, "global_iteration", 0)),
            wall_ms=round(wall_ms, 3),
            phases=phases,
            coverage=round(min(1.0, attributed / wall_ms), 4)
            if wall_ms > 0 else 0.0,
            # span-measured aggregation compute time (the tiled phase
            # segment additionally carries the select→aggregate glue)
            aggregation_ms=round(float(getattr(
                meta, "aggregation_duration_ms", 0.0))
                or phases["aggregate"], 3),
            store={"insert_ms": round(sum(insert_ms.values()), 3),
                   "select_ms": round(select_ms, 3)},
            extras={k: round(v, 3) for k, v in sorted(extra.items())},
            learners=learners,
            totals={"uplink_bytes": float(sum(uplink.values())),
                    "downlink_bytes": float(sum(downlink.values()))},
            trace_armed=bool(self.trace_target(
                int(getattr(meta, "global_iteration", 0)))),
        )
        if self.serving_probe is not None:
            try:
                profile.serving = dict(self.serving_probe() or {})
            except Exception:  # noqa: BLE001 - a probe never fails a round
                logger.exception("serving occupancy probe failed")
        try:
            # per-round folded-stack delta (telemetry/prof.py): one
            # attribute check + a dict diff when the sampler is live,
            # nothing otherwise
            from metisfl_tpu.telemetry import prof as _prof

            if _prof.sampling():
                counts = _prof.counts_snapshot()
                if self._prof_snapshot is not None:
                    profile.prof = _prof.delta(self._prof_snapshot,
                                               counts)
                self._prof_snapshot = counts
        except Exception:  # noqa: BLE001 - profiling is best-effort
            logger.exception("round profile stack delta failed")
        record = profile.to_dict()
        with self._lock:
            self._tail.append(record)
            del self._tail[:-self._tail_limit]
        return record

    def attach_critical_path(self, record: dict) -> None:
        """Fold the round's causal critical path (telemetry/causal.py)
        into an assembled profile record, in place. Called OFF the
        controller lock and strictly AFTER the round span ends — the
        walk reads the finished-span ring, so the root record must have
        landed. Populates nothing when the ring is off (``telemetry.
        fabric.span_ring`` unset and fabric disabled) — the field stays
        its empty default."""
        try:
            from metisfl_tpu.telemetry import causal as _causal
            from metisfl_tpu.telemetry import trace as _trace

            records, cursor, _lost = _trace.spans_since(self._span_cursor)
            with self._lock:
                self._span_cursor = cursor
                if records:
                    self._span_buf.extend(records)
                    del self._span_buf[:-self._span_buf_limit]
                spans = list(self._span_buf)
            if not spans:
                return
            cp = _causal.round_critical_path(
                spans, round_no=record.get("round"))
            if cp is None:
                return
            record["critical_path"] = _causal.summarize(cp)
        except Exception:  # noqa: BLE001 - attribution is best-effort
            logger.exception("round critical-path attribution failed")

    @staticmethod
    def _codec_totals() -> Dict[Any, float]:
        from metisfl_tpu.comm import codec as _codec

        return _codec.attributed_totals()

    def persist(self, record: dict) -> None:
        """Append one profile line to the JSONL sink (best-effort, same
        degradation contract as the trace sink)."""
        if not self._path:
            return
        line = json.dumps(record, default=str) + "\n"
        with self._sink_lock:
            if not self._path:
                return
            try:
                if self._fh is None:
                    self._fh = open(self._path, "a", buffering=1)
                self._fh.write(line)
            except OSError:
                self._path = ""
                self._fh = None

    def close(self) -> None:
        """Release the sink file handle (controller shutdown). Idempotent;
        a persist() after close simply reopens — correctness never depends
        on close being called."""
        with self._sink_lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None

    def profiles_path(self) -> str:
        return self._path

    def tail(self, n: int = 3) -> List[dict]:
        with self._lock:
            return list(self._tail[-n:]) if n > 0 else []

    def summary(self) -> Dict[str, Any]:
        """Compact latest-round view for DescribeFederation / status."""
        with self._lock:
            last = dict(self._tail[-1]) if self._tail else {}
            rounds = len(self._tail)
        out: Dict[str, Any] = {
            "enabled": True,
            "trace_every_rounds": self.trace_every_rounds,
            "rounds_profiled": rounds,
        }
        if last:
            out.update({
                "last_round": last.get("round", 0),
                "wall_ms": last.get("wall_ms", 0.0),
                "coverage": last.get("coverage", 0.0),
                "phases": dict(last.get("phases", {})),
                "uplink_bytes": last.get("totals", {}).get(
                    "uplink_bytes", 0.0),
                "downlink_bytes": last.get("totals", {}).get(
                    "downlink_bytes", 0.0),
            })
        return out


def prune_attribution_series(learner_id: str) -> None:
    """Prune the codec-attribution and RPC peer-byte series for a
    departed learner. Module-level (not a collector method) so the
    controller can call it UNCONDITIONALLY on leave — attribution may
    have been minted while a collector was active (or by a direct
    caller) even if the profile plane is off now, and those series must
    not outlive the learner."""
    # lazy imports: codec/rpc import this package at module level
    try:
        from metisfl_tpu.comm import codec as _codec

        _codec.prune_attribution(learner_id)
    except ImportError:  # pragma: no cover - comm always present
        pass
    try:
        from metisfl_tpu.comm import rpc as _rpc

        _rpc.prune_peer_series(learner_id)
    except ImportError:  # pragma: no cover - optional grpc dependency
        pass


# --------------------------------------------------------------------- #
# process-level hooks (post-mortem bundles read the active collector)
# --------------------------------------------------------------------- #

_COLLECTOR: Optional[ProfileCollector] = None


def set_collector(collector: Optional[ProfileCollector]) -> None:
    """Register the process's active collector (the controller's); the
    flight recorder snapshots its tail into crash bundles."""
    global _COLLECTOR
    _COLLECTOR = collector


def collector() -> Optional[ProfileCollector]:
    return _COLLECTOR


def tail(n: int = 3) -> List[dict]:
    """The latest round profiles ([] when no collector is active)."""
    if _COLLECTOR is None:
        return []
    return _COLLECTOR.tail(n)
