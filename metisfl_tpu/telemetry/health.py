"""Learning-health plane: per-update model statistics + divergence scores.

Fourth telemetry layer next to spans (how long), metrics (how much), and
events (what happened): *is the federation actually learning, and is any
learner pulling against it*. The systems planes can say a round took 4 s
and which learner straggled; nothing before this module watched the
content of the uplinks themselves. Robust aggregation rules
(:mod:`metisfl_tpu.aggregation.robust`) silently *mask* diverging or
poisoned updates — this plane *measures and exposes* them, the
observability analogue of Krum: per-update norms, cohort alignment, and
a per-learner divergence score, normalized the same round-relative way
as the straggler score (controller/core.py ``_straggler_scores``).

Statistics (host numpy, read-only — the dtype-preserving aggregation
contract in :mod:`metisfl_tpu.aggregation.base` is untouched), computed
per uplink against the community model the task trained from:

- ``update_norm`` — L2 norm of the flattened update ``u_i = w_i − w``;
- ``layer_norms`` — the same norm broken down per top-level layer
  (first two ``/``-separated name components), so a single exploding
  head/adapter is attributable;
- ``cos_prev_delta`` — cosine of ``u_i`` against the previous round's
  community delta (is this learner still pushing the direction the
  federation just moved, or against it).

At round completion the cohort folds: cosine of each update against the
cohort mean update, a deviation ``d_i = ‖u_i − ū‖``, and the **robust
z-score** ``z_i = (d_i − median d) / (1.4826·MAD + 0.05·median + ε)``
(median/MAD instead of mean/std so the outlier being scored cannot
inflate its own yardstick). Per-learner scores are the EWMA of
``max(z_i, 0)`` across rounds — like the straggler score, a recovered
learner decays back within a few rounds. A round whose raw ``z_i``
crosses ``anomaly_threshold`` emits an ``UpdateAnomalous`` event; every
round emits ``RoundHealth`` with the convergence snapshot (community
update norm, effective step size ``‖Δw‖/‖w‖``, participation entropy of
the applied scales, cohort train-loss quantiles from the
``TaskResult.train_metrics`` learners already ship).

Overhead contract: ``telemetry.health.enabled=false`` (or secure
aggregation, whose payloads are opaque ciphertext) leaves the
controller's monitor unset — the uplink hot path costs ONE attribute
check and performs no statistics work. Enabled, the per-uplink pass is
O(params) host work, tracked by the ``health`` section of ``bench.py``.
"""

from __future__ import annotations

import logging
import math
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger("metisfl_tpu.telemetry.health")

# EWMA blend weight and anomaly threshold defaults live in
# config/federation.py HealthConfig; these mirror them for direct use.
DEFAULT_ALPHA = 0.3
DEFAULT_ANOMALY_THRESHOLD = 3.0
# robust-z denominator: sigma ≈ 1.4826·MAD for a normal cohort, plus a
# floor fraction of the median so jitter around a tiny median cannot
# mint huge scores, plus an absolute epsilon for the all-identical case
_MAD_SIGMA = 1.4826
_MEDIAN_FLOOR = 0.05
_EPS = 1e-12
# per-snapshot layer-breakdown cap (bounds DescribeFederation payloads
# for thousand-tensor models; the largest norms are the interesting ones)
_MAX_LAYER_ROWS = 32
# Pending per-round update vectors are dropped at each cohort fold; this
# caps the buffer against an async federation whose folds lag uplinks.
# Sized to the largest supported cohort scale (bench.py bench_cohort
# drives 4096) so a legitimate sync round is never silently truncated;
# evictions are counted and surfaced as ``pending_evicted`` in the next
# round snapshot (evicted learners get no score that round).
_MAX_PENDING = 4096
# Buffered-vector width cap: updates larger than this are buffered as a
# fixed seeded coordinate subsample scaled by sqrt(d/k) (norms and
# cosines preserved in expectation — a JL-style sketch), so the cohort
# buffer is O(cohort x SKETCH_DIM), never O(cohort x params): the
# stride-aggregation memory-bounding story survives the health plane
# (worst case 4096 x 16384 f32 = 256 MiB, vs gigabytes of raw vectors).
# Per-uplink norms stay EXACT — only the cohort mean/deviation/cosine
# statistics use the sketch. Models at or under the cap are exact too.
_SKETCH_DIM = 16384
_SKETCH_SEED = 0xC0FFEE
# raw divergence assigned to a non-finite (NaN/Inf-weight) update — a
# finite sentinel well past any default threshold, so the anomaly fires
# and every downstream JSON surface stays strict-parseable
_NON_FINITE_Z_FACTOR = 10.0


def flatten_model(model: Dict[str, np.ndarray]) -> np.ndarray:
    names = sorted(model)
    if not names:
        return np.zeros(0, np.float32)
    return np.concatenate([np.asarray(model[n], np.float32).ravel()
                           for n in names])


def layer_key(name: str) -> str:
    """Per-top-level-layer attribution key: the first two ``/``-separated
    components of a flattened tensor name (``params/Dense_0/kernel`` →
    ``params/Dense_0``; a bare ``w`` stays ``w``)."""
    return "/".join(name.split("/")[:2])


def finite_metrics(metrics: Any) -> Dict[str, float]:
    """Learner-shipped metric mapping filtered down to finite floats.
    The wire validates neither the container nor the values — a non-dict
    payload (version skew, malice), None/str values, and NaN/Inf must
    all be dropped, never raised on: in the controller's completion
    handler an escaping exception would skip ``schedule_next`` and stall
    the sync round barrier, and NaN breaks strict-JSON surfaces. Shared
    by the controller's round-lineage recording and the per-uplink
    summaries here — one filter, no drift."""
    if not isinstance(metrics, dict):
        return {}
    out: Dict[str, float] = {}
    for key, value in metrics.items():
        try:
            f = float(value)
        except (TypeError, ValueError):
            continue
        if math.isfinite(f):
            out[str(key)] = f
    return out


def cosine(a: np.ndarray, b: np.ndarray) -> float:
    """Cosine similarity; 0.0 for zero/empty/mismatched vectors (an
    undefined angle must not look like perfect alignment)."""
    if a.size == 0 or a.shape != b.shape:
        return 0.0
    na = float(np.linalg.norm(a))
    nb = float(np.linalg.norm(b))
    if na <= 0.0 or nb <= 0.0:
        return 0.0
    return float(np.dot(a, b) / (na * nb))


def robust_z(values: Dict[str, float]) -> Dict[str, float]:
    """Cohort median/MAD z-scores (see module docstring for the exact
    denominator). Cohorts smaller than 3 score 0 everywhere: with one
    member there is no cohort to diverge from, and with two the
    deviations from the cohort mean are equal by symmetry (‖u_i − ū‖ =
    ‖u_1 − u_2‖/2 for both), so divergence is unattributable — scoring
    needs at least 3 participants."""
    if len(values) < 3:
        return {k: 0.0 for k in values}
    arr = np.asarray(list(values.values()), np.float64)
    med = float(np.median(arr))
    mad = float(np.median(np.abs(arr - med)))
    denom = _MAD_SIGMA * mad + _MEDIAN_FLOOR * abs(med) + _EPS
    return {k: float((v - med) / denom) for k, v in values.items()}


def participation_entropy(scales: Dict[str, float]) -> float:
    """Normalized Shannon entropy of the applied contribution weights
    (1.0 = perfectly uniform cohort, → 0 as one learner dominates)."""
    weights = [max(0.0, float(w)) for w in scales.values()]
    total = sum(weights)
    if total <= 0.0 or len(weights) < 2:
        return 1.0 if weights else 0.0
    h = -sum((w / total) * math.log(w / total)
             for w in weights if w > 0.0)
    return float(h / math.log(len(weights)))


def _quantiles(values: List[float]) -> Dict[str, float]:
    arr = np.asarray(values, np.float64)
    return {"min": round(float(arr.min()), 6),
            "p50": round(float(np.median(arr)), 6),
            "max": round(float(arr.max()), 6)}


class HealthMonitor:
    """Controller-side learning-health state machine.

    ``observe_update`` runs per accepted uplink (scheduling-executor
    thread), ``complete_round`` at each successful aggregation (same
    thread — the controller serializes both); ``scores``/``last_stats``/
    ``round_health`` are read from RPC threads (DescribeFederation), so
    shared state sits behind one small lock. Update vectors are buffered
    only until their cohort folds."""

    def __init__(self, alpha: float = DEFAULT_ALPHA,
                 anomaly_threshold: float = DEFAULT_ANOMALY_THRESHOLD):
        if not 0.0 < alpha <= 1.0:
            raise ValueError("health alpha must be in (0, 1]")
        if anomaly_threshold <= 0.0:
            raise ValueError("health anomaly_threshold must be > 0")
        self.alpha = float(alpha)
        self.anomaly_threshold = float(anomaly_threshold)
        self._lock = threading.Lock()
        # learner_id -> (update vector — sketched when wide, its
        # PRE-sketch width, summary dict) for the round in flight;
        # cleared (and memory released) at each cohort fold
        self._pending: Dict[str, Tuple[np.ndarray, int,
                                       Dict[str, Any]]] = {}
        self._evicted = 0  # buffered vectors dropped since the last fold
        # per-dimension cached subsample indices (same indices for every
        # learner, or cross-update cosines would be meaningless)
        self._sketch_idx: Dict[int, np.ndarray] = {}
        self._ewma: Dict[str, float] = {}
        self._last: Dict[str, Dict[str, Any]] = {}  # last uplink summary
        self._prev_community: Optional[np.ndarray] = None
        # previous community delta, sketched, plus its PRE-sketch width:
        # sketches of different-width vectors share a shape but live in
        # incomparable subspaces, so comparability is keyed on the width
        self._prev_delta: Optional[np.ndarray] = None
        self._prev_delta_dim: Optional[int] = None
        self.round_health: Dict[str, Any] = {}

    # -- per-uplink (scheduling executor) ------------------------------ #

    def _sketch(self, vec: np.ndarray) -> np.ndarray:
        """Fixed seeded coordinate subsample scaled by sqrt(d/k) for
        vectors wider than ``_SKETCH_DIM`` (norms/cosines preserved in
        expectation); identity for small vectors. The SAME indices apply
        to every vector of a given width — update vectors and the
        community delta must land in one comparable subspace."""
        if vec.size <= _SKETCH_DIM:
            return vec
        idx = self._sketch_idx.get(vec.size)
        if idx is None:
            rng = np.random.default_rng(_SKETCH_SEED)
            idx = np.sort(rng.choice(vec.size, _SKETCH_DIM, replace=False))
            self._sketch_idx[vec.size] = idx
        return vec[idx] * np.float32(math.sqrt(vec.size / _SKETCH_DIM))

    def note_community(self, community: Dict[str, np.ndarray]) -> None:
        """Anchor the reference for round/effective-step deltas (called at
        seed/replace; aggregation re-anchors inside complete_round)."""
        flat = flatten_model(community)
        with self._lock:
            self._prev_community = flat
            self._prev_delta = None
            self._prev_delta_dim = None

    def observe_update(self, learner_id: str, model: Dict[str, np.ndarray],
                       reference: Dict[str, np.ndarray],
                       train_metrics: Optional[Dict[str, float]] = None,
                       ) -> Dict[str, Any]:
        """One uplink's statistics; buffers the update vector for the
        cohort fold and returns the per-uplink summary. Single pass over
        the tensors: the per-tensor diff feeds both the flat vector and
        the per-layer norm breakdown (this is the health plane's hot
        path — bench.py section ``health`` tracks it)."""
        names = sorted(set(model) & set(reference))
        parts: List[np.ndarray] = []
        layer_sq: Dict[str, float] = {}
        for name in names:
            diff = (np.asarray(model[name], np.float32).ravel()
                    - np.asarray(reference[name], np.float32).ravel())
            parts.append(diff)
            key = layer_key(name)
            layer_sq[key] = layer_sq.get(key, 0.0) + float(diff @ diff)
        flat = (np.concatenate(parts) if parts else np.zeros(0, np.float32))
        dim = flat.size  # pre-sketch width: the comparability key
        norm = float(np.linalg.norm(flat)) if flat.size else 0.0
        finite = math.isfinite(norm)
        if not finite:
            # NaN/Inf weights (exploding gradients — the most diverged
            # update possible) are definitionally anomalous: never let
            # the vector enter the cohort mean (NaN would propagate into
            # EVERY learner's score and no anomaly would fire) or the
            # norm leak into JSON surfaces — buffer a sentinel instead;
            # the fold assigns it a finite off-scale divergence
            flat = np.zeros(0, np.float32)
        else:
            # bound buffer memory at O(SKETCH_DIM) per learner
            # (update_norm above stays exact; no-op for small models)
            flat = self._sketch(flat)
        with self._lock:
            prev_delta = self._prev_delta
            prev_dim = self._prev_delta_dim
        summary: Dict[str, Any] = {
            "update_norm": round(norm, 6) if finite else 0.0,
            "layer_norms": {k: round(math.sqrt(v), 6)
                            for k, v in sorted(layer_sq.items(),
                                               key=lambda kv: -kv[1])
                            [:_MAX_LAYER_ROWS]
                            if math.isfinite(v)},
            # comparable only when the pre-sketch widths match — two
            # different-width vectors sketch to the same shape but
            # sample different coordinates (a noise cosine, not 0.0)
            "cos_prev_delta": round(
                cosine(flat, prev_delta)
                if finite and prev_delta is not None and dim == prev_dim
                else 0.0, 6),
        }
        if not finite:
            summary["non_finite"] = True
        clean = finite_metrics(train_metrics) if train_metrics else {}
        if clean:
            summary["train_metrics"] = clean
        with self._lock:
            self._pending[learner_id] = (flat, dim, summary)
            while len(self._pending) > _MAX_PENDING:
                self._pending.pop(next(iter(self._pending)))
                self._evicted += 1
            self._last[learner_id] = dict(summary)
        return summary

    # -- per-round cohort fold (scheduling executor) ------------------- #

    def complete_round(self, round_no: int,
                       community: Dict[str, np.ndarray],
                       scales: Dict[str, float],
                       ) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
        """Fold the buffered cohort: cohort-mean cosines, robust-z
        deviation scores, EWMA divergence update, and the round's
        convergence snapshot. Returns ``(round_health, anomalies)``."""
        with self._lock:
            pending = dict(self._pending)
            self._pending.clear()
            evicted, self._evicted = self._evicted, 0
            prev_community = self._prev_community
        if evicted:
            # never silently truncate: evicted learners get no score
            # this round, and the snapshot says so
            logger.warning(
                "health pending buffer overflowed: %d update vector(s) "
                "evicted before the round %d fold (cohort larger than "
                "the %d-entry buffer); evicted learners are unscored "
                "this round", evicted, round_no, _MAX_PENDING)
        new_flat = flatten_model(community)
        update_norm = 0.0
        effective_step = 0.0
        delta: Optional[np.ndarray] = None
        if (prev_community is not None
                and prev_community.shape == new_flat.shape
                and new_flat.size):
            delta = new_flat - prev_community
            update_norm = float(np.linalg.norm(delta))
            if not math.isfinite(update_norm):
                # a NaN community (a non-finite stored model survived a
                # non-robust aggregation) must not leak into the JSON
                # surfaces or next round's cosine reference
                update_norm, delta = 0.0, None
            else:
                prev_norm = float(np.linalg.norm(prev_community))
                # like cosine(): a ~zero-norm reference (zero-seeded
                # model) makes the ratio undefined — report 0.0, not a
                # ~1e12 blowup
                effective_step = (update_norm / prev_norm
                                  if prev_norm > 1e-9 else 0.0)

        # Cohort alignment + deviation. Comparability is keyed on the
        # PRE-sketch width: a partial/malformed/version-skewed update
        # (different tensor set) must not enter the mean — sketched, it
        # would share the dominant SHAPE while sampling different
        # coordinates, polluting every learner's statistics with
        # subspace noise. Off-width updates go unscored this round.
        entries = {lid: (v, d) for lid, (v, d, _s) in pending.items()
                   if v.size}
        dims = [d for _v, d in entries.values()]
        dominant = max(set(dims), key=dims.count) if dims else None
        vecs = {lid: v for lid, (v, d) in entries.items() if d == dominant}
        deviations: Dict[str, float] = {}
        cos_cohort: Dict[str, float] = {}
        if vecs:
            mean_u = np.mean(list(vecs.values()), axis=0)
            for lid, v in vecs.items():
                cos_cohort[lid] = round(cosine(v, mean_u), 6)
                deviations[lid] = float(np.linalg.norm(v - mean_u))
        raw_z = robust_z(deviations)
        for lid, (_v, _d, summary) in pending.items():
            if summary.get("non_finite"):
                # excluded from the cohort mean above; scored with a
                # finite off-scale sentinel so the anomaly always fires
                raw_z[lid] = self.anomaly_threshold * _NON_FINITE_Z_FACTOR

        anomalies: List[Dict[str, Any]] = []
        with self._lock:
            for lid, z in raw_z.items():
                prev = self._ewma.get(lid, 0.0)
                clamped = max(0.0, z)
                score = (clamped if prev <= 0.0
                         else self.alpha * clamped + (1 - self.alpha) * prev)
                self._ewma[lid] = score
                last = self._last.get(lid)
                if last is not None:
                    last["cos_cohort"] = cos_cohort.get(lid, 0.0)
                    last["divergence_raw"] = round(z, 4)
                    last["divergence_score"] = round(score, 4)
                if z >= self.anomaly_threshold:
                    anomalies.append({
                        "learner_id": lid, "round": round_no,
                        "score": round(score, 4), "raw": round(z, 4),
                        "update_norm": (pending[lid][2]["update_norm"]
                                        if lid in pending else 0.0)})
            self._prev_community = new_flat
            # sketched like every buffered update vector, so next
            # round's cos_prev_delta compares in the same subspace;
            # the pre-sketch width is the comparability key
            self._prev_delta = (self._sketch(delta)
                                if delta is not None else None)
            self._prev_delta_dim = (delta.size if delta is not None
                                    else None)
            scores_snapshot = {lid: round(s, 4)
                               for lid, s in self._ewma.items()}

        # non-finite losses (a zero-step task ships loss=NaN) must not
        # poison the cohort quantiles — one bad learner would otherwise
        # turn the whole round's cohort_loss into NaN
        losses = [s["train_metrics"]["loss"]
                  for _v, _d, s in pending.values()
                  if math.isfinite(s.get("train_metrics", {}).get(
                      "loss", math.nan))]
        health: Dict[str, Any] = {
            "round": int(round_no),
            "round_update_norm": round(update_norm, 6),
            "effective_step": round(effective_step, 6),
            "participation_entropy": round(
                participation_entropy(scales), 4),
            "update_norms": {lid: s["update_norm"]
                             for lid, (_v, _d, s) in pending.items()},
            "cos_cohort": cos_cohort,
            "cos_prev_delta": {lid: s["cos_prev_delta"]
                               for lid, (_v, _d, s) in pending.items()},
            "divergence_raw": {lid: round(z, 4)
                               for lid, z in raw_z.items()},
            "divergence_score": scores_snapshot,
            "anomalous": sorted(a["learner_id"] for a in anomalies),
        }
        if evicted:
            health["pending_evicted"] = int(evicted)
        if losses:
            health["cohort_loss"] = _quantiles(losses)
        with self._lock:
            self.round_health = health
        return health, anomalies

    # -- reads (RPC threads) + lifecycle ------------------------------- #

    def scores(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._ewma)

    def last_stats(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {lid: dict(s) for lid, s in self._last.items()}

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self.round_health)

    def drop(self, learner_id: str) -> None:
        """Forget a departed learner (bounded state + gauge cardinality
        under churn, same posture as the straggler series prune)."""
        with self._lock:
            self._pending.pop(learner_id, None)
            self._ewma.pop(learner_id, None)
            self._last.pop(learner_id, None)

    # -- checkpoint persistence (controller save/restore) -------------- #

    def export_state(self) -> Dict[str, Any]:
        """Scores + last summaries + the latest round snapshot — small,
        codec-serializable. Update VECTORS are deliberately not
        persisted (O(params) each); after a failover the first fold has
        no previous delta and ``cos_prev_delta`` restarts at 0."""
        with self._lock:
            return {"ewma": {k: float(v) for k, v in self._ewma.items()},
                    "last": {k: dict(v) for k, v in self._last.items()},
                    "round_health": dict(self.round_health)}

    def restore_state(self, state: Dict[str, Any]) -> None:
        with self._lock:
            self._ewma = {k: float(v)
                          for k, v in (state.get("ewma") or {}).items()}
            self._last = {k: dict(v)
                          for k, v in (state.get("last") or {}).items()}
            self.round_health = dict(state.get("round_health") or {})
