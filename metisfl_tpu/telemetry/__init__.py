"""Federation-wide telemetry: trace spans + metrics registry + events.

Zero-dependency observability for the federation runtime (ROADMAP
north-star: a production service must tell you *where* a round is stuck
while it is stuck, not after the experiment ends):

- :mod:`metisfl_tpu.telemetry.trace` — context-manager spans with
  federation-wide trace/span ids, a process-local JSONL sink, and
  propagation over gRPC metadata (controller dispatch → learner train →
  aggregation stitch into one tree per round, rooted at the controller's
  round span; the driver collects every process's sink files).
- :mod:`metisfl_tpu.telemetry.metrics` — thread-safe counters / gauges /
  histograms with Prometheus text exposition, served via the
  ``GetMetrics`` RPC on controller and learner and the optional
  plain-HTTP ``/metrics`` listener (:mod:`metisfl_tpu.telemetry.httpd`).
- :mod:`metisfl_tpu.telemetry.events` — typed, structured event journal
  (joins, rounds, dispatches, retries, faults) in a bounded ring buffer
  + JSONL sink; the tail rides in ``DescribeFederation`` snapshots and
  post-mortem bundles.
- :mod:`metisfl_tpu.telemetry.postmortem` — the flight recorder: on an
  unhandled crash, chaos kill, or failover relaunch, a process dumps its
  event tail + open spans + metrics into ``<workdir>/postmortem/``.
- ``python -m metisfl_tpu.telemetry <trace dir or .jsonl>`` renders a
  round's span tree from the sink; ``--postmortem`` renders the
  pre-crash timeline from bundles; ``python -m metisfl_tpu.status``
  live-watches a running federation over ``DescribeFederation``.

Everything is opt-out via federation config ``telemetry.enabled=false``
(:func:`apply_config`), and the event journal separately via
``telemetry.events.enabled=false``; the disabled paths are
attribute-check cheap.
"""

from __future__ import annotations

from metisfl_tpu.telemetry import events, metrics, postmortem, trace
from metisfl_tpu.telemetry.metrics import parse_exposition, registry
from metisfl_tpu.telemetry.trace import (
    METADATA_KEY,
    SpanContext,
    current_context,
    extract,
    outbound_metadata,
    span,
)

__all__ = [
    "metrics",
    "trace",
    "events",
    "postmortem",
    "registry",
    "parse_exposition",
    "span",
    "current_context",
    "extract",
    "outbound_metadata",
    "SpanContext",
    "METADATA_KEY",
    "apply_config",
    "render_metrics",
]


def render_metrics() -> str:
    """The process registry's Prometheus exposition (GetMetrics RPC body)."""
    return registry().render()


def apply_config(telemetry_config, service: str = "",
                 config_hash: str = "") -> None:
    """Configure process-wide telemetry from a federation config's
    ``telemetry`` section (config/federation.py TelemetryConfig): one call
    in each process entry point (controller/learner ``__main__``,
    in-process federation, tests). ``config_hash`` stamps post-mortem
    bundles so incidents from different configs are tellable apart."""
    enabled = bool(getattr(telemetry_config, "enabled", True))
    metrics.set_enabled(enabled)
    sink_dir = getattr(telemetry_config, "dir", "")
    ev_cfg = getattr(telemetry_config, "events", None)
    ev_enabled = enabled and bool(getattr(ev_cfg, "enabled", True))
    events.configure(enabled=ev_enabled, service=service,
                     dir=sink_dir if ev_enabled else "",
                     ring_size=int(getattr(ev_cfg, "ring_size", 0) or 0))
    if enabled:
        trace.configure(enabled=True, service=service, dir=sink_dir)
    else:
        # disable without forgetting any previously configured sink dir:
        # a later re-enable (set_enabled / a default-enabled config in
        # the same process) restores it
        trace.set_enabled(False)
    pm_dir = getattr(telemetry_config, "postmortem_dir", "")
    if enabled and pm_dir:
        postmortem.configure(pm_dir, service=service,
                             config_hash=config_hash)
