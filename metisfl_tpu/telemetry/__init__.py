"""Federation-wide telemetry: trace spans + metrics registry.

Zero-dependency observability for the federation runtime (ROADMAP
north-star: a production service must tell you *where* a round is stuck
while it is stuck, not after the experiment ends):

- :mod:`metisfl_tpu.telemetry.trace` — context-manager spans with
  federation-wide trace/span ids, a process-local JSONL sink, and
  propagation over gRPC metadata (controller dispatch → learner train →
  aggregation stitch into one tree per round, rooted at the controller's
  round span; the driver collects every process's sink files).
- :mod:`metisfl_tpu.telemetry.metrics` — thread-safe counters / gauges /
  histograms with Prometheus text exposition, served via the
  ``GetMetrics`` RPC on controller and learner and the optional
  plain-HTTP ``/metrics`` listener (:mod:`metisfl_tpu.telemetry.httpd`).
- ``python -m metisfl_tpu.telemetry <trace dir or .jsonl>`` renders a
  round's span tree from the sink.

Everything is opt-out via federation config ``telemetry.enabled=false``
(:func:`apply_config`); the disabled paths are attribute-check cheap.
"""

from __future__ import annotations

from metisfl_tpu.telemetry import metrics, trace
from metisfl_tpu.telemetry.metrics import parse_exposition, registry
from metisfl_tpu.telemetry.trace import (
    METADATA_KEY,
    SpanContext,
    current_context,
    extract,
    outbound_metadata,
    span,
)

__all__ = [
    "metrics",
    "trace",
    "registry",
    "parse_exposition",
    "span",
    "current_context",
    "extract",
    "outbound_metadata",
    "SpanContext",
    "METADATA_KEY",
    "apply_config",
    "render_metrics",
]


def render_metrics() -> str:
    """The process registry's Prometheus exposition (GetMetrics RPC body)."""
    return registry().render()


def apply_config(telemetry_config, service: str = "") -> None:
    """Configure process-wide telemetry from a federation config's
    ``telemetry`` section (config/federation.py TelemetryConfig): one call
    in each process entry point (controller/learner ``__main__``,
    in-process federation, tests)."""
    enabled = bool(getattr(telemetry_config, "enabled", True))
    metrics.set_enabled(enabled)
    if enabled:
        trace.configure(enabled=True, service=service,
                        dir=getattr(telemetry_config, "dir", ""))
    else:
        # disable without forgetting any previously configured sink dir:
        # a later re-enable (set_enabled / a default-enabled config in
        # the same process) restores it
        trace.set_enabled(False)
