"""Federation-wide telemetry: trace spans + metrics registry + events.

Zero-dependency observability for the federation runtime (ROADMAP
north-star: a production service must tell you *where* a round is stuck
while it is stuck, not after the experiment ends):

- :mod:`metisfl_tpu.telemetry.trace` — context-manager spans with
  federation-wide trace/span ids, a process-local JSONL sink, and
  propagation over gRPC metadata (controller dispatch → learner train →
  aggregation stitch into one tree per round, rooted at the controller's
  round span; the driver collects every process's sink files).
- :mod:`metisfl_tpu.telemetry.metrics` — thread-safe counters / gauges /
  histograms with Prometheus text exposition, served via the
  ``GetMetrics`` RPC on controller and learner and the optional
  plain-HTTP ``/metrics`` listener (:mod:`metisfl_tpu.telemetry.httpd`).
- :mod:`metisfl_tpu.telemetry.events` — typed, structured event journal
  (joins, rounds, dispatches, retries, faults) in a bounded ring buffer
  + JSONL sink; the tail rides in ``DescribeFederation`` snapshots and
  post-mortem bundles.
- :mod:`metisfl_tpu.telemetry.postmortem` — the flight recorder: on an
  unhandled crash, chaos kill, or failover relaunch, a process dumps its
  event tail + open spans + metrics into ``<workdir>/postmortem/``.
- :mod:`metisfl_tpu.telemetry.health` — the learning-health plane:
  per-uplink update statistics, per-learner divergence scores, and
  per-round convergence snapshots, computed controller-side and
  surfaced through every plane above (opt-out via
  ``telemetry.health.enabled=false``; controller-local, so
  :func:`apply_config` has nothing process-global to arm for it).
- :mod:`metisfl_tpu.telemetry.sketch` + cardinality budgets in the
  metrics registry — past ``telemetry.cardinality_budget`` the
  per-learner families collapse to mergeable quantile digests and
  top-K heavy-hitter sketches, bounding exposition / status /
  checkpoints at O(budget) for 100k-client fleets
  (docs/OBSERVABILITY.md "Telemetry at scale").
- :mod:`metisfl_tpu.telemetry.alerts` — the SLO alerting plane:
  config-driven threshold / rate / digest-quantile rules with ``for:``
  holds and resolve hysteresis, evaluated over the bounded
  :mod:`metisfl_tpu.telemetry.timeseries` ring that also feeds the
  ``status --watch`` sparklines.
- ``python -m metisfl_tpu.telemetry <trace dir or .jsonl>`` renders a
  round's span tree from the sink; ``--postmortem`` renders the
  pre-crash timeline from bundles (including alerts at death);
  ``python -m metisfl_tpu.status`` live-watches a running federation
  over ``DescribeFederation``.

Everything is opt-out via federation config ``telemetry.enabled=false``
(:func:`apply_config`), and the event journal separately via
``telemetry.events.enabled=false``; the disabled paths are
attribute-check cheap.
"""

from __future__ import annotations

from metisfl_tpu.telemetry import (
    events,
    health,
    metrics,
    postmortem,
    sketch,
    timeseries,
    trace,
)
from metisfl_tpu.telemetry import alerts  # needs events/metrics/timeseries
from metisfl_tpu.telemetry.metrics import parse_exposition, registry
from metisfl_tpu.telemetry.trace import (
    METADATA_KEY,
    SpanContext,
    current_context,
    extract,
    outbound_metadata,
    span,
)

# --------------------------------------------------------------------- #
# Canonical metric series names. SURVEY.md §5.5 flags stringly-typed
# metric names as a reference defect (config/federation.py:16 cites it):
# every registration site and every scrape-side consumer imports these,
# so a typo fails at import time instead of silently minting a new
# series. The full catalog (types, labels, semantics) lives in
# docs/OBSERVABILITY.md "Metric names and labels".
# --------------------------------------------------------------------- #

# controller round lifecycle (controller/core.py)
M_ROUND_DURATION_SECONDS = "round_duration_seconds"
M_ROUNDS_TOTAL = "rounds_total"
M_ROUND_PHASE_DURATION_SECONDS = "round_phase_duration_seconds"
M_UPLINK_BYTES_TOTAL = "uplink_bytes_total"
M_CONTROLLER_ACTIVE_LEARNERS = "controller_active_learners"
M_AGGREGATION_FAILURES_TOTAL = "aggregation_failures_total"
M_LEARNER_STRAGGLER_SCORE = "learner_straggler_score"
# churn-tolerant scheduling (controller/core.py + selection.py)
M_LEARNER_DROPPED_TOTAL = "learner_dropped_total"
M_DISPATCH_RETRIES_TOTAL = "dispatch_retries_total"
M_ROUNDS_REDISPATCHED_TOTAL = "rounds_redispatched_total"
M_LEARNER_CHURN_SCORE = "learner_churn_score"
# learning-health plane (controller/core.py + telemetry/health.py)
M_LEARNER_DIVERGENCE_SCORE = "learner_divergence_score"
M_ROUND_UPDATE_NORM = "round_update_norm"
# causal tracing plane (telemetry/causal.py + telemetry/fabric.py)
M_ROUND_CRITICAL_PATH_SECONDS = "round_critical_path_seconds"
# performance observatory (telemetry/profile.py + controller/core.py)
M_DOWNLINK_BYTES_TOTAL = "downlink_bytes_total"
M_CODEC_LEARNER_SECONDS = "codec_learner_seconds_total"
M_LEARNER_ACHIEVED_MFU = "learner_achieved_mfu"
M_LEARNER_STEP_MS_EWMA = "learner_step_ms_ewma"
M_LEARNER_HBM_PEAK_BYTES = "learner_hbm_peak_bytes"
# learner runtime (learner/learner.py)
M_LEARNER_TRAIN_DURATION_SECONDS = "learner_train_duration_seconds"
M_LEARNER_STEP_MILLISECONDS = "learner_step_milliseconds"
M_LEARNER_JIT_COMPILE_SECONDS = "learner_jit_compile_seconds"
M_LEARNER_TASKS_TOTAL = "learner_tasks_total"
M_LEARNER_EVAL_DURATION_SECONDS = "learner_eval_duration_seconds"
M_LEARNER_REATTACH_TOTAL = "learner_reattach_total"
# RPC transport (comm/rpc.py)
M_RPC_PEER_BYTES_TOTAL = "rpc_peer_bytes_total"
M_RPC_CLIENT_CALLS_TOTAL = "rpc_client_calls_total"
M_RPC_CLIENT_LATENCY_SECONDS = "rpc_client_latency_seconds"
M_RPC_CLIENT_BYTES_TOTAL = "rpc_client_bytes_total"
M_RPC_CLIENT_ERRORS_TOTAL = "rpc_client_errors_total"
M_RPC_SERVER_CALLS_TOTAL = "rpc_server_calls_total"
M_RPC_SERVER_LATENCY_SECONDS = "rpc_server_latency_seconds"
M_RPC_SERVER_BYTES_TOTAL = "rpc_server_bytes_total"
M_RPC_SERVER_ERRORS_TOTAL = "rpc_server_errors_total"
# wire codec (comm/codec.py)
M_CODEC_DURATION_SECONDS = "codec_duration_seconds"
M_CODEC_BYTES_TOTAL = "codec_bytes_total"
# model store cache (store/cached.py)
M_STORE_CACHE_HITS_TOTAL = "store_cache_hits_total"
M_STORE_CACHE_MISSES_TOTAL = "store_cache_misses_total"
M_STORE_CACHE_RESIDENT_BYTES = "store_cache_resident_bytes"
M_STORE_CACHE_ENTRIES = "store_cache_entries"
# integrity framing (tensor/pytree.py)
M_CORRUPT_PAYLOADS_TOTAL = "corrupt_payloads_total"
# chaos injector (chaos/injector.py)
M_CHAOS_FAULTS_INJECTED_TOTAL = "chaos_faults_injected_total"
# driver failover supervision (driver/session.py)
M_CONTROLLER_RESTARTS_TOTAL = "controller_restarts_total"
M_GATEWAY_RESTARTS_TOTAL = "gateway_restarts_total"
# controller hot-standby (controller/wal.py + __main__.py --standby)
M_CONTROLLER_WAL_RECORDS_TOTAL = "controller_wal_records_total"
M_CONTROLLER_WAL_LAG_RECORDS = "controller_wal_lag_records"
M_CONTROLLER_FAILOVER_TOTAL = "controller_failover_total"
M_CONTROLLER_FAILOVER_PROMOTE_SECONDS = "controller_failover_promote_seconds"
# model registry (registry/registry.py)
M_REGISTRY_VERSIONS_TOTAL = "registry_versions_total"
M_REGISTRY_VERSION_STATE = "registry_version_state"
M_REGISTRY_PROMOTIONS_TOTAL = "registry_promotions_total"
M_REGISTRY_ROLLBACKS_TOTAL = "registry_rollbacks_total"
# telemetry-at-scale plane (telemetry/metrics.py cardinality budgets +
# telemetry/alerts.py; docs/OBSERVABILITY.md "Telemetry at scale")
M_METRICS_SERIES_OVERFLOW_TOTAL = metrics.SERIES_OVERFLOW_TOTAL
M_METRICS_FAMILY_SERIES = metrics.FAMILY_SERIES
M_ALERTS_ACTIVE = alerts.ALERTS_ACTIVE
M_ALERTS_FIRED_TOTAL = alerts.ALERTS_FIRED_TOTAL
# continuous profiling plane (telemetry/prof.py sampler + lock wrappers)
M_PROF_SAMPLES_TOTAL = "prof_samples_total"
M_LOCK_WAIT_SECONDS = "lock_wait_seconds"
M_LOCK_CONTENTION_TOTAL = "lock_contention_total"
# accelerator runtime observability (telemetry/runtime.py)
M_JAX_COMPILES_TOTAL = "jax_compiles_total"
M_JAX_COMPILE_SECONDS = "jax_compile_seconds"
M_JAX_DEVICE_MEMORY_BYTES = "jax_device_memory_bytes"
# fleet telemetry fabric (telemetry/fabric.py FleetCollector)
M_FABRIC_COLLECTIONS_TOTAL = "fabric_collections_total"
M_FABRIC_PEER_OFFSET_MS = "fabric_peer_clock_offset_ms"
M_FABRIC_COLLECT_SECONDS = "fabric_collect_duration_seconds"
# distributed slice aggregators (aggregation/slice.py + distributed.py)
M_SLICE_UPLINKS_TOTAL = "slice_uplinks_total"
M_SLICE_HELD_MODELS = "slice_held_models"
M_SLICE_FAILURES_TOTAL = "slice_failures_total"
M_SLICE_REHOMING_SECONDS = "slice_rehoming_seconds"
# masked partial-fold plane (secure/distributed.py + recovery.py)
M_SECURE_MASKED_UPLINKS_TOTAL = "secure_masked_uplinks_total"
M_SECURE_MASKED_FOLDS_TOTAL = "secure_masked_folds_total"
M_SECURE_SETTLEMENT_SECONDS = "secure_settlement_seconds"
M_SECURE_RECOVERED_PARTIES_TOTAL = "secure_recovered_parties_total"
M_SECURE_MASK_GEN_SECONDS = "secure_mask_gen_seconds"
# serving gateway (serving/gateway.py)
M_SERVING_REQUESTS_TOTAL = "serving_requests_total"
M_SERVING_REQUEST_LATENCY_SECONDS = "serving_request_latency_seconds"
M_SERVING_BATCH_ROWS = "serving_batch_rows"
M_SERVING_MODEL_VERSION = "serving_model_version"
M_SERVING_SWAPS_TOTAL = "serving_swaps_total"
M_SERVING_QUEUE_DEPTH = "serving_queue_depth"
# continuous-batching decode (serving/decode.py)
M_SERVING_DECODE_QUEUE_DEPTH = "serving_decode_queue_depth"
M_SERVING_DECODE_ACTIVE_SLOTS = "serving_decode_active_slots"
M_SERVING_DECODE_TOKENS_TOTAL = "serving_decode_tokens_total"
M_SERVING_DECODE_TOKENS_PER_SEC = "serving_decode_tokens_per_sec"
# serving fleet: router + autoscaler (serving/fleet.py + driver/session.py)
M_ROUTER_REQUESTS_TOTAL = "serving_router_requests_total"
M_ROUTER_RETRIES_TOTAL = "serving_router_retries_total"
M_ROUTER_REQUEST_LATENCY_SECONDS = "serving_router_request_latency_seconds"
M_SERVING_REPLICA_UP = "serving_replica_up"
M_SERVING_FLEET_REPLICAS = "serving_fleet_replicas"
M_SERVING_SCALE_TOTAL = "serving_scale_total"

__all__ = [
    "metrics",
    "trace",
    "events",
    "health",
    "postmortem",
    "alerts",
    "sketch",
    "timeseries",
    "registry",
    "prune_learner",
    "parse_exposition",
    "span",
    "current_context",
    "extract",
    "outbound_metadata",
    "SpanContext",
    "METADATA_KEY",
    "apply_config",
    "render_metrics",
] + [name for name in dir() if name.startswith("M_")]


def render_metrics() -> str:
    """The process registry's Prometheus exposition (GetMetrics RPC body)."""
    return registry().render()


def prune_learner(learner_id: str) -> None:
    """Drop every per-learner metric series for a departed learner, in
    ONE place: all registry families registered with a cardinality
    label (``budget_label`` — the "learner"/"peer" families) plus the
    codec/RPC attribution state that backs them. The straggler /
    divergence / churn / profile planes used to hand-prune their own
    gauges on ``leave()``; they all call (or are covered by) this
    helper now, and the drift-guard test in tests/test_scaletel.py
    asserts no ``M_*`` per-learner family leaks a series after a
    join→leave cycle."""
    registry().prune_label_value(learner_id)
    # codec encode/decode process totals + any per-peer RPC byte state —
    # non-series attribution that would re-mint series if left behind
    from metisfl_tpu.telemetry import profile as _profile

    _profile.prune_attribution_series(learner_id)


def apply_config(telemetry_config, service: str = "",
                 config_hash: str = "") -> None:
    """Configure process-wide telemetry from a federation config's
    ``telemetry`` section (config/federation.py TelemetryConfig): one call
    in each process entry point (controller/learner ``__main__``,
    in-process federation, tests). ``config_hash`` stamps post-mortem
    bundles so incidents from different configs are tellable apart."""
    enabled = bool(getattr(telemetry_config, "enabled", True))
    metrics.set_enabled(enabled)
    # cardinality budget (docs/OBSERVABILITY.md "Telemetry at scale"):
    # 0 (default) keeps every per-learner family exact — today's
    # behavior, bit-identical exposition
    registry().set_cardinality_budget(
        int(getattr(telemetry_config, "cardinality_budget", 0) or 0))
    sink_dir = getattr(telemetry_config, "dir", "")
    ev_cfg = getattr(telemetry_config, "events", None)
    ev_enabled = enabled and bool(getattr(ev_cfg, "enabled", True))
    events.configure(enabled=ev_enabled, service=service,
                     dir=sink_dir if ev_enabled else "",
                     ring_size=int(getattr(ev_cfg, "ring_size", 0) or 0))
    if enabled:
        trace.configure(enabled=True, service=service, dir=sink_dir)
    else:
        # disable without forgetting any previously configured sink dir:
        # a later re-enable (set_enabled / a default-enabled config in
        # the same process) restores it
        trace.set_enabled(False)
    pm_dir = getattr(telemetry_config, "postmortem_dir", "")
    if enabled and pm_dir:
        postmortem.configure(pm_dir, service=service,
                             config_hash=config_hash)
    # fleet telemetry fabric (telemetry/fabric.py): arm the process
    # exporter + the finished-span ring, and mint a fresh epoch so
    # collectors treat this configuration as a new incarnation
    fab_cfg = getattr(telemetry_config, "fabric", None)
    fabric.configure(
        enabled=enabled and bool(getattr(fab_cfg, "enabled", True)),
        span_ring=int(getattr(fab_cfg, "span_ring", 0) or 0))
    # continuous profiling plane (telemetry/prof.py): arm (or stop) the
    # stack sampler and flip the instrumented-lock factories — hot locks
    # constructed after this call adopt the configured mode
    prof_cfg = getattr(telemetry_config, "prof", None)
    prof.configure(
        enabled=enabled and bool(getattr(prof_cfg, "enabled", True)),
        hz=float(getattr(prof_cfg, "hz", 0.0) or 0.0),
        budget=int(getattr(prof_cfg, "budget", 0) or 0))
    # accelerator runtime observability (telemetry/runtime.py): arm the
    # XLA compile listener + memory accounting; the service name picks
    # the memory-attribution plane (controller / learner / serving)
    rt_cfg = getattr(telemetry_config, "runtime", None)
    runtime.set_plane(service)
    runtime.configure(
        enabled=enabled and bool(getattr(rt_cfg, "enabled", True)),
        budget=int(getattr(rt_cfg, "budget", 0) or 0),
        mem_every_s=float(getattr(rt_cfg, "mem_every_s", 0.0) or 0.0),
        storm_window_s=float(
            getattr(rt_cfg, "storm_window_s", 0.0) or 0.0),
        storm_threshold=int(
            getattr(rt_cfg, "storm_threshold", 0) or 0))


# Imported at the BOTTOM so profile.py (which reads the M_* constants at
# its own import time) sees a fully-initialized package — the other
# submodules import nothing back from this package. fabric imports only
# sibling submodules at module level (its RPC client is lazy), so the
# same late import keeps the comm <-> telemetry layering acyclic. prof
# loads FIRST: fabric, profile, and runtime all reference it; runtime
# loads before fabric (fabric's CollectTelemetry serves its section).
from metisfl_tpu.telemetry import prof  # noqa: E402
from metisfl_tpu.telemetry import runtime  # noqa: E402
from metisfl_tpu.telemetry import fabric, profile  # noqa: E402

__all__ += ["profile", "fabric", "prof", "runtime"]
