"""Config-driven SLO alerting over the in-process metrics registry.

Every observability plane so far (PRs 1/3/4/6) produces signals an
operator can *look at*; nothing watches them. This module is the
watching half: declarative rules (federation config ``telemetry.alerts``)
evaluated on a bounded cadence against the live registry, with the
state machine production alerting systems converge on — a ``for:`` hold
before firing (one slow sample is not an incident) and resolve
hysteresis (a value oscillating at the threshold must not flap the
alert). Firing and resolving emit typed journal events
(:class:`~metisfl_tpu.telemetry.events.AlertFiring` /
``AlertResolved``), drive the ``alerts_active`` / ``alerts_fired_total``
metric families, surface in ``DescribeFederation`` → the ``status``
CLI's ``alerts:`` line, and ride in post-mortem bundles ("alerts at
death" — the firing page nobody got).

Rule schema (one dict per rule; validated at config load exactly like
chaos rules — a typo'd rule fails startup, not fire-time)::

    telemetry:
      alerts:
        - name: drop_burst              # unique; the alert's identity
          metric: learner_dropped_total # registry family name
          kind: rate                    # value | rate | quantile
          labels: {reason: quarantine}  # optional: one series; omitted
                                        #   = sum across the family
          window_s: 30                  # rate: trailing window
          quantile: 0.99                # quantile: which one (digest-
                                        #   backed past the budget)
          op: ">"                       # > >= < <=
          threshold: 0.5
          for_s: 5                      # breach must HOLD this long
          resolve_ratio: 0.8            # hysteresis: a ">" alert only
                                        #   resolves below 0.8*threshold
                                        #   ("<" ops: above thr/ratio)
          severity: warning             # info | warning | critical

Evaluation happens on the engine's daemon thread
(``telemetry.alerts_interval_s``) plus a synchronous :meth:`poll` at
every round close, over a bounded
:class:`~metisfl_tpu.telemetry.timeseries.TimeSeriesRing` that doubles
as the ``status --watch`` sparkline source. A rule whose family is not
registered yet samples 0.0 — rules may be declared before the first
learner mints the series.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from metisfl_tpu.telemetry import events as _events
from metisfl_tpu.telemetry import metrics as _metrics
from metisfl_tpu.telemetry.timeseries import TimeSeriesRing

logger = logging.getLogger("metisfl_tpu.telemetry")

_KINDS = ("value", "rate", "quantile")
_OPS = (">", ">=", "<", "<=")
_SEVERITIES = ("info", "warning", "critical")

# registry families sampled into the ring every poll even with no rule
# over them — the status CLI's default sparklines
DEFAULT_SERIES = ("rounds_total", "controller_active_learners",
                  "round_update_norm")

ALERTS_ACTIVE = "alerts_active"
ALERTS_FIRED_TOTAL = "alerts_fired_total"


@dataclass(frozen=True)
class AlertRule:
    """One validated alert rule (see module docstring for the schema)."""

    name: str
    metric: str
    threshold: float
    kind: str = "value"
    labels: Dict[str, str] = field(default_factory=dict)
    window_s: float = 60.0
    quantile: float = 0.99
    op: str = ">"
    for_s: float = 0.0
    resolve_ratio: float = 1.0
    severity: str = "warning"

    _FIELDS = ("name", "metric", "threshold", "kind", "labels", "window_s",
               "quantile", "op", "for_s", "resolve_ratio", "severity")

    @classmethod
    def from_spec(cls, spec: Dict[str, Any]) -> "AlertRule":
        if not isinstance(spec, dict):
            raise ValueError(f"alert rule must be a mapping, got {spec!r}")
        unknown = set(spec) - set(cls._FIELDS)
        if unknown:
            raise ValueError(
                f"alert rule {spec.get('name', '?')!r}: unknown keys "
                f"{sorted(unknown)}")
        name = str(spec.get("name", "") or "")
        if not name:
            raise ValueError("alert rule needs a non-empty 'name'")
        metric = str(spec.get("metric", "") or "")
        if not metric:
            raise ValueError(f"alert rule {name!r} needs a 'metric'")
        if "threshold" not in spec:
            raise ValueError(f"alert rule {name!r} needs a 'threshold'")
        try:
            threshold = float(spec["threshold"])
        except (TypeError, ValueError):
            raise ValueError(
                f"alert rule {name!r}: threshold {spec['threshold']!r} "
                "is not a number") from None
        kind = str(spec.get("kind", "value"))
        if kind not in _KINDS:
            raise ValueError(
                f"alert rule {name!r}: kind {kind!r} not in {_KINDS}")
        op = str(spec.get("op", ">"))
        if op not in _OPS:
            raise ValueError(f"alert rule {name!r}: op {op!r} not in {_OPS}")
        labels = spec.get("labels") or {}
        if (not isinstance(labels, dict)
                or not all(isinstance(k, str) for k in labels)):
            raise ValueError(
                f"alert rule {name!r}: labels must be a string mapping")
        window_s = float(spec.get("window_s", 60.0))
        if kind == "rate" and window_s <= 0.0:
            raise ValueError(
                f"alert rule {name!r}: rate rules need window_s > 0")
        quantile = float(spec.get("quantile", 0.99))
        if not 0.0 < quantile <= 1.0:
            raise ValueError(
                f"alert rule {name!r}: quantile must be in (0, 1]")
        for_s = float(spec.get("for_s", 0.0))
        if for_s < 0.0:
            raise ValueError(f"alert rule {name!r}: for_s must be >= 0")
        resolve_ratio = float(spec.get("resolve_ratio", 1.0))
        if not 0.0 < resolve_ratio <= 1.0:
            raise ValueError(
                f"alert rule {name!r}: resolve_ratio must be in (0, 1] "
                "(1 = no hysteresis)")
        severity = str(spec.get("severity", "warning"))
        if severity not in _SEVERITIES:
            raise ValueError(
                f"alert rule {name!r}: severity {severity!r} not in "
                f"{_SEVERITIES}")
        return cls(name=name, metric=metric, threshold=threshold, kind=kind,
                   labels={str(k): str(v) for k, v in labels.items()},
                   window_s=window_s, quantile=quantile, op=op, for_s=for_s,
                   resolve_ratio=resolve_ratio, severity=severity)

    def series_key(self) -> str:
        if not self.labels:
            return self.metric
        pairs = ",".join(f"{k}={v}" for k, v in sorted(self.labels.items()))
        return f"{self.metric}{{{pairs}}}"

    def breaches(self, value: float) -> bool:
        if self.op == ">":
            return value > self.threshold
        if self.op == ">=":
            return value >= self.threshold
        if self.op == "<":
            return value < self.threshold
        return value <= self.threshold

    def resolved(self, value: float) -> bool:
        """Hysteresis bound, margin-form so it stays monotone for zero
        and negative thresholds (a multiplicative bound would invert
        there and flap the alert every poll): the margin is
        ``(1 - resolve_ratio) * |threshold|``; a ">"-family alert
        resolves only below ``threshold - margin``, a "<"-family one
        only above ``threshold + margin``. For positive thresholds the
        ">" bound is exactly the familiar ``threshold * resolve_ratio``;
        ratio 1 (or threshold 0) = plain de-breach."""
        margin = abs(self.threshold) * (1.0 - self.resolve_ratio)
        if self.op in (">", ">="):
            return value < self.threshold - margin
        return value > self.threshold + margin

    def describe_expr(self) -> str:
        head = {"value": self.series_key(),
                "rate": f"rate({self.series_key()}[{self.window_s:g}s])",
                "quantile": f"q{self.quantile:g}({self.metric})"}[self.kind]
        return f"{head} {self.op} {self.threshold:g}"


def validate_rules(specs: List[Dict[str, Any]]) -> List[AlertRule]:
    """Parse + validate a config's rule list (duplicate names rejected —
    two rules sharing an identity would fight over one state machine)."""
    rules: List[AlertRule] = []
    seen = set()
    for spec in specs or []:
        rule = AlertRule.from_spec(spec)
        if rule.name in seen:
            raise ValueError(f"duplicate alert rule name {rule.name!r}")
        seen.add(rule.name)
        rules.append(rule)
    return rules


class _RuleState:
    __slots__ = ("status", "since", "fired_at", "value")

    def __init__(self):
        self.status = "ok"          # ok | pending | firing
        self.since = 0.0            # breach start (pending/firing)
        self.fired_at = 0.0
        self.value = 0.0


class AlertEngine:
    """Evaluates a rule set against a metrics registry on a bounded
    cadence; owns the time-series ring the rules (and the status CLI's
    sparklines) read from. Thread-safe; ``poll()`` is also callable
    synchronously (round close, tests — pass ``now`` for a fake clock)."""

    def __init__(self, rules: List[AlertRule],
                 registry: Optional[_metrics.Registry] = None,
                 interval_s: float = 1.0,
                 ring: Optional[TimeSeriesRing] = None):
        self.rules = list(rules)
        self.registry = registry or _metrics.registry()
        self.interval_s = max(0.05, float(interval_s))
        self.ring = ring or TimeSeriesRing()
        self._states: Dict[str, _RuleState] = {
            rule.name: _RuleState() for rule in self.rules}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.fired_total = 0
        self.resolved_total = 0
        # rules whose sampling raised (e.g. a rule mistargeting a
        # histogram family) — logged once per rule, not per poll
        self._broken_rules: set = set()
        self._m_active = self.registry.gauge(
            ALERTS_ACTIVE,
            "Alert rules currently firing (1 while firing; series "
            "removed on resolve)", ("alert",))
        self._m_fired = self.registry.counter(
            ALERTS_FIRED_TOTAL, "Alert firings by rule", ("alert",))

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        """Arm the evaluation daemon (idempotent)."""
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="alert-engine", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.poll()
            except Exception:  # noqa: BLE001 - alerting never takes a
                logger.exception("alert poll failed")  # controller down

    def shutdown(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5.0)
        # bounded cardinality: a dead engine's gauge series must not
        # shadow a later controller's in the process-global registry
        with self._lock:
            for rule in self.rules:
                self._m_active.remove(alert=rule.name)

    # -- evaluation ------------------------------------------------------

    def _sample(self, rule: AlertRule, now: float) -> float:
        family = self.registry.get(rule.metric)
        if family is None:
            return 0.0  # rule declared before the family minted
        if rule.kind == "quantile":
            quantile = getattr(family, "quantile", None)
            return float(quantile(rule.quantile)) if quantile else 0.0
        if rule.labels:
            try:
                raw = float(family.value(**rule.labels))
            except (ValueError, AttributeError):
                return 0.0  # label-set mismatch: inert, never fatal
        else:
            total = getattr(family, "total", None)
            raw = float(total()) if total else 0.0
        if rule.kind == "value":
            return raw
        key = rule.series_key()
        self.ring.record(key, raw, ts=now)
        return self.ring.rate(key, rule.window_s, now=now)

    def poll(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One evaluation pass; returns the transitions it caused
        (``[{"alert", "transition", "value"}, ...]``)."""
        now = time.time() if now is None else float(now)
        for name in DEFAULT_SERIES:
            family = self.registry.get(name)
            if family is not None and hasattr(family, "total"):
                self.ring.record(name, float(family.total()), ts=now)
        transitions: List[Dict[str, Any]] = []
        for rule in self.rules:
            try:
                value = self._sample(rule, now)
                self.ring.record(f"alert/{rule.name}", value, ts=now)
                with self._lock:
                    transition = self._step(rule, value, now)
            except Exception:  # noqa: BLE001 - one broken rule must not
                # stop the OTHER rules from being evaluated (a rule can
                # mistarget a family whose read path raises)
                if rule.name not in self._broken_rules:
                    self._broken_rules.add(rule.name)
                    logger.exception(
                        "alert rule %s failed to evaluate; skipping it "
                        "(other rules keep evaluating)", rule.name)
                continue
            if transition:
                transitions.append(
                    {"alert": rule.name, "transition": transition,
                     "value": value})
        return transitions

    def _step(self, rule: AlertRule, value: float,
              now: float) -> Optional[str]:
        """Advance one rule's state machine; called under _lock. Event
        emission happens here too — emits are lock-cheap appends."""
        state = self._states[rule.name]
        state.value = value
        if state.status == "firing":
            if rule.resolved(value):
                state.status = "ok"
                active_s = now - state.fired_at
                self.resolved_total += 1
                self._m_active.remove(alert=rule.name)
                _events.emit(_events.AlertResolved, name=rule.name,
                             value=round(value, 6),
                             active_s=round(active_s, 3))
                logger.info("alert %s RESOLVED (value %.6g after %.1fs)",
                            rule.name, value, active_s)
                return "resolved"
            return None
        breach = rule.breaches(value)
        if not breach:
            state.status = "ok"
            return None
        if state.status == "ok":
            state.status = "pending"
            state.since = now
        if now - state.since >= rule.for_s:
            state.status = "firing"
            state.fired_at = now
            self.fired_total += 1
            self._m_active.set(1, alert=rule.name)
            self._m_fired.inc(alert=rule.name)
            _events.emit(_events.AlertFiring, name=rule.name,
                         expr=rule.describe_expr(),
                         value=round(value, 6), threshold=rule.threshold,
                         severity=rule.severity)
            logger.warning("alert %s FIRING: %s (value %.6g)",
                           rule.name, rule.describe_expr(), value)
            return "firing"
        return None

    # -- read side -------------------------------------------------------

    def active(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        now = time.time() if now is None else float(now)
        with self._lock:
            return [
                {"name": rule.name, "severity": rule.severity,
                 "expr": rule.describe_expr(),
                 "value": round(self._states[rule.name].value, 6),
                 "threshold": rule.threshold,
                 "active_s": round(
                     max(0.0, now - self._states[rule.name].fired_at), 3)}
                for rule in self.rules
                if self._states[rule.name].status == "firing"]

    def summary(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The ``alerts`` section of a DescribeFederation snapshot."""
        active = self.active(now=now)
        with self._lock:
            pending = sum(1 for s in self._states.values()
                          if s.status == "pending")
        return {"enabled": True, "rules": len(self.rules),
                "active": active, "pending": pending,
                "fired_total": self.fired_total,
                "resolved_total": self.resolved_total}

    def series_snapshot(self, points: int = 30) -> Dict[str, Any]:
        return self.ring.snapshot(points=points)


# --------------------------------------------------------------------- #
# process-global handle (the flight recorder's "alerts at death")
# --------------------------------------------------------------------- #

_ENGINE: Optional[AlertEngine] = None


def set_engine(engine: Optional[AlertEngine]) -> None:
    global _ENGINE
    _ENGINE = engine


def engine() -> Optional[AlertEngine]:
    return _ENGINE


def active_summary() -> Optional[Dict[str, Any]]:
    """The live engine's summary, or None when no engine is armed —
    what post-mortem bundles record as the alerts at death."""
    if _ENGINE is None:
        return None
    try:
        return _ENGINE.summary()
    except Exception:  # noqa: BLE001 - flight-recorder path never raises
        return None
