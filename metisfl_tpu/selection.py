"""Model selection: which stored learner models join an aggregation.

Equivalent of the reference's ``Selector`` / ``ScheduledCardinality``
(reference metisfl/controller/selection/scheduled_cardinality.h:14-33): with
fewer than two scheduled learners the aggregation uses ALL active learners'
latest models (so an async single-learner completion still averages against
the rest of the federation); otherwise exactly the scheduled set.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence


class ScheduledCardinalitySelector:
    name = "scheduled_cardinality"

    def __init__(self):
        # latest advisory divergence scores the controller handed over
        # (telemetry.health.advisory) — recorded for operators/tests;
        # this selector's choice is deliberately unaffected by them
        self.last_advisory_scores: Optional[Dict[str, float]] = None

    def select(self, scheduled: Sequence[str], active: Sequence[str],
               advisory_scores: Optional[Dict[str, float]] = None,
               ) -> List[str]:
        if advisory_scores is not None:
            self.last_advisory_scores = dict(advisory_scores)
        if len(scheduled) < 2:
            return list(active)
        return [lid for lid in scheduled if lid in set(active)]


SELECTORS = {"scheduled_cardinality": ScheduledCardinalitySelector}


def make_selector(name: str):
    try:
        return SELECTORS[name.lower()]()
    except KeyError:
        raise ValueError(f"unknown selector {name!r}; have {sorted(SELECTORS)}") from None
