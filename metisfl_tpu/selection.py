"""Model selection and churn-aware admission: which stored learner models
join an aggregation, and which learners are healthy enough to dispatch to.

Equivalent of the reference's ``Selector`` / ``ScheduledCardinality``
(reference metisfl/controller/selection/scheduled_cardinality.h:14-33): with
fewer than two scheduled learners the aggregation uses ALL active learners'
latest models (so an async single-learner completion still averages against
the rest of the federation); otherwise exactly the scheduled set.

:class:`ChurnTracker` adds the cross-device admission signal the silo
regime never needed: per-learner churn/flap scores (EWMA of leave,
flap-rejoin, and failed-dispatch events — the membership counterpart of
the straggler and divergence scores) with optional temporary quarantine
of flapping learners, which cohort sampling consults (Oort-style guided
selection, OSDI 2021: prefer clients that actually deliver).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence


class ScheduledCardinalitySelector:
    name = "scheduled_cardinality"

    def __init__(self):
        # latest advisory divergence scores the controller handed over
        # (telemetry.health.advisory) — recorded for operators/tests;
        # this selector's choice is deliberately unaffected by them
        self.last_advisory_scores: Optional[Dict[str, float]] = None

    def select(self, scheduled: Sequence[str], active: Sequence[str],
               advisory_scores: Optional[Dict[str, float]] = None,
               ) -> List[str]:
        if advisory_scores is not None:
            self.last_advisory_scores = dict(advisory_scores)
        if len(scheduled) < 2:
            return list(active)
        return [lid for lid in scheduled if lid in set(active)]


class ChurnTracker:
    """Per-learner churn/flap scores with optional quarantine.

    Score semantics mirror the divergence score's EWMA posture: each
    churn event (``leave``, ``flap_rejoin``, ``dispatch_failure``) blends
    a 1.0 observation in (``score = alpha + (1-alpha)*score``), each
    successful completion blends a 0.0 in, so a learner that leaves and
    rejoins every few rounds saturates toward 1.0 while one that delivers
    steadily decays toward 0.0 within a few rounds.

    Quarantine (``quarantine_score > 0`` arms it): a churn event that
    lifts a learner's score past the threshold excludes it from cohort
    sampling for ``quarantine_s`` seconds — a flapping endpoint stops
    consuming over-provisioned dispatch slots that a stable replacement
    could use. The tracker deliberately SURVIVES leave (a flapper's
    history is the whole signal); state is bounded by ``max_entries``
    with oldest-touched eviction, so 100k-client churn cannot grow it
    without bound. Thread-safe: the controller notes events from RPC
    threads and samples cohorts from the scheduling executor.
    """

    def __init__(self, alpha: float = 0.3, quarantine_score: float = 0.0,
                 quarantine_s: float = 30.0, max_entries: int = 8192):
        self.alpha = float(alpha)
        self.quarantine_score = float(quarantine_score)
        self.quarantine_s = float(quarantine_s)
        self.max_entries = max(16, int(max_entries))
        self._lock = threading.Lock()
        # learner_id -> score, insertion/touch-ordered for bounded eviction
        self._scores: Dict[str, float] = {}
        self._quarantined_until: Dict[str, float] = {}

    # events worth a full 1.0 observation
    CHURN_EVENTS = ("leave", "flap_rejoin", "dispatch_failure")

    def note(self, learner_id: str, event: str,
             now: Optional[float] = None) -> float:
        """Fold one membership event into the learner's score; returns
        the updated score. ``event='completion'`` is the decay tick.
        Returns the score AFTER the blend; quarantine arms when a churn
        event pushes it past the threshold."""
        observation = 1.0 if event in self.CHURN_EVENTS else 0.0
        now = time.time() if now is None else now
        with self._lock:
            prev = self._scores.pop(learner_id, 0.0)  # pop+set: touch order
            score = self.alpha * observation + (1.0 - self.alpha) * prev
            self._scores[learner_id] = score
            while len(self._scores) > self.max_entries:
                evicted, _ = next(iter(self._scores.items()))
                del self._scores[evicted]
                self._quarantined_until.pop(evicted, None)
            if (observation > 0.0 and self.quarantine_score > 0.0
                    and score >= self.quarantine_score):
                self._quarantined_until[learner_id] = now + self.quarantine_s
            return score

    def score(self, learner_id: str) -> float:
        with self._lock:
            return self._scores.get(learner_id, 0.0)

    def scores(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._scores)

    def quarantined(self, learner_id: str,
                    now: Optional[float] = None) -> bool:
        now = time.time() if now is None else now
        with self._lock:
            until = self._quarantined_until.get(learner_id, 0.0)
            if until and until <= now:
                del self._quarantined_until[learner_id]  # expired
                return False
            return until > now

    def quarantined_ids(self, now: Optional[float] = None) -> List[str]:
        now = time.time() if now is None else now
        with self._lock:
            expired = [lid for lid, until in self._quarantined_until.items()
                       if until <= now]
            for lid in expired:
                del self._quarantined_until[lid]
            return sorted(self._quarantined_until)


SELECTORS = {"scheduled_cardinality": ScheduledCardinalitySelector}


def make_selector(name: str):
    try:
        return SELECTORS[name.lower()]()
    except KeyError:
        raise ValueError(f"unknown selector {name!r}; have {sorted(SELECTORS)}") from None
