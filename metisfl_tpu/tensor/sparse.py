"""Top-k sparsified federation uplink with error feedback.

``TrainParams.ship_dtype="topk<D>"`` (e.g. ``"topk16"``) ships each float
tensor of the learner's **update** (trained weights minus the round's
dispatched community model) as its ``size/D`` largest-magnitude entries —
value + flat index — instead of the dense tensor: ~``D/2``× less uplink
than f32 (8× at D=16; value f32 + index int32 per kept entry). What the
sparsifier drops is not lost: the learner keeps the dropped remainder as a
per-tensor **error-feedback residual** and adds it to the next round's
update before re-sparsifying (Deep-Gradient-Compression-style memory), so
small-but-persistent coordinates still reach the controller, just later.

The reference ships every model as a raw dense blob (no wire compression
at all — its ~100 MB FHE models forced the stub-per-request workaround,
/root/reference/metisfl/controller/core/controller.cc:594-604); this and
``int8q`` (tensor/quantize.py) are the rebuild's uplink ladder:
f32 → bf16 (2×) → int8q (4×) → topk16 (8×) → topk64 (32×).

Wire shape: like int8q, the sparse payload rides the ordinary named-tensor
blob — each sparsified tensor ``name`` becomes THREE companion entries
``name#tkidx`` (flat indices), ``name#tkval`` (f32 values), and
``name#tkshape`` (dense shape) — so codecs, stores, and transports are
untouched. The controller reconstructs dense weights at parse time
(``densify_named``: community + scatter(update)) and everything downstream
(lineage stores, FedAvg/rolling/robust rules, server optimizers) runs on
exact dense f32. Because the reconstruction reference must be the SAME
community model the learner trained from, topk shipping is valid only for
synchronous/semi-synchronous protocols (config-validated): under async the
community model advances between dispatch and completion.

Integer/bool tensors and tiny floats (size < MIN_SPARSE_SIZE, where
index+shape overhead beats the savings) pass through dense, mirroring
``ship_dtype``'s float-only rule.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

import numpy as np

IDX_SUFFIX = "#tkidx"
VAL_SUFFIX = "#tkval"
SHAPE_SUFFIX = "#tkshape"
_SUFFIXES = (IDX_SUFFIX, VAL_SUFFIX, SHAPE_SUFFIX)

SHIP_TOPK_PREFIX = "topk"
_TOPK_RE = re.compile(r"^topk(\d*)$")
DEFAULT_DENOM = 16
# below this many elements the idx+val+shape companions cost more wire
# than the dense tensor they replace
MIN_SPARSE_SIZE = 64


def parse_topk(ship_dtype: str) -> Optional[int]:
    """``"topk<D>"`` → D (bare ``"topk"`` → DEFAULT_DENOM); None when the
    string is not a topk spec. Raises on a malformed denominator."""
    m = _TOPK_RE.match(str(ship_dtype).strip().lower())
    if m is None:
        return None
    denom = int(m.group(1)) if m.group(1) else DEFAULT_DENOM
    if not 1 <= denom <= 100_000:
        raise ValueError(
            f"ship_dtype {ship_dtype!r}: denominator must be in "
            f"[1, 100000], got {denom}")
    return denom


def sparsify_update(
    new_named: List[Tuple[str, np.ndarray]],
    ref: Dict[str, np.ndarray],
    denom: int,
    residual: Dict[str, np.ndarray],
) -> List[Tuple[str, np.ndarray]]:
    """[(name, trained)] + {name: dispatched} → sparse wire entries.

    For each float tensor: ``u = (trained - dispatched) + residual``; the
    top ``ceil(size/denom)`` entries of ``|u|`` ship as (idx, val, shape);
    the rest becomes the new residual (mutated in place in ``residual``).
    Tensors absent from ``ref`` (shape/name drift after a model swap) and
    non-float/tiny tensors ship dense, and their residual resets; residuals
    for names no longer in the model are pruned (they could never ship
    again and would otherwise leak dense f32 copies for the learner's
    lifetime).
    """
    current = {name for name, _ in new_named}
    for gone in [k for k in residual if k not in current]:
        residual.pop(gone)
    out: List[Tuple[str, np.ndarray]] = []
    for name, arr in new_named:
        arr = np.asarray(arr)
        if any(name.endswith(s) for s in _SUFFIXES):
            raise ValueError(f"tensor name {name!r} collides with a "
                             "topk companion suffix")
        ref_arr = ref.get(name)
        if (not np.issubdtype(arr.dtype, np.floating)
                or arr.size < MIN_SPARSE_SIZE
                or ref_arr is None
                or np.asarray(ref_arr).shape != arr.shape):
            residual.pop(name, None)
            out.append((name, arr))
            continue
        u = (np.asarray(arr, np.float32)
             - np.asarray(ref_arr, np.float32)).ravel()
        res = residual.get(name)
        if res is not None and res.shape == u.shape:
            u = u + res
        k = max(1, -(-arr.size // denom))  # ceil
        # argpartition: O(n) selection of the k largest |u|
        idx = np.argpartition(np.abs(u), arr.size - k)[arr.size - k:]
        idx = np.sort(idx)
        vals = u[idx]
        new_res = u.copy()
        new_res[idx] = 0.0
        residual[name] = new_res
        idx_dtype = np.int32 if arr.size <= np.iinfo(np.int32).max \
            else np.int64
        out.append((name + IDX_SUFFIX, idx.astype(idx_dtype)))
        out.append((name + VAL_SUFFIX, vals.astype(np.float32)))
        out.append((name + SHAPE_SUFFIX,
                    np.asarray(arr.shape, np.int64)))
    return out


def is_sparse(names) -> bool:
    return any(str(n).endswith(VAL_SUFFIX) for n in names)


def densify_named(
    tensors: Dict[str, np.ndarray],
    community: Dict[str, np.ndarray],
) -> Dict[str, np.ndarray]:
    """{wire name: arr} + {name: community tensor} → dense f32 weights:
    ``community + scatter(update)`` per sparsified tensor; companion
    entries consumed; dense passthrough entries kept as-is."""
    out: Dict[str, np.ndarray] = {}
    for name, arr in tensors.items():
        if any(name.endswith(s) for s in _SUFFIXES):
            continue
        out[name] = arr
    for name, vals in tensors.items():
        if not name.endswith(VAL_SUFFIX):
            continue
        base = name[: -len(VAL_SUFFIX)]
        idx = tensors.get(base + IDX_SUFFIX)
        shape = tensors.get(base + SHAPE_SUFFIX)
        if idx is None or shape is None:
            raise ValueError(f"sparse tensor {base!r}: missing "
                             "companion idx/shape entries")
        ref = community.get(base)
        shape = tuple(int(d) for d in np.asarray(shape).ravel())
        if ref is None or tuple(np.asarray(ref).shape) != shape:
            raise ValueError(
                f"sparse tensor {base!r}: no community tensor of shape "
                f"{shape} to densify against (topk shipping requires the "
                "controller to hold the dispatched community model)")
        dense = np.asarray(ref, np.float32).ravel().copy()
        flat_idx = np.asarray(idx).ravel()
        if flat_idx.size and (flat_idx.min() < 0
                              or flat_idx.max() >= dense.size):
            raise ValueError(f"sparse tensor {base!r}: index out of range")
        if np.unique(flat_idx).size != flat_idx.size:
            # a well-formed sparsify_update payload has unique indices;
            # duplicates would silently drop contributions under numpy's
            # unbuffered fancy-index add
            raise ValueError(f"sparse tensor {base!r}: duplicate indices")
        dense[flat_idx] += np.asarray(vals, np.float32).ravel()
        out[base] = dense.reshape(shape)
    return out
