"""Tensor wire format.

A tensor travels as ``(TensorSpec, bytes)``: a small header describing dtype,
shape and kind, plus the flattened little-endian row-major payload. This is
the capability equivalent of the reference's ``TensorSpec`` proto
(reference metisfl/proto/model.proto:14-60) and its C++/numpy serde
(proto_tensor_serde.h:13-32, proto_messages_factory.py:419-507), with two
deliberate TPU-first changes:

- ``bfloat16`` is a first-class dtype (the reference had no TPU dtypes).
- payloads are always little-endian C-order; Fortran-order inputs are
  normalized at the boundary instead of carrying a layout flag through the
  whole stack.

Ciphertext / masked tensors reuse the same container with an opaque payload
(``TensorKind.CIPHERTEXT`` / ``MASKED``), mirroring the reference's
``CiphertextTensor`` wrapping (model.proto:69-72).
"""

from __future__ import annotations

import enum
import struct
from dataclasses import dataclass
from typing import Tuple

import numpy as np

try:  # ml_dtypes ships with jax; bfloat16 numpy dtype lives there.
    import ml_dtypes

    _BFLOAT16 = np.dtype(ml_dtypes.bfloat16)
    _FLOAT8_E4M3 = np.dtype(ml_dtypes.float8_e4m3fn)
    _FLOAT8_E5M2 = np.dtype(ml_dtypes.float8_e5m2)
except ImportError:  # pragma: no cover - ml_dtypes is a jax dependency
    _BFLOAT16 = None
    _FLOAT8_E4M3 = None
    _FLOAT8_E5M2 = None


class DType(enum.IntEnum):
    """Wire dtype tags. Values are stable — they are part of the wire format."""

    F32 = 1
    F64 = 2
    F16 = 3
    BF16 = 4
    I8 = 5
    I16 = 6
    I32 = 7
    I64 = 8
    U8 = 9
    U16 = 10
    U32 = 11
    U64 = 12
    BOOL = 13
    F8_E4M3 = 14
    F8_E5M2 = 15


class TensorKind(enum.IntEnum):
    """What the payload holds."""

    PLAINTEXT = 0
    CIPHERTEXT = 1  # opaque HE ciphertext bytes; dtype/shape describe plaintext
    MASKED = 2      # additively masked plaintext (secure aggregation)


_DTYPE_TO_NP = {
    DType.F32: np.dtype(np.float32),
    DType.F64: np.dtype(np.float64),
    DType.F16: np.dtype(np.float16),
    DType.I8: np.dtype(np.int8),
    DType.I16: np.dtype(np.int16),
    DType.I32: np.dtype(np.int32),
    DType.I64: np.dtype(np.int64),
    DType.U8: np.dtype(np.uint8),
    DType.U16: np.dtype(np.uint16),
    DType.U32: np.dtype(np.uint32),
    DType.U64: np.dtype(np.uint64),
    DType.BOOL: np.dtype(np.bool_),
}
if _BFLOAT16 is not None:
    _DTYPE_TO_NP[DType.BF16] = _BFLOAT16
    _DTYPE_TO_NP[DType.F8_E4M3] = _FLOAT8_E4M3
    _DTYPE_TO_NP[DType.F8_E5M2] = _FLOAT8_E5M2

_NP_TO_DTYPE = {v: k for k, v in _DTYPE_TO_NP.items()}
_NATIVE_LITTLE = struct.pack("=H", 1) == b"\x01\x00"
# The wire format and the serde below assume a little-endian host (true for
# every TPU host platform: x86-64 and aarch64). Fail loudly otherwise.
assert _NATIVE_LITTLE, "metisfl_tpu requires a little-endian host"


def np_dtype_of(dtype: DType) -> np.dtype:
    try:
        return _DTYPE_TO_NP[dtype]
    except KeyError:
        raise ValueError(f"unsupported wire dtype {dtype!r}") from None


def wire_dtype_of(dtype) -> DType:
    dtype = np.dtype(dtype)
    try:
        return _NP_TO_DTYPE[dtype]
    except KeyError:
        raise ValueError(f"numpy dtype {dtype} has no wire representation") from None


@dataclass(frozen=True)
class TensorSpec:
    """Header for one tensor on the wire."""

    shape: Tuple[int, ...]
    dtype: DType
    kind: TensorKind = TensorKind.PLAINTEXT

    @property
    def size(self) -> int:
        n = 1
        for d in self.shape:
            n *= d
        return n

    @property
    def nbytes(self) -> int:
        return self.size * np_dtype_of(self.dtype).itemsize


# Header layout (little-endian):
#   u8 version | u8 dtype | u8 kind | u8 ndim | u32 dims[ndim] | u64 payload_len
_HEADER_VERSION = 1


def tensor_to_bytes(array: np.ndarray, kind: TensorKind = TensorKind.PLAINTEXT,
                    payload: bytes | None = None) -> bytes:
    """Serialize an array (or an opaque payload with array-shaped metadata)."""
    array = np.asarray(array)
    # Normalize byte order at the boundary: the wire is always little-endian.
    # (Little-endian hosts only — asserted at import; '<x' dtypes hash equal
    # to native ones there, so only explicit big-endian inputs need a swap.)
    if array.dtype.byteorder == ">":
        array = array.astype(array.dtype.newbyteorder("="))
    dtype = wire_dtype_of(array.dtype)
    if payload is None:
        payload = np.ascontiguousarray(array).tobytes()
    return _header_bytes(TensorSpec(array.shape, dtype, kind), len(payload)) + payload


def opaque_tensor_to_bytes(spec: TensorSpec, payload: bytes) -> bytes:
    """Serialize an opaque (ciphertext/masked) payload under plaintext metadata."""
    return _header_bytes(spec, len(payload)) + payload


def _header_bytes(spec: TensorSpec, payload_len: int) -> bytes:
    return struct.pack(
        f"<BBBB{len(spec.shape)}IQ",
        _HEADER_VERSION,
        int(spec.dtype),
        int(spec.kind),
        len(spec.shape),
        *spec.shape,
        payload_len,
    )


def tensor_from_bytes(buf, offset: int = 0, copy: bool = True):
    """Deserialize one tensor; returns ``(array_or_payload, spec, next_offset)``.

    For PLAINTEXT tensors returns a numpy array — a writable copy by default;
    pass ``copy=False`` for a zero-copy **read-only** view that aliases (and
    keeps alive) ``buf``. For CIPHERTEXT / MASKED returns the raw payload
    bytes (the caller owns decryption).
    """
    view = memoryview(buf)
    try:
        version, dtype_tag, kind_tag, ndim = struct.unpack_from("<BBBB", view, offset)
        if version != _HEADER_VERSION:
            raise ValueError(f"unsupported tensor wire version {version}")
        offset += 4
        shape = struct.unpack_from(f"<{ndim}I", view, offset)
        offset += 4 * ndim
        (payload_len,) = struct.unpack_from("<Q", view, offset)
        offset += 8
    except struct.error as exc:
        raise ValueError(f"truncated tensor header: {exc}") from None
    if offset + payload_len > len(view):
        raise ValueError(
            f"truncated tensor payload (need {offset + payload_len} bytes, "
            f"have {len(view)})"
        )
    payload = view[offset : offset + payload_len]
    offset += payload_len
    spec = TensorSpec(tuple(shape), DType(dtype_tag), TensorKind(kind_tag))
    if spec.kind is TensorKind.PLAINTEXT:
        arr = np.frombuffer(payload, dtype=np_dtype_of(spec.dtype)).reshape(spec.shape)
        if copy:
            arr = arr.copy()
        return arr, spec, offset
    return bytes(payload), spec, offset


def quantify(array: np.ndarray) -> dict:
    """Zero/non-zero/byte counts for round metadata.

    Capability parity with the reference's ``QuantifyTensor``
    (proto_tensor_serde.h:34-50) used for community-model size records.
    """
    array = np.asarray(array)
    nonzero = int(np.count_nonzero(array))
    return {
        "values": int(array.size),
        "non_zeros": nonzero,
        "zeros": int(array.size) - nonzero,
        "bytes": int(array.nbytes),
    }


def resolve_ship_dtype(name: str) -> np.dtype:
    """A DType name ("bf16", "f16", ...) → numpy dtype, with a clear
    error listing the valid names (used by TrainParams.ship_dtype; the
    quantized "int8q" mode is handled by callers before this resolver,
    but belongs in the guidance a typo gets back)."""
    try:
        return np_dtype_of(DType[name.upper()])
    except KeyError:
        raise ValueError(
            f"unknown ship_dtype {name!r}; valid names: "
            f"{[d.name.lower() for d in DType] + ['int8q']}") from None


def narrow_named(named, target: np.dtype):
    """[(name, arr)] with float tensors cast to ``target``; integer/bool
    state (step counters, token ids) passes through — casting it through a
    float mantissa would corrupt it. Shared by the uplink (ship_dtype) and
    downlink (downlink_dtype) wire-narrowing paths."""
    return [(n, np.asarray(a, target)
             if np.issubdtype(np.asarray(a).dtype, np.floating)
             and np.asarray(a).dtype != target else a)
            for n, a in named]
