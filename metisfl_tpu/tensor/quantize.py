"""int8 absmax quantization for the federation uplink.

``TrainParams.ship_dtype="int8q"`` ships each float tensor as int8 plus a
per-tensor fp32 scale (absmax/127) — 4× less uplink bandwidth than f32
(2× less than ``bf16`` shipping) at ~0.4% of per-tensor max quantization
error. The reference has no wire compression at all (its CIFAR models
travel as raw f64-widened blobs that forced the stub-per-request hack,
controller.cc:594-604).

Wire shape: the quantized payload stays inside the ordinary named-tensor
blob — each quantized tensor ``name`` is followed by a companion scalar
``name#qscale`` — so stores, codecs, and transports are untouched; the
controller dequantizes right after parsing (``dequantize_named``) and
aggregation runs on exact f32. Integer/bool tensors (step counters,
embeddings' token ids) pass through unquantized, like ``ship_dtype``'s
float-only rule.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

QSCALE_SUFFIX = "#qscale"
SHIP_INT8Q = "int8q"


def quantize_named(named: List[Tuple[str, np.ndarray]]):
    """[(name, arr)] → same list with float tensors replaced by
    (name, int8) + (name#qscale, f32 scalar)."""
    out: List[Tuple[str, np.ndarray]] = []
    for name, arr in named:
        arr = np.asarray(arr)
        if name.endswith(QSCALE_SUFFIX):
            raise ValueError(f"tensor name {name!r} collides with the "
                             "quantization-scale suffix")
        if not np.issubdtype(arr.dtype, np.floating):
            out.append((name, arr))
            continue
        absmax = float(np.max(np.abs(arr))) if arr.size else 0.0
        scale = absmax / 127.0 if absmax > 0 else 1.0
        q = np.clip(np.round(np.asarray(arr, np.float32) / scale),
                    -127, 127).astype(np.int8)
        out.append((name, q))
        out.append((name + QSCALE_SUFFIX,
                    np.asarray([scale], np.float32)))
    return out


def is_quantized(names) -> bool:
    return any(str(n).endswith(QSCALE_SUFFIX) for n in names)


def dequantize_named(tensors: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """{name: arr} (as parsed from a blob) → floats restored to f32;
    companion scale entries consumed. Non-quantized dicts pass through."""
    if not is_quantized(tensors):
        return tensors
    out: Dict[str, np.ndarray] = {}
    for name, arr in tensors.items():
        if name.endswith(QSCALE_SUFFIX):
            continue
        scale_key = name + QSCALE_SUFFIX
        if scale_key in tensors:
            scale = float(np.asarray(tensors[scale_key]).ravel()[0])
            out[name] = (np.asarray(arr, np.float32) * scale)
        else:
            out[name] = arr
    return out
