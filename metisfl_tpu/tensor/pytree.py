"""Pytree ⇄ named-tensor model blobs.

The federation wire contract moves *models* — ordered, named, flat tensors —
while the JAX learner works on *pytrees* (Flax param dicts). This module is
the bridge. It replaces the reference's ``Model``/``Model.Variable`` proto
(reference metisfl/proto/model.proto:100-152) and the get/set weight paths in
``ModelOps`` (metisfl/models/model_ops.py:24-110): names are derived from the
pytree key path, so a blob round-trips through any transport back into the
exact same tree structure.
"""

from __future__ import annotations

import os
import struct
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Sequence, Tuple

import numpy as np
import jax

from metisfl_tpu import telemetry as _tel
from metisfl_tpu.telemetry import metrics as _tmetrics
from metisfl_tpu.tensor.spec import (
    TensorKind,
    TensorSpec,
    opaque_tensor_to_bytes,
    tensor_from_bytes,
    tensor_to_bytes,
)

NamedTensors = List[Tuple[str, np.ndarray]]

_MAGIC = b"MTFB"  # metisfl-tpu federated blob
# v2 adds integrity framing: a <u64 body_len, u32 crc32> trailer-header
# over the tensor body, so a bit-flipped or truncated blob is rejected at
# the wire boundary instead of deserializing into garbage weights that
# would silently poison an aggregation. v1 blobs (pre-integrity
# checkpoints) still parse — unverified.
_BLOB_VERSION = 2
# v3: length-framed, crc field written as zero and never verified —
# store-local files only (write_named_tensors(checksum=False)); the wire
# always ships v2
_BLOB_VERSION_NOCRC = 3

# Payloads rejected by the integrity framing (length or checksum). The
# RPC layer surfaces the ValueError as INVALID_ARGUMENT; the controller's
# malformed-result path drops the contribution without stalling the round.
_M_CORRUPT = _tmetrics.registry().counter(
    _tel.M_CORRUPT_PAYLOADS_TOTAL,
    "Model blobs rejected by length/checksum integrity framing")


def _escape(part: str) -> str:
    # '/' joins path components; escape literal '/' (and the escape char) so
    # {'a': {'b': x}} and {'a/b': y} can never collide.
    return part.replace("%", "%25").replace("/", "%2F")


def _key_to_name(path) -> str:
    parts = []
    for entry in path:
        if isinstance(entry, jax.tree_util.DictKey):
            parts.append(_escape(str(entry.key)))
        elif isinstance(entry, jax.tree_util.SequenceKey):
            parts.append(str(entry.idx))
        elif isinstance(entry, jax.tree_util.GetAttrKey):
            parts.append(_escape(str(entry.name)))
        elif isinstance(entry, jax.tree_util.FlattenedIndexKey):
            parts.append(str(entry.key))
        else:  # pragma: no cover - future key types
            parts.append(_escape(str(entry)))
    return "/".join(parts)


def _check_unique(names) -> None:
    if len(set(names)) != len(names):
        seen, dupes = set(), set()
        for n in names:
            (dupes if n in seen else seen).add(n)
        raise ValueError(f"duplicate tensor names in model: {sorted(dupes)[:5]}")


def pytree_to_named_tensors(tree) -> NamedTensors:
    """Flatten a pytree of arrays to ``[(name, np.ndarray), ...]`` (ordered)."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    named = [(_key_to_name(path), np.asarray(leaf)) for path, leaf in flat]
    _check_unique([n for n, _ in named])
    return named


def named_tensors_to_pytree(named: NamedTensors, treedef_like):
    """Rebuild a pytree structured like ``treedef_like`` from named tensors."""
    flat = jax.tree_util.tree_flatten_with_path(treedef_like)
    paths = [_key_to_name(p) for p, _ in flat[0]]
    _check_unique([n for n, _ in named])
    by_name = dict(named)
    missing = [p for p in paths if p not in by_name]
    if missing:
        raise KeyError(f"model blob is missing tensors: {missing[:5]}")
    leaves = [by_name[p] for p in paths]
    return jax.tree_util.tree_unflatten(flat[1], leaves)


@dataclass
class ModelBlob:
    """A serializable model: ordered named tensors plus opaque entries.

    ``tensors`` holds plaintext arrays; ``opaque`` holds ciphertext/masked
    payloads keyed by the same names (a blob is either all-plaintext or
    all-opaque in practice, but the container does not force it).
    """

    tensors: NamedTensors = field(default_factory=list)
    opaque: Dict[str, tuple] = field(default_factory=dict)  # name -> (payload, spec)

    @property
    def names(self) -> List[str]:
        seen = [n for n, _ in self.tensors]
        seen.extend(self.opaque.keys())
        return seen

    @property
    def num_parameters(self) -> int:
        return sum(int(a.size) for _, a in self.tensors) + sum(
            spec.size for _, spec in self.opaque.values()
        )

    def to_bytes(self) -> bytes:
        chunks = []
        for name, arr in self.tensors:
            nb = name.encode("utf-8")
            chunks.append(struct.pack("<H", len(nb)))
            chunks.append(nb)
            chunks.append(tensor_to_bytes(arr))
        for name, (payload, spec) in self.opaque.items():
            nb = name.encode("utf-8")
            chunks.append(struct.pack("<H", len(nb)))
            chunks.append(nb)
            chunks.append(opaque_tensor_to_bytes(spec, payload))
        body = b"".join(chunks)
        return b"".join([
            _MAGIC,
            struct.pack("<BI", _BLOB_VERSION, len(self.names)),
            struct.pack("<QI", len(body), zlib.crc32(body)),
            body,
        ])

    @classmethod
    def from_bytes(cls, buf, copy: bool = True,
                   allow_nocrc: bool = False) -> "ModelBlob":
        """``allow_nocrc=True`` accepts the v3 store-local variant; the
        default REJECTS it so a wire payload whose version byte got
        flipped (or a peer deliberately shipping v3) cannot sidestep the
        v2 integrity framing — only the disk store's own read path,
        whose files it wrote itself, opts in (docs/SCALE.md)."""
        view = memoryview(buf)
        if bytes(view[:4]) != _MAGIC:
            raise ValueError("not a metisfl-tpu model blob")
        version, count = struct.unpack_from("<BI", view, 4)
        offset = 9
        if version == 3 and not allow_nocrc:
            _M_CORRUPT.inc()
            raise ValueError(
                "unchecksummed v3 model blob rejected outside the store "
                "read path (wire payloads must carry the v2 crc framing)")
        if version in (2, 3):
            try:
                body_len, crc = struct.unpack_from("<QI", view, offset)
            except struct.error:
                _M_CORRUPT.inc()
                raise ValueError("truncated model blob header") from None
            offset += 12
            body = view[offset:]
            if len(body) != body_len:
                _M_CORRUPT.inc()
                raise ValueError(
                    f"model blob length mismatch (framed {body_len} body "
                    f"bytes, have {len(body)}) — truncated or spliced "
                    "payload")
            # v3 (store-local, write_named_tensors(checksum=False)) is
            # length-framed only: truncation still rejects, the model was
            # crc-verified at the wire before it ever reached the store
            if version == 2 and zlib.crc32(body) != crc:
                _M_CORRUPT.inc()
                raise ValueError(
                    "model blob checksum mismatch — corrupt payload "
                    "rejected before deserialization")
        elif version != 1:  # v1: legacy pre-integrity blobs parse unverified
            raise ValueError(f"unsupported blob version {version}")
        blob = cls()
        for _ in range(count):
            (nlen,) = struct.unpack_from("<H", view, offset)
            offset += 2
            name = bytes(view[offset : offset + nlen]).decode("utf-8")
            offset += nlen
            value, spec, offset = tensor_from_bytes(view, offset, copy=copy)
            if spec.kind is TensorKind.PLAINTEXT:
                blob.tensors.append((name, value))
            else:
                blob.opaque[name] = (value, spec)
        return blob


def write_named_tensors(fd: int, named: NamedTensors,
                        checksum: bool = True) -> int:
    """Stream a tensors-only blob to an open file descriptor with ZERO
    staging copies; with ``checksum=True`` the file bytes are identical
    to ``ModelBlob(tensors=named).to_bytes()``.

    ``to_bytes`` pays three full-model memcpys (per-tensor ``tobytes``,
    the body join, the framing join) before the file write — ~3x the
    model size in pure memory traffic, which is what capped disk-store
    ingest at ~21 models/s (VERDICT weak #5, BENCH_r05). Here each
    tensor contributes a read-only ``memoryview`` straight over its
    buffer: the crc folds incrementally across the views and ``writev``
    gathers them into the file, so the only model-sized copy left is the
    kernel's. Returns the number of bytes written.

    ``checksum=False`` writes the v3 length-framed variant: same layout,
    crc field zero and never verified. For STORE-LOCAL files only
    (docs/SCALE.md): the uplink was already crc-checked at the RPC
    decode, ``os.replace`` keeps half-written files from ever appearing
    under their final name, and the length frame still rejects
    truncation — re-hashing the model on every insert AND select was
    pure hot-path overhead (~half the write cost at bench model size).
    Wire blobs keep the v2 checksum."""
    chunks: List = []
    for name, arr in named:
        arr = np.asarray(arr)
        if arr.dtype.byteorder == ">":  # wire is little-endian (spec.py)
            arr = arr.astype(arr.dtype.newbyteorder("="))
        # header shape BEFORE ascontiguousarray: it promotes 0-d scalars
        # to 1-d, which would change the wire header vs tensor_to_bytes
        shape = arr.shape
        arr = np.ascontiguousarray(arr)
        nb = name.encode("utf-8")
        from metisfl_tpu.tensor.spec import _header_bytes, wire_dtype_of

        chunks.append(struct.pack("<H", len(nb)) + nb + _header_bytes(
            TensorSpec(shape, wire_dtype_of(arr.dtype),
                       TensorKind.PLAINTEXT), arr.nbytes))
        # flat byte view — keeps the (possibly temporary contiguous)
        # array alive through the write, no serialization copy
        chunks.append(arr.data.cast("B"))
    body_len = sum(len(c) for c in chunks)
    crc = 0
    if checksum:
        for c in chunks:
            crc = zlib.crc32(c, crc)
    header = b"".join([
        _MAGIC,
        struct.pack("<BI",
                    _BLOB_VERSION if checksum else _BLOB_VERSION_NOCRC,
                    len(named)),
        struct.pack("<QI", body_len, crc),
    ])
    total = len(header) + body_len
    buffers: List = [header] + chunks
    if hasattr(os, "writev"):
        while buffers:
            written = os.writev(fd, buffers[:64])
            while buffers and written >= len(buffers[0]):
                written -= len(buffers[0])
                buffers.pop(0)
            if written:
                buffers[0] = memoryview(buffers[0])[written:]
    else:  # pragma: no cover - non-POSIX fallback
        for buf in buffers:
            os.write(fd, buf)
    return total


def pack_model(params_tree) -> bytes:
    """One-call pytree → wire bytes."""
    return ModelBlob(tensors=pytree_to_named_tensors(params_tree)).to_bytes()


def unpack_model(buf, treedef_like):
    """One-call wire bytes → pytree shaped like ``treedef_like``."""
    blob = ModelBlob.from_bytes(buf)
    return named_tensors_to_pytree(blob.tensors, treedef_like)
