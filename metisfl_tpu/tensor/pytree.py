"""Pytree ⇄ named-tensor model blobs.

The federation wire contract moves *models* — ordered, named, flat tensors —
while the JAX learner works on *pytrees* (Flax param dicts). This module is
the bridge. It replaces the reference's ``Model``/``Model.Variable`` proto
(reference metisfl/proto/model.proto:100-152) and the get/set weight paths in
``ModelOps`` (metisfl/models/model_ops.py:24-110): names are derived from the
pytree key path, so a blob round-trips through any transport back into the
exact same tree structure.
"""

from __future__ import annotations

import struct
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Sequence, Tuple

import numpy as np
import jax

from metisfl_tpu import telemetry as _tel
from metisfl_tpu.telemetry import metrics as _tmetrics
from metisfl_tpu.tensor.spec import (
    TensorKind,
    TensorSpec,
    opaque_tensor_to_bytes,
    tensor_from_bytes,
    tensor_to_bytes,
)

NamedTensors = List[Tuple[str, np.ndarray]]

_MAGIC = b"MTFB"  # metisfl-tpu federated blob
# v2 adds integrity framing: a <u64 body_len, u32 crc32> trailer-header
# over the tensor body, so a bit-flipped or truncated blob is rejected at
# the wire boundary instead of deserializing into garbage weights that
# would silently poison an aggregation. v1 blobs (pre-integrity
# checkpoints) still parse — unverified.
_BLOB_VERSION = 2

# Payloads rejected by the integrity framing (length or checksum). The
# RPC layer surfaces the ValueError as INVALID_ARGUMENT; the controller's
# malformed-result path drops the contribution without stalling the round.
_M_CORRUPT = _tmetrics.registry().counter(
    _tel.M_CORRUPT_PAYLOADS_TOTAL,
    "Model blobs rejected by length/checksum integrity framing")


def _escape(part: str) -> str:
    # '/' joins path components; escape literal '/' (and the escape char) so
    # {'a': {'b': x}} and {'a/b': y} can never collide.
    return part.replace("%", "%25").replace("/", "%2F")


def _key_to_name(path) -> str:
    parts = []
    for entry in path:
        if isinstance(entry, jax.tree_util.DictKey):
            parts.append(_escape(str(entry.key)))
        elif isinstance(entry, jax.tree_util.SequenceKey):
            parts.append(str(entry.idx))
        elif isinstance(entry, jax.tree_util.GetAttrKey):
            parts.append(_escape(str(entry.name)))
        elif isinstance(entry, jax.tree_util.FlattenedIndexKey):
            parts.append(str(entry.key))
        else:  # pragma: no cover - future key types
            parts.append(_escape(str(entry)))
    return "/".join(parts)


def _check_unique(names) -> None:
    if len(set(names)) != len(names):
        seen, dupes = set(), set()
        for n in names:
            (dupes if n in seen else seen).add(n)
        raise ValueError(f"duplicate tensor names in model: {sorted(dupes)[:5]}")


def pytree_to_named_tensors(tree) -> NamedTensors:
    """Flatten a pytree of arrays to ``[(name, np.ndarray), ...]`` (ordered)."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    named = [(_key_to_name(path), np.asarray(leaf)) for path, leaf in flat]
    _check_unique([n for n, _ in named])
    return named


def named_tensors_to_pytree(named: NamedTensors, treedef_like):
    """Rebuild a pytree structured like ``treedef_like`` from named tensors."""
    flat = jax.tree_util.tree_flatten_with_path(treedef_like)
    paths = [_key_to_name(p) for p, _ in flat[0]]
    _check_unique([n for n, _ in named])
    by_name = dict(named)
    missing = [p for p in paths if p not in by_name]
    if missing:
        raise KeyError(f"model blob is missing tensors: {missing[:5]}")
    leaves = [by_name[p] for p in paths]
    return jax.tree_util.tree_unflatten(flat[1], leaves)


@dataclass
class ModelBlob:
    """A serializable model: ordered named tensors plus opaque entries.

    ``tensors`` holds plaintext arrays; ``opaque`` holds ciphertext/masked
    payloads keyed by the same names (a blob is either all-plaintext or
    all-opaque in practice, but the container does not force it).
    """

    tensors: NamedTensors = field(default_factory=list)
    opaque: Dict[str, tuple] = field(default_factory=dict)  # name -> (payload, spec)

    @property
    def names(self) -> List[str]:
        seen = [n for n, _ in self.tensors]
        seen.extend(self.opaque.keys())
        return seen

    @property
    def num_parameters(self) -> int:
        return sum(int(a.size) for _, a in self.tensors) + sum(
            spec.size for _, spec in self.opaque.values()
        )

    def to_bytes(self) -> bytes:
        chunks = []
        for name, arr in self.tensors:
            nb = name.encode("utf-8")
            chunks.append(struct.pack("<H", len(nb)))
            chunks.append(nb)
            chunks.append(tensor_to_bytes(arr))
        for name, (payload, spec) in self.opaque.items():
            nb = name.encode("utf-8")
            chunks.append(struct.pack("<H", len(nb)))
            chunks.append(nb)
            chunks.append(opaque_tensor_to_bytes(spec, payload))
        body = b"".join(chunks)
        return b"".join([
            _MAGIC,
            struct.pack("<BI", _BLOB_VERSION, len(self.names)),
            struct.pack("<QI", len(body), zlib.crc32(body)),
            body,
        ])

    @classmethod
    def from_bytes(cls, buf, copy: bool = True) -> "ModelBlob":
        view = memoryview(buf)
        if bytes(view[:4]) != _MAGIC:
            raise ValueError("not a metisfl-tpu model blob")
        version, count = struct.unpack_from("<BI", view, 4)
        offset = 9
        if version == 2:
            try:
                body_len, crc = struct.unpack_from("<QI", view, offset)
            except struct.error:
                _M_CORRUPT.inc()
                raise ValueError("truncated model blob header") from None
            offset += 12
            body = view[offset:]
            if len(body) != body_len:
                _M_CORRUPT.inc()
                raise ValueError(
                    f"model blob length mismatch (framed {body_len} body "
                    f"bytes, have {len(body)}) — truncated or spliced "
                    "payload")
            if zlib.crc32(body) != crc:
                _M_CORRUPT.inc()
                raise ValueError(
                    "model blob checksum mismatch — corrupt payload "
                    "rejected before deserialization")
        elif version != 1:  # v1: legacy pre-integrity blobs parse unverified
            raise ValueError(f"unsupported blob version {version}")
        blob = cls()
        for _ in range(count):
            (nlen,) = struct.unpack_from("<H", view, offset)
            offset += 2
            name = bytes(view[offset : offset + nlen]).decode("utf-8")
            offset += nlen
            value, spec, offset = tensor_from_bytes(view, offset, copy=copy)
            if spec.kind is TensorKind.PLAINTEXT:
                blob.tensors.append((name, value))
            else:
                blob.opaque[name] = (value, spec)
        return blob


def pack_model(params_tree) -> bytes:
    """One-call pytree → wire bytes."""
    return ModelBlob(tensors=pytree_to_named_tensors(params_tree)).to_bytes()


def unpack_model(buf, treedef_like):
    """One-call wire bytes → pytree shaped like ``treedef_like``."""
    blob = ModelBlob.from_bytes(buf)
    return named_tensors_to_pytree(blob.tensors, treedef_like)
