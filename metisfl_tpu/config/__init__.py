from metisfl_tpu.config.federation import (
    AggregationConfig,
    EvalConfig,
    FederationConfig,
    LearnerEndpoint,
    ModelStoreConfig,
    SecureAggConfig,
    TerminationConfig,
    load_config,
)

__all__ = [
    "FederationConfig",
    "AggregationConfig",
    "ModelStoreConfig",
    "SecureAggConfig",
    "TerminationConfig",
    "EvalConfig",
    "LearnerEndpoint",
    "load_config",
]
