from metisfl_tpu.config.federation import (
    AggregationConfig,
    CheckpointConfig,
    EvalConfig,
    FederationConfig,
    LearnerEndpoint,
    ModelStoreConfig,
    SecureAggConfig,
    TelemetryConfig,
    TerminationConfig,
    load_config,
)

__all__ = [
    "FederationConfig",
    "AggregationConfig",
    "CheckpointConfig",
    "ModelStoreConfig",
    "SecureAggConfig",
    "TelemetryConfig",
    "TerminationConfig",
    "EvalConfig",
    "LearnerEndpoint",
    "load_config",
]
