"""Typed federation environment.

Single source of truth for runtime configuration — replaces the reference's
three duplicated tiers (YAML env, ``ControllerParams`` proto, hex-proto CLI
args; SURVEY.md §5.6 flags the duplication): one dataclass tree, loadable
from YAML (reference examples/config/template.yaml shape) or built in code,
serializable through the wire codec for process launch.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from metisfl_tpu.comm.codec import dumps, loads
from metisfl_tpu.comm.messages import TrainParams
from metisfl_tpu.comm.ssl import SSLConfig


@dataclass
class TerminationConfig:
    """Reference fedenv_parser.py TerminationSignals + driver monitor loop
    (driver_session.py:443-477): stop on rounds, wall-clock, or metric."""

    federation_rounds: int = 10
    execution_cutoff_mins: float = 0.0       # 0 → no wall-clock cutoff
    metric_cutoff_score: float = 0.0         # 0 → no metric cutoff
    metric_name: str = "accuracy"


@dataclass
class SchedulingConfig:
    """Churn-tolerant round scheduling (docs/RESILIENCE.md "Cross-device
    churn"): quorum barriers, FedBuff buffer sizing, churn-aware
    admission, and bounded dispatch retry. Every plane here is opt-out:
    the defaults reduce each controller hot path to one attribute check
    and keep round behavior bit-identical to the plain barriers."""

    # K-of-N quorum for sync/semi-sync rounds: the round releases the
    # moment `quorum` dispatched learners reported (reporters become the
    # cohort; the stragglers' tasks expire exactly like deadline drops).
    # 0 = full-cohort barrier (today's behavior, bit-identical); any
    # quorum >= the dispatched size is likewise the full barrier.
    quorum: int = 0
    # over-provisioned dispatch (Oort-style): with a quorum configured,
    # each round dispatches ceil(quorum * (1 + overprovision)) learners
    # so ~30% per-round dropout still leaves a quorum of reporters
    overprovision: float = 0.0
    # protocol=asynchronous_buffered: uplinks fold into a buffer of this
    # many reporters; aggregation triggers per buffer-fill (FedBuff K)
    buffer_size: int = 10
    # churn/flap scoring (selection.py ChurnTracker): EWMA of leave /
    # flap-rejoin / failed-dispatch events per learner, alongside the
    # straggler and divergence scores. false: no tracker constructed
    # (one attribute check on every membership path)
    churn_tracking: bool = True
    churn_alpha: float = 0.3
    # quarantine: a churn event lifting a learner's score past this
    # excludes it from cohort sampling for quarantine_s seconds
    # (0 = scoring only, never quarantine)
    quarantine_score: float = 0.0
    quarantine_s: float = 30.0
    # bounded dispatch retry-with-backoff: when a train dispatch provably
    # fails, drop the dead learner from the round barrier and dispatch a
    # replacement learner after backoff, up to this many retries per
    # round (0 = off: a failed dispatch stalls to the deadline, today's
    # behavior). Doubles retry_backoff_s per consecutive retry.
    dispatch_retries: int = 0
    retry_backoff_s: float = 0.5
    # consecutive zero-reporter round deadlines tolerated before the
    # round HALTS with a lineage error instead of re-dispatching forever
    # (0 = unbounded re-dispatch, today's behavior)
    max_empty_redispatch: int = 8


@dataclass
class TreeAggregationConfig:
    """Tree-aggregation tier (aggregation/tree.py): partition the cohort
    into ``branch`` slices, fold each in a worker (parallel store selects
    + parallel host folds), fold the partials — controller fan-in becomes
    O(branch) and fold residency is bounded by ~branch sub-blocks instead
    of the cohort. Applies to the weighted-sum rules on the store path
    (fedavg / scaffold / fedstride); everything else falls back to the
    flat fold. ``enabled=false`` leaves the aggregation path at one
    attribute check."""

    enabled: bool = False
    branch: int = 8
    workers: int = 0                         # 0 → min(branch, cpu_count)
    # distributed tier (aggregation/slice.py + distributed.py,
    # docs/RESILIENCE.md "Distributed slice aggregators"): promote the
    # branches to driver-booted slice aggregator PROCESSES — each owns a
    # contiguous cohort slice, receives its learners' uplinks over gRPC,
    # and ships one partial fold; the controller fans in O(branch)
    # partials and re-homes a dead aggregator's slice mid-round. false
    # (default) keeps the in-process tier and the one-attribute-check
    # hot path.
    distributed: bool = False
    # slice endpoints [{name, host, port, spool_dir}]; the driver fills
    # one per branch when left empty (operators running their own
    # aggregator fleet list them explicitly)
    slices: List[Dict[str, Any]] = field(default_factory=list)
    # spool root for the driver-booted aggregators ("" → <workdir>/slices);
    # the per-slice spool is what mid-round re-homing recovers from
    spool_dir: str = ""
    # bounded submit retry before an unreachable aggregator is declared
    # dead and its slice re-homes (doubling backoff, PR 8's posture)
    rehome_retries: int = 3
    rehome_backoff_s: float = 0.2


@dataclass
class AggregationConfig:
    rule: str = "fedavg"                     # fedavg | fedstride | fedrec |
                                             # secure_agg | fedavgm |
                                             # fedadam | fedyogi | scaffold |
                                             # median | trimmed_mean |
                                             # krum | multikrum
    # server-optimizer hyperparameters (fedavgm / fedadam / fedyogi only)
    server_learning_rate: float = 1.0
    server_beta1: float = 0.9
    server_beta2: float = 0.99
    server_tau: float = 1e-3
    scaler: str = "train_dataset_size"       # participants | train_dataset_size | batches
    stride_length: int = 0                   # 0 → all models in one block
    # how many learners participate per round (1.0 = all) — reference
    # ControllerParams.participation_ratio
    participation_ratio: float = 1.0
    # FedAsync-style staleness damping: contribution weights multiply by
    # (1 + staleness_rounds)^-decay and renormalize. 0 disables. Only
    # meaningful under the asynchronous protocol (synchronous barriers
    # have staleness 0 everywhere).
    staleness_decay: float = 0.0
    # byzantine-robust rules (aggregation/robust.py): tail fraction each
    # side for trimmed_mean; assumed byzantine count for krum/multikrum
    # (0 derives the max tolerable (n-3)//2 from the cohort)
    trim_ratio: float = 0.1
    byzantine_f: int = 0
    # streaming aggregation (aggregation/streaming.py, docs/SCALE.md):
    # fold each accepted uplink into the community accumulator as it
    # arrives off the wire — no store round-trip — for fedavg /
    # fedstride / fedrec when the store lineage permits; other rules
    # (and secure agg) automatically fall back to the store path.
    # false (default) keeps today's path at one attribute check.
    streaming: bool = False
    # hierarchical tree-aggregation tier for the store path
    tree: TreeAggregationConfig = field(default_factory=TreeAggregationConfig)


@dataclass
class ModelStoreConfig:
    store: str = "in_memory"                 # in_memory | disk | cached_disk
                                             # | remote
    lineage_length: int = 0                  # 0 → derive from aggregation rule
    root: str = ""                           # disk store directory
    cache_mb: int = 256                      # cached_disk memory budget
    # store="remote": endpoint of a python -m metisfl_tpu.store.server
    # process (the reference's external-Redis posture, SURVEY.md §2.1 C12)
    host: str = "localhost"
    port: int = 0
    # parallel ingest (store/ingest.py, docs/SCALE.md): >0 decouples
    # payload persistence from the uplink path — a bounded pool of this
    # many writers drains completions into the store and aggregation
    # fences on drain before select. 0 (default) = today's synchronous
    # insert on the completion path (one attribute check).
    ingest_workers: int = 0


@dataclass
class SecureAggConfig:
    enabled: bool = False
    scheme: str = "masking"                  # masking | ckks | identity
    # CKKS params (reference ckks_scheme.cc:13-75 defaults; the native ring
    # packs 8192 coefficients regardless — kept for config parity)
    batch_size: int = 4096
    scaling_factor_bits: int = 52
    key_dir: str = ""
    # masking: the controller must know the party count to verify that all
    # masks cancel; the driver fills this in (secrets never enter this
    # config — they travel in per-learner secure files only)
    num_parties: int = 0
    # masking dropout recovery (the Bonawitz threshold t): never unmask a
    # partial sum of fewer surviving parties than this — at 1 the "sum"
    # would be a single learner's plaintext update
    min_recovery_parties: int = 2
    # masking at distributed scale (secure/distributed.py): 0 = every
    # pair masks against every other (the classic O(n·model) Bonawitz
    # construction); k > 0 = the deterministic ring k-regular mask graph
    # (Bell et al.) — O(k·model) mask generation per learner, dropout
    # recovery refuses splits that would isolate any survivor
    mask_neighbors: int = 0


@dataclass
class EventsConfig:
    """Structured event journal (telemetry/events.py): typed federation
    events (joins, rounds, dispatches, retries, faults) in a bounded
    in-memory ring + JSONL sink (under ``telemetry.dir``). The ring tail
    rides in ``DescribeFederation`` snapshots and post-mortem bundles.
    ``enabled=false`` makes every emit call site a one-attribute-check
    no-op (telemetry.enabled=false implies it)."""

    enabled: bool = True
    ring_size: int = 512


@dataclass
class HealthConfig:
    """Learning-health plane (telemetry/health.py): per-uplink update
    statistics (norms, cohort alignment), per-learner EWMA divergence
    scores (cohort-median/MAD robust z, the convergence analogue of the
    straggler score), and per-round convergence snapshots. Controller-
    side and host-numpy only; ``enabled=false`` leaves the uplink hot
    path at one attribute check (secure aggregation implies off — the
    payloads are opaque ciphertext)."""

    enabled: bool = True
    # EWMA blend for per-learner divergence scores (~last 3-4 rounds
    # dominate, matching the straggler analytics)
    alpha: float = 0.3
    # robust-z threshold past which an uplink emits UpdateAnomalous
    anomaly_threshold: float = 3.0
    # advisory hook: pass the scores to selection + robust aggregation
    # (informational — results are bit-identical either way; the rules
    # record/log which flagged learners entered the cohort)
    advisory: bool = False


@dataclass
class ProfileConfig:
    """Performance observatory (telemetry/profile.py): typed per-round
    cost profiles on the controller (phase waterfall, per-learner
    uplink/downlink wire bytes + codec attribution, store/aggregation
    time), device-utilization capture in the learner train loop
    (step-time EWMA, achieved MFU, HBM watermark, shipped back in
    ``TaskResult.device_stats``), and flag-gated periodic ``jax.profiler``
    trace capture. ``enabled=false`` leaves every hot path at one
    attribute check (no collector constructed, no device stats
    shipped). ``python -m metisfl_tpu.perf`` renders the profiles."""

    enabled: bool = True
    # arm a jax.profiler capture on the dispatched tasks every N rounds
    # (0 = never); sessions land under <dir>/jaxprof/round<N>/ in
    # collision-free per-capture subdirs
    trace_every_rounds: int = 0
    # RoundProfile JSONL sink dir ("" → telemetry.dir, next to traces)
    dir: str = ""


@dataclass
class ProfConfig:
    """Continuous profiling plane (telemetry/prof.py): an always-on
    stack sampler per process (daemon thread over
    ``sys._current_frames()``) folding into a bounded mergeable
    folded-stack table, plus instrumented wrappers on the hot locks
    (controller registry, store lineage/LRU, ingest, slice reducer,
    serving queue, fleet collector) recording wait-time histograms and
    per-site contention counters. Profiles ride ``CollectTelemetry``
    and each RoundProfile carries the per-round folded-stack delta;
    ``python -m metisfl_tpu.perf --flame`` / ``--flame-diff`` render
    them. ``enabled=false``: no sampler thread, and the lock factories
    hand back raw ``threading`` locks — zero wrapper cost."""

    enabled: bool = True
    # sampling frequency; 67 Hz is deliberately off-harmonic with the
    # 1/10/100 ms periods federation work is built from (GWP posture)
    hz: float = 67.0
    # folded-stack table budget: top-`budget` stacks keep exact labels,
    # the crowd collapses into the SpaceSaving eviction floor — fleet
    # profiles stay O(budget) however long the process runs
    budget: int = 512


@dataclass
class RuntimeConfig:
    """Accelerator runtime observability (telemetry/runtime.py): XLA
    compile tracking (``jax.monitoring`` duration listener + the
    ``monitored_jit`` attribution wrappers on the jit entrypoints we
    own), cold-vs-recompile classification with storm events, and
    device/host memory accounting sampled on the prof cadence.
    ``enabled=false`` installs no listener, wrapped jits pass straight
    through at one attribute check, and the ``CollectTelemetry``
    section is an ``{"enabled": false}`` stub."""

    enabled: bool = True
    # per-fn compile-row budget: this many names stay exact, the crowd
    # folds into the "_other" row (PR 9 posture)
    budget: int = 256
    # memory-sample gate on the prof sampler cadence (seconds): a 67 Hz
    # sampler costs one memory walk per this interval, not 67/s
    mem_every_s: float = 1.0
    # a recompile storm = storm_threshold recompiles of ONE function
    # inside storm_window_s (emits a jax_recompile_storm event, muted
    # per function for one window)
    storm_window_s: float = 10.0
    storm_threshold: int = 4


@dataclass
class FabricConfig:
    """Fleet telemetry fabric (telemetry/fabric.py): the
    ``CollectTelemetry`` cursor-pull RPC every role-carrying endpoint
    answers, and the driver-side :class:`FleetCollector` that polls the
    fleet with jitter, corrects per-peer clock skew NTP-style, and
    streams the merged span timeline into ``traces.jsonl`` live.
    ``enabled=false`` leaves every server at one attribute check (the
    handler answers a stub and the finished-span ring is disabled)."""

    enabled: bool = True
    # collector poll period (seconds) and its relative jitter in [0, 1)
    # — jitter de-correlates N collectors against one fleet
    poll_every_s: float = 2.0
    jitter: float = 0.3
    # clock-offset EWMA blend and the RTT gate: an offset sample is
    # accepted only when its round trip stays within rtt_gate × the
    # best RTT seen for that peer (a congested exchange can be off by
    # rtt/2)
    offset_alpha: float = 0.2
    rtt_gate: float = 3.0
    # per-process finished-span ring the cursor pulls read from
    # (0 → the trace module's default, 4096)
    span_ring: int = 0
    # causal critical-path attribution (telemetry/causal.py) over the
    # merged span buffer: refresh per sweep, export the heaviest edges
    # as round_critical_path_seconds{edge} and the snapshot's crit row.
    # False skips the walk (span collection itself is unaffected).
    critical_path: bool = True
    # how many heaviest edges each summary/gauge keeps
    critical_path_edges: int = 5


@dataclass
class TelemetryConfig:
    """Federation-wide observability (metisfl_tpu/telemetry): trace spans
    + metrics registry + event journal. ``enabled=false`` opts the whole
    subsystem out (instrument call sites become attribute-check no-ops)."""

    enabled: bool = True
    # JSONL trace-sink directory. "" → spans are not persisted (ids and
    # durations still flow into RoundMetadata); the driver fills this in
    # with <workdir>/telemetry so controller + learner files stitch.
    dir: str = ""
    # Cardinality budget for the per-learner metric families
    # (docs/OBSERVABILITY.md "Telemetry at scale"): past this many
    # series a family collapses to quantile series + top-K offender
    # series + a distinct count (mergeable sketches, telemetry/
    # sketch.py), bounding exposition / describe() / checkpoint at
    # O(budget) however large the fleet. 0 (default) = exact series,
    # today's behavior bit-identically.
    cardinality_budget: int = 0
    # SLO alert rules (telemetry/alerts.py AlertEngine; schema in its
    # module docstring): threshold / rate / digest-quantile expressions
    # with for: hold durations and resolve hysteresis. Validated at
    # config load; empty (default) constructs no engine.
    alerts: List[Dict[str, Any]] = field(default_factory=list)
    # alert-engine evaluation cadence (also the sampling period of the
    # bounded time-series ring behind status --watch sparklines)
    alerts_interval_s: float = 1.0
    # optional plain-HTTP /metrics listener on the controller (0 = off);
    # learners take --metrics-port on their CLI instead (N learners on
    # one host cannot share a configured port)
    http_port: int = 0
    # event journal (telemetry/events.py)
    events: EventsConfig = field(default_factory=EventsConfig)
    # learning-health plane (telemetry/health.py)
    health: HealthConfig = field(default_factory=HealthConfig)
    # performance observatory (telemetry/profile.py)
    profile: ProfileConfig = field(default_factory=ProfileConfig)
    # fleet telemetry fabric (telemetry/fabric.py)
    fabric: FabricConfig = field(default_factory=FabricConfig)
    # continuous profiling plane (telemetry/prof.py)
    prof: ProfConfig = field(default_factory=ProfConfig)
    # accelerator runtime observability (telemetry/runtime.py)
    runtime: RuntimeConfig = field(default_factory=RuntimeConfig)
    # flight-recorder bundle directory (telemetry/postmortem.py): crash /
    # chaos-kill / failover post-mortems land here. "" → recorder off;
    # the driver fills this in with <workdir>/postmortem.
    postmortem_dir: str = ""


@dataclass
class CommConfig:
    """Transport knobs (comm/rpc.py RpcClient construction).

    ``default_deadline_s`` bounds every RPC whose call site passes
    ``timeout=None`` — an unbounded default would let one hung peer park
    a dispatch thread forever. ``<= 0`` restores unbounded calls
    (explicit operator opt-out). DEADLINE_EXCEEDED is retried only for
    idempotent methods (getters, join, health)."""

    default_deadline_s: float = 120.0
    retries: int = 10
    retry_sleep_s: float = 1.0


@dataclass
class FailoverConfig:
    """Driver-side controller supervision (docs/RESILIENCE.md).

    The controller process is relaunched with ``--resume`` when it dies
    mid-run: the checkpoint restores the community model, round counter,
    AND the learner registry + auth tokens, so rejoining learners are
    recognized as themselves. ``max_controller_restarts`` bounds the
    budget (a deterministically-crashing controller must eventually
    fail the run); backoff doubles per consecutive restart."""

    supervise_controller: bool = True
    max_controller_restarts: int = 3
    restart_backoff_s: float = 1.0


@dataclass
class ControllerStandbyConfig:
    """Controller hot-standby (controller/wal.py + ``python -m
    metisfl_tpu.controller --standby``; docs/RESILIENCE.md "Controller
    hot-standby"). When enabled, the primary appends registry deltas and
    round-state snapshots to a write-ahead log under ``wal_dir`` (atomic
    rename before the ack, the spool posture) and the driver boots a
    warm standby that tails it. The standby escalates exactly like every
    other liveness path: WAL tail stale past ``stale_after_s`` →
    grpc.health.v1 probe of the primary → ``probe_failures`` consecutive
    non-SERVING verdicts → promote (restore WAL state, serve on its own
    pinned port, re-dispatch the in-flight round)."""

    enabled: bool = False
    host: str = "localhost"
    # standby gRPC port (0: the driver picks a free one and ships it to
    # every peer so the two-endpoint redial contract is pinned up front)
    port: int = 0
    # WAL directory shared by primary and standby (empty: the driver
    # defaults it under its workdir)
    wal_dir: str = ""
    # seconds without WAL progress before the standby probes the primary
    stale_after_s: float = 3.0
    # standby tail-loop poll cadence
    probe_interval_s: float = 0.5
    # consecutive non-SERVING health probes that trigger promotion
    probe_failures: int = 3


@dataclass
class ControllerConfig:
    """Controller-process knobs beyond the flat endpoint fields
    (``controller_host``/``controller_port`` predate this block and stay
    where every peer already reads them)."""

    standby: ControllerStandbyConfig = field(
        default_factory=ControllerStandbyConfig)


@dataclass
class ChaosConfig:
    """Deterministic fault injection (metisfl_tpu/chaos). ``rules`` are
    FaultRule dicts; each may carry ``process`` ("controller",
    "learner", or "learner_<idx>") — the driver filters rules per
    subprocess and arms them via the METISFL_TPU_CHAOS env var. Off by
    default; the transport's off-path cost is one attribute read."""

    enabled: bool = False
    seed: int = 0
    rules: List[Dict[str, Any]] = field(default_factory=list)


@dataclass
class PromotionConfig:
    """Promotion gate for the model registry (registry/registry.py): a
    candidate version moves to the ``stable`` channel only when every
    enabled rule passes. ``auto=false`` leaves promotion entirely to the
    operator (the ``PromoteVersion`` RPC)."""

    auto: bool = True
    # eval metric compared against the current stable version, as a
    # "<dataset>/<metric>" key of the folded community evaluation (mean
    # across learners). The candidate must not regress past min_delta:
    # loss/error-like metrics improve downward, everything else upward.
    metric: str = "test/accuracy"
    min_delta: float = 0.0
    # refuse to promote before the version's eval round-trip reported
    # back (false: metric rule only applies once metrics exist)
    require_eval: bool = True
    # refuse to promote a version whose source round scored any learner
    # update anomalous (UpdateAnomalous / health["anomalous"])
    forbid_anomalies: bool = True
    # bounded divergence-score quantile from the learning-health plane:
    # the source round's per-learner divergence scores at
    # ``divergence_quantile`` must stay <= max_divergence (0 = rule off)
    max_divergence: float = 0.0
    divergence_quantile: float = 0.9


@dataclass
class RegistryConfig:
    """Versioned community-model registry (registry/registry.py): every
    successful aggregation registers a candidate version (monotonic id,
    round, parent, config hash, health snapshot, eval metrics once they
    report), channel-promoted candidate → stable through the gate above,
    with explicit rollback and bounded retention GC. Lineage persists
    through the controller checkpoint so it survives ``--resume``
    failover. ``enabled=false`` keeps the post-aggregation path at one
    attribute check."""

    enabled: bool = False
    # retired + candidate versions kept beyond the channel heads; older
    # ones are garbage-collected (their blobs erased from the store and
    # their per-version gauge series pruned)
    retention: int = 5
    promotion: PromotionConfig = field(default_factory=PromotionConfig)


@dataclass
class ServingFleetConfig:
    """Replicated serving fleet (serving/fleet.py, docs/DEPLOYMENT.md
    "Serving fleet"): N driver-booted gateway replicas behind a
    consistent-hash router process (``python -m metisfl_tpu.serving
    --router``). Key-stable routing keeps the crc32 canary split
    globally coherent across replicas; replicas stagger their registry
    polls deterministically so a promotion rolls through the fleet one
    replica at a time; the router drains around dead/draining replicas
    with bounded retry to the next hash owner. ``enabled=false``
    (default) keeps PR 5's single supervised gateway exactly as it
    was."""

    enabled: bool = False
    # replicas booted at launch (the autoscaler moves the live count
    # within [min_replicas, max_replicas] afterwards)
    replicas: int = 2
    min_replicas: int = 1
    max_replicas: int = 4
    # router gRPC port (0: the driver picks a free one and points
    # serving.port — what serving_client() dials — at it)
    router_port: int = 0
    # consistent-hash virtual nodes per replica (keyspace smoothing)
    vnodes: int = 64
    # bounded retry past the hash owner when it fails at call time
    retry_hops: int = 2
    # router health-probe cadence over the replica fleet
    probe_every_s: float = 1.0
    # autoscaler rules (telemetry/alerts.py AlertRule schema, kinds
    # value|rate, evaluated over fleet-summed serving_* families by the
    # driver): scale_up firing boots a replica, scale_down drains one.
    # Empty = no autoscaler. Example:
    #   scale_up: {metric: serving_requests_total, kind: rate,
    #              window_s: 10, op: ">", threshold: 50, for_s: 2}
    scale_up: Dict[str, Any] = field(default_factory=dict)
    scale_down: Dict[str, Any] = field(default_factory=dict)
    # minimum seconds between scale actions (flap damping)
    scale_cooldown_s: float = 30.0
    # replica endpoints [{name, host, port}]; the driver fills one per
    # replica when left empty (operators running their own fleet list
    # them explicitly)
    gateways: List[Dict[str, Any]] = field(default_factory=list)


@dataclass
class ServingDecodeConfig:
    """Continuous-batching autoregressive decode (serving/decode.py):
    the gateway's ``Generate`` endpoint schedules a slot-based
    in-flight batch at step granularity over the KV-cache programs in
    models/generate.py — finished sequences retire and queued prompts
    join between decode steps, one jitted step program at fixed slot
    shapes. Greedy by contract; output is bit-identical to a solo
    ``generate`` call at the same ``max_len``."""

    # concurrent sequences per channel's in-flight batch
    slots: int = 4
    # KV-cache length: every request's prompt + max_new_tokens must fit
    max_len: int = 512


@dataclass
class ServingConfig:
    """Serving gateway (serving/gateway.py): a driver-bootable process
    (``python -m metisfl_tpu.serving``) serving inference over the
    federation's BytesService RPC with a micro-batching queue, atomic
    hot-swap to newly promoted versions, and a percentage-based canary
    split toward the ``candidate`` channel. Requires the registry."""

    enabled: bool = False
    host: str = "0.0.0.0"
    # gateway gRPC port (0: the driver picks a free one at launch)
    port: int = 0
    # micro-batching: coalesce concurrent requests until the batch holds
    # max_batch rows or max_wait_ms elapsed since the first queued row.
    # Every forward pass pads to exactly max_batch rows (one compiled
    # program, and per-row results stay bit-identical to unbatched).
    max_batch: int = 8
    max_wait_ms: float = 5.0
    # deterministic canary: requests whose key hashes into the lowest
    # canary_percent slots route to the candidate channel (0 = all stable)
    canary_percent: float = 0.0
    # registry poll period: how often the gateway compares channel heads
    # against the controller and hot-swaps on change
    poll_every_s: float = 1.0
    # which learner recipe builds the gateway's model engine (the forward
    # pass needs the same architecture the federation trains)
    recipe_index: int = 0
    # replicated fleet behind a consistent-hash router (serving/fleet.py)
    fleet: ServingFleetConfig = field(default_factory=ServingFleetConfig)
    # continuous-batching decode for the Generate endpoint
    decode: ServingDecodeConfig = field(default_factory=ServingDecodeConfig)


@dataclass
class CheckpointConfig:
    """Controller-side global checkpoint (SURVEY.md §5.4: the reference has
    no resume flow; community model + round counter are rebuilt here)."""

    dir: str = ""                            # "" → checkpointing disabled
    every_n_rounds: int = 1


@dataclass
class EvalConfig:
    batch_size: int = 256
    datasets: List[str] = field(default_factory=lambda: ["test"])
    metrics: List[str] = field(default_factory=lambda: ["loss", "accuracy"])
    every_n_rounds: int = 1


@dataclass
class LearnerEndpoint:
    hostname: str = "localhost"
    port: int = 0
    # per-learner dataset shard paths / recipe names (driver-side concern)
    dataset: Dict[str, Any] = field(default_factory=dict)
    # Multi-host learner world: processes launched for this ONE learner.
    # Rank 0 serves the federation; ranks 1..world_size-1 replay its compute
    # calls (parallel/replicated.py). The local launcher starts all ranks on
    # the endpoint host; true one-rank-per-host worlds are launched by the
    # operator with the METISFL_JAX_* env vars.
    world_size: int = 1
    coordinator_port: int = 0                # 0 → driver picks a free port


@dataclass
class FederationConfig:
    protocol: str = "synchronous"            # synchronous | semi_synchronous |
                                             # asynchronous |
                                             # asynchronous_buffered
    semi_sync_lambda: float = 1.0
    semi_sync_recompute_every_round: bool = False
    # Straggler deadline for sync/semi-sync rounds: a dispatched learner that
    # has not reported within this many seconds is dropped from the round
    # barrier and the round proceeds with whoever did report. 0 → no deadline
    # (reference behavior: a hung learner stalls the round forever,
    # SURVEY.md §5.3).
    round_deadline_secs: float = 0.0
    # Learner liveness: after this many consecutive failed train dispatches a
    # learner is treated as unreachable and excluded from cohort sampling
    # until it completes a task or rejoins (the reference only logs failed
    # dispatches and keeps scheduling them, controller.cc:783-786). 0 → off.
    max_dispatch_failures: int = 3
    scheduling: SchedulingConfig = field(default_factory=SchedulingConfig)
    aggregation: AggregationConfig = field(default_factory=AggregationConfig)
    model_store: ModelStoreConfig = field(default_factory=ModelStoreConfig)
    secure: SecureAggConfig = field(default_factory=SecureAggConfig)
    termination: TerminationConfig = field(default_factory=TerminationConfig)
    checkpoint: CheckpointConfig = field(default_factory=CheckpointConfig)
    registry: RegistryConfig = field(default_factory=RegistryConfig)
    serving: ServingConfig = field(default_factory=ServingConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    comm: CommConfig = field(default_factory=CommConfig)
    failover: FailoverConfig = field(default_factory=FailoverConfig)
    controller: ControllerConfig = field(default_factory=ControllerConfig)
    chaos: ChaosConfig = field(default_factory=ChaosConfig)
    ssl: SSLConfig = field(default_factory=SSLConfig)
    train: TrainParams = field(default_factory=TrainParams)
    eval: EvalConfig = field(default_factory=EvalConfig)
    controller_host: str = "localhost"
    controller_port: int = 50051
    learners: List[LearnerEndpoint] = field(default_factory=list)

    def __post_init__(self):
        if self.secure.enabled and self.aggregation.rule not in ("secure_agg",):
            raise ValueError(
                "secure aggregation requires aggregation.rule == 'secure_agg' "
                "(reference fedenv_parser.py:301-309 enforces PWA iff HE)"
            )
        if self.aggregation.rule == "secure_agg" and not self.secure.enabled:
            raise ValueError("aggregation.rule 'secure_agg' requires secure.enabled")
        if (self.secure.enabled and self.secure.scheme == "masking"
                and self.aggregation.scaler != "participants"):
            # MaskingBackend.weighted_sum rejects non-uniform scales at
            # aggregation time; fail at startup instead of stalling round 1.
            raise ValueError(
                "masking secure aggregation requires uniform scales: set "
                "aggregation.scaler: participants — that configuration "
                "composes with aggregation.streaming, "
                "aggregation.tree.distributed, and quorum dropout "
                f"recovery (got scaler={self.aggregation.scaler!r})")
        if (self.secure.enabled and self.secure.scheme == "masking"
                and self.protocol.startswith("asynchronous")):
            # Pairwise masks only cancel when ALL parties' payloads enter one
            # combine — structurally a synchronous barrier (a FedBuff
            # buffer is a partial cohort too). Async secure federations
            # need a partial-cohort-capable scheme (ckks).
            raise ValueError(
                "masking secure aggregation requires protocol: synchronous "
                "or semi_synchronous (pairwise masks only cancel across "
                "one round barrier; semi_synchronous masking still "
                "tolerates dropouts via seed-share recovery). For a truly "
                "asynchronous secure federation use scheme: ckks")
        if self.protocol not in ("synchronous", "semi_synchronous",
                                 "asynchronous", "asynchronous_buffered"):
            raise ValueError(f"unknown protocol {self.protocol!r}")
        sched = self.scheduling
        if sched.quorum < 0:
            raise ValueError("scheduling.quorum must be >= 0")
        if sched.quorum > 0 and self.protocol.startswith("asynchronous"):
            # the asynchronous protocols have no round barrier a quorum
            # could shorten — a silently ignored knob would "validate"
            # churn tolerance that was never armed
            raise ValueError(
                "scheduling.quorum requires a synchronous or "
                "semi-synchronous protocol (asynchronous rounds have no "
                "barrier; use scheduling.buffer_size for "
                "asynchronous_buffered)")
        if sched.overprovision < 0.0:
            raise ValueError("scheduling.overprovision must be >= 0")
        if sched.overprovision > 0.0 and sched.quorum <= 0:
            # only the quorum sampler reads overprovision — accepting it
            # alone would silently arm nothing (same posture as the
            # quorum/asynchronous rejection above)
            raise ValueError(
                "scheduling.overprovision requires scheduling.quorum > 0 "
                "(over-provisioning sizes the quorum dispatch)")
        if sched.buffer_size < 1:
            raise ValueError("scheduling.buffer_size must be >= 1")
        if not 0.0 < sched.churn_alpha <= 1.0:
            # same posture as telemetry.health.alpha: a typo'd blend
            # weight would silently freeze or unsmooth every churn score
            raise ValueError("scheduling.churn_alpha must be in (0, 1]")
        if sched.quarantine_score < 0.0:
            raise ValueError("scheduling.quarantine_score must be >= 0")
        if sched.quarantine_score > 0.0 and sched.quarantine_s <= 0.0:
            raise ValueError(
                "scheduling.quarantine_s must be > 0 when quarantine is "
                "armed (a zero-length quarantine never excludes anyone)")
        if sched.quarantine_score > 0.0 and not sched.churn_tracking:
            raise ValueError(
                "scheduling.quarantine_score requires churn_tracking "
                "(quarantine is driven by the churn scores)")
        if sched.dispatch_retries < 0:
            raise ValueError("scheduling.dispatch_retries must be >= 0")
        if sched.dispatch_retries > 0 and sched.retry_backoff_s <= 0.0:
            raise ValueError(
                "scheduling.retry_backoff_s must be > 0 when "
                "dispatch_retries is armed")
        if sched.max_empty_redispatch < 0:
            raise ValueError("scheduling.max_empty_redispatch must be >= 0")
        if self.chaos.enabled:
            # a typo'd fault name must fail at config time, not fire-time
            # (an injector that silently never fires "validates" nothing)
            from metisfl_tpu.chaos.injector import ChaosInjector
            try:
                ChaosInjector.from_spec({"seed": self.chaos.seed,
                                         "rules": self.chaos.rules})
            except (TypeError, ValueError) as exc:
                raise ValueError(f"invalid chaos rule: {exc}") from None
        if self.failover.max_controller_restarts < 0:
            raise ValueError("failover.max_controller_restarts must be >= 0")
        standby = self.controller.standby
        if standby.enabled:
            if standby.stale_after_s <= 0.0:
                raise ValueError(
                    "controller.standby.stale_after_s must be > 0 (a "
                    "zero staleness window probes a healthy primary "
                    "every tick)")
            if standby.probe_interval_s <= 0.0:
                raise ValueError(
                    "controller.standby.probe_interval_s must be > 0")
            if standby.probe_failures < 1:
                raise ValueError(
                    "controller.standby.probe_failures must be >= 1 "
                    "(promotion must require at least one probe verdict)")
            if standby.port < 0:
                raise ValueError("controller.standby.port must be >= 0")
        elif standby.wal_dir:
            # the silently-armed-nothing posture (quorum/overprovision):
            # a wal_dir on a disabled standby replicates to nobody
            raise ValueError(
                "controller.standby.wal_dir requires "
                "controller.standby.enabled (the WAL exists to keep a "
                "standby promote-ready)")
        if self.registry.enabled and self.secure.enabled and (
                self.secure.scheme != "masking"):
            # under ckks the registered blobs are opaque ciphertext: the
            # gateway could never decode them and eval-gated promotion
            # would compare metrics of models nobody can serve. Masking
            # is different by construction — the masks cancel at
            # settlement (secure/recovery.py), so the registered
            # community is the protocol's PUBLIC plain output and
            # round-pinned versioning composes with it
            raise ValueError(
                "registry requires a decodable community model: secure "
                f"scheme {self.secure.scheme!r} registers opaque "
                "ciphertext — use scheme: masking, whose settled output "
                "is the public plain aggregate and composes with the "
                "registry")
        if self.registry.enabled and self.registry.retention < 1:
            raise ValueError("registry.retention must be >= 1")
        if self.registry.enabled:
            q = self.registry.promotion.divergence_quantile
            if not 0.0 < q <= 1.0:
                raise ValueError(
                    "registry.promotion.divergence_quantile must be in "
                    "(0, 1]")
        if self.serving.enabled:
            if not self.registry.enabled:
                # the gateway serves registry channels; without versions
                # there is nothing to install or swap
                raise ValueError(
                    "serving.enabled requires registry.enabled (the "
                    "gateway serves promoted registry versions)")
            if self.serving.max_batch < 1:
                raise ValueError("serving.max_batch must be >= 1")
            if self.serving.max_wait_ms < 0:
                raise ValueError("serving.max_wait_ms must be >= 0")
            if not 0.0 <= self.serving.canary_percent <= 100.0:
                raise ValueError(
                    "serving.canary_percent must be in [0, 100]")
            if self.serving.recipe_index < 0:
                # a negative index would silently pick a recipe from the
                # END of the driver's list via Python indexing
                raise ValueError("serving.recipe_index must be >= 0")
            if self.serving.decode.slots < 1:
                raise ValueError("serving.decode.slots must be >= 1")
            if self.serving.decode.max_len < 2:
                # one prompt token + one generated token is the minimum
                # generation the cache must hold
                raise ValueError("serving.decode.max_len must be >= 2")
            fleet = self.serving.fleet
            if fleet.enabled:
                if fleet.min_replicas < 1:
                    raise ValueError(
                        "serving.fleet.min_replicas must be >= 1")
                if fleet.max_replicas < fleet.min_replicas:
                    raise ValueError(
                        "serving.fleet.max_replicas must be >= "
                        "min_replicas")
                if not (fleet.min_replicas <= fleet.replicas
                        <= fleet.max_replicas):
                    raise ValueError(
                        "serving.fleet.replicas must lie within "
                        "[min_replicas, max_replicas]")
                if fleet.vnodes < 1:
                    raise ValueError("serving.fleet.vnodes must be >= 1")
                if fleet.retry_hops < 0:
                    raise ValueError(
                        "serving.fleet.retry_hops must be >= 0")
                if fleet.probe_every_s <= 0.0:
                    raise ValueError(
                        "serving.fleet.probe_every_s must be > 0")
                if fleet.scale_cooldown_s < 0.0:
                    raise ValueError(
                        "serving.fleet.scale_cooldown_s must be >= 0")
                if fleet.scale_up or fleet.scale_down:
                    # a typo'd scale rule must fail at config time, not
                    # at the first traffic surge (the alert/chaos-rule
                    # posture); quantile kinds are rejected inside —
                    # a scraped family sum has no digest to read
                    from metisfl_tpu.serving.fleet import FleetAutoscaler
                    try:
                        FleetAutoscaler(
                            fleet.scale_up or None,
                            fleet.scale_down or None,
                            fleet.min_replicas, fleet.max_replicas,
                            cooldown_s=fleet.scale_cooldown_s)
                    except (TypeError, ValueError) as exc:
                        raise ValueError(
                            f"invalid serving.fleet scale rule: "
                            f"{exc}") from None
        fleet = self.serving.fleet
        if fleet.enabled and not self.serving.enabled:
            # the silently-armed-nothing posture (quorum/overprovision):
            # a fleet block on a disabled serving plane boots nothing
            raise ValueError(
                "serving.fleet.enabled requires serving.enabled")
        if ((fleet.scale_up or fleet.scale_down)
                and not fleet.enabled):
            # scale rules only drive the fleet autoscaler — accepting
            # them alone would silently arm nothing
            raise ValueError(
                "serving.fleet.scale_up/scale_down require "
                "serving.fleet.enabled (the autoscaler boots and drains "
                "fleet replicas)")
        if not 0.0 < self.telemetry.health.alpha <= 1.0:
            # a typo'd blend weight would silently freeze (0) or unsmooth
            # (>1 oscillates) every divergence score
            raise ValueError("telemetry.health.alpha must be in (0, 1]")
        if self.telemetry.health.anomaly_threshold <= 0.0:
            # threshold 0 would flag EVERY above-median update anomalous
            raise ValueError(
                "telemetry.health.anomaly_threshold must be > 0")
        if self.telemetry.profile.trace_every_rounds < 0:
            # a negative period would silently never fire via the modulo
            raise ValueError(
                "telemetry.profile.trace_every_rounds must be >= 0")
        if self.telemetry.cardinality_budget < 0:
            raise ValueError("telemetry.cardinality_budget must be >= 0")
        fab = self.telemetry.fabric
        if fab.poll_every_s <= 0.0:
            raise ValueError("telemetry.fabric.poll_every_s must be > 0")
        if not 0.0 <= fab.jitter < 1.0:
            raise ValueError("telemetry.fabric.jitter must be in [0, 1)")
        if not 0.0 < fab.offset_alpha <= 1.0:
            # same posture as the other EWMA blends: a typo'd weight
            # would silently freeze or unsmooth every offset estimate
            raise ValueError(
                "telemetry.fabric.offset_alpha must be in (0, 1]")
        if fab.rtt_gate < 1.0:
            # a gate under 1 rejects even the best-RTT sample — the
            # estimator would never converge
            raise ValueError("telemetry.fabric.rtt_gate must be >= 1")
        if fab.span_ring < 0:
            raise ValueError("telemetry.fabric.span_ring must be >= 0")
        if fab.critical_path_edges < 1:
            # 0 edges is an attribution that attributes nothing — turn
            # the walk off with critical_path=false instead
            raise ValueError(
                "telemetry.fabric.critical_path_edges must be >= 1")
        pr = self.telemetry.prof
        if pr.enabled:
            if not 0.0 < pr.hz <= 1000.0:
                # 0 would park the sampler thread in a busy loop's
                # degenerate cousin (wait(inf)); past 1 kHz the sampler
                # IS the workload it claims to measure
                raise ValueError(
                    "telemetry.prof.hz must be in (0, 1000]")
            if pr.budget < 16:
                # a tiny table thrashes the SpaceSaving floor and every
                # profile becomes eviction noise
                raise ValueError("telemetry.prof.budget must be >= 16")
        rt = self.telemetry.runtime
        if rt.enabled:
            # the silently-armed-nothing posture: a knob that would make
            # the plane record nothing (or storm-mute everything) must
            # fail at config time, not "run" blind
            if rt.budget < 8:
                raise ValueError("telemetry.runtime.budget must be >= 8")
            if rt.mem_every_s <= 0.0:
                raise ValueError(
                    "telemetry.runtime.mem_every_s must be > 0")
            if rt.storm_window_s <= 0.0:
                raise ValueError(
                    "telemetry.runtime.storm_window_s must be > 0")
            if rt.storm_threshold < 2:
                # 1 would flag every single recompile as a "storm"
                raise ValueError(
                    "telemetry.runtime.storm_threshold must be >= 2")
        if self.telemetry.alerts_interval_s <= 0.0:
            raise ValueError("telemetry.alerts_interval_s must be > 0")
        if self.telemetry.alerts:
            # a typo'd rule must fail at config time, not at fire time —
            # an alert that silently never evaluates "watches" nothing
            # (same posture as the chaos-rule validation below)
            from metisfl_tpu.telemetry.alerts import validate_rules
            try:
                validate_rules(self.telemetry.alerts)
            except (TypeError, ValueError) as exc:
                raise ValueError(f"invalid telemetry.alerts rule: "
                                 f"{exc}") from None
        if not 0.0 < self.aggregation.participation_ratio <= 1.0:
            raise ValueError("participation_ratio must be in (0, 1]")
        if self.model_store.ingest_workers < 0:
            raise ValueError("model_store.ingest_workers must be >= 0")
        if self.aggregation.tree.enabled and self.aggregation.tree.branch < 2:
            # a 1-way "tree" is the flat fold with extra thread hops
            raise ValueError("aggregation.tree.branch must be >= 2")
        if self.aggregation.tree.workers < 0:
            raise ValueError("aggregation.tree.workers must be >= 0")
        tree = self.aggregation.tree
        if tree.distributed:
            if not tree.enabled:
                # the distributed tier IS the tree tier's topology — a
                # silently ignored knob would "validate" a fleet that was
                # never booted (the overprovision/quorum posture)
                raise ValueError(
                    "aggregation.tree.distributed requires "
                    "aggregation.tree.enabled")
            # capability matrix (docs/SECURITY.md "Secure aggregation at
            # scale"): masking COMPOSES with the distributed tier —
            # masked payloads are modular uint64 sums, so slices fold
            # them as plain blobs and the masks cancel at the root by
            # construction (secure/distributed.py). HE ciphertexts do
            # not: CKKS addition needs the evaluation context the slices
            # deliberately never hold.
            if self.secure.enabled and self.secure.scheme != "masking":
                raise ValueError(
                    "aggregation.tree.distributed with secure aggregation "
                    "requires secure.scheme: masking (masked partial sums "
                    "fold key-free at the slices; "
                    f"scheme={self.secure.scheme!r} payloads need the "
                    "one-combine path)")
            if self.aggregation.streaming and not (
                    self.secure.enabled
                    and self.secure.scheme == "masking"):
                raise ValueError(
                    "aggregation.tree.distributed with "
                    "aggregation.streaming requires masking secure "
                    "aggregation (slices fold masked uplinks on arrival; "
                    "plaintext uplinks fold at their slice aggregator, "
                    "not in the controller's stream)")
            if self.model_store.ingest_workers > 0:
                raise ValueError(
                    "aggregation.tree.distributed is incompatible with "
                    "model_store.ingest_workers (uplinks bypass the root "
                    "store entirely; there is nothing to ingest — this "
                    "holds for every secure scheme and for plaintext)")
            if self.aggregation.rule.lower() not in ("fedavg", "scaffold",
                                                     "fedstride",
                                                     "secure_agg"):
                # same silently-ignored-knob posture as the checks above:
                # a rule that cannot slice-fold would boot (and pay for)
                # a whole aggregator fleet that never receives a byte
                raise ValueError(
                    f"aggregation.tree.distributed requires a weighted-"
                    f"sum rule (fedavg/scaffold/fedstride) or masked "
                    f"secure_agg, not {self.aggregation.rule!r}")
            if tree.rehome_retries < 0:
                raise ValueError(
                    "aggregation.tree.rehome_retries must be >= 0")
            if tree.rehome_retries > 0 and tree.rehome_backoff_s <= 0.0:
                raise ValueError(
                    "aggregation.tree.rehome_backoff_s must be > 0 when "
                    "rehome_retries is armed")
        if (self.aggregation.streaming and self.secure.enabled
                and self.secure.scheme != "masking"):
            # streaming folds payloads on arrival; masked payloads are
            # modular uint64 sums so fold-on-arrival is exact
            # (secure/distributed.py MaskedStreamingAggregator), but HE
            # ciphertexts need the keyed full-cohort combine — fail
            # loudly instead of silently falling back, the operator
            # asked for a path this federation cannot take
            raise ValueError(
                "aggregation.streaming with secure aggregation requires "
                "secure.scheme: masking (masked payloads fold on arrival "
                f"as modular sums; scheme={self.secure.scheme!r} "
                "ciphertexts cannot)")
        if self.train.dp_noise_multiplier < 0.0 or self.train.dp_clip_norm < 0.0:
            # a sign typo must not silently disable the mechanism
            raise ValueError("dp_clip_norm and dp_noise_multiplier must be "
                             ">= 0")
        if self.aggregation.staleness_decay < 0.0:
            raise ValueError("staleness_decay must be >= 0")
        if self.aggregation.rule.lower() == "scaffold":
            if self.secure.enabled:
                # control deltas (essentially averaged local gradients)
                # would ship and fold in plaintext next to encrypted model
                # payloads, defeating the keyless-controller guarantee
                raise ValueError(
                    "scaffold is incompatible with secure aggregation: "
                    "control deltas are not encrypted/masked")
            if self.train.dp_clip_norm > 0.0:
                # the model delta would be privatized but the control delta
                # ships raw — the DP guarantee would silently not hold
                raise ValueError(
                    "scaffold is incompatible with dp_clip_norm: control "
                    "deltas are not privatized, so the DP guarantee would "
                    "not cover them")
            if any(int(getattr(ep, "world_size", 1)) > 1
                   for ep in self.learners):
                # the multi-host replay protocol has no grad-offset op
                raise ValueError(
                    "scaffold is not supported for multi-host learner "
                    "worlds (world_size > 1)")
            if self.train.optimizer.lower() != "sgd":
                # the Option-II variate update divides by K*lr, which is the
                # inverse of a plain-SGD step; with an adaptive local
                # optimizer the variate would be silently mis-scaled
                raise ValueError(
                    "scaffold requires optimizer='sgd' (the control-variate "
                    "update c_i+ = c_i - c + (x - y)/(K*lr) assumes plain "
                    "SGD local steps)")
        if (self.secure.enabled and self.secure.scheme == "masking"
                and self.aggregation.staleness_decay > 0.0):
            # damping re-introduces non-uniform scales AFTER the scaler, and
            # pairwise masks only cancel under uniform scales — a deadline
            # straggler would otherwise poison every aggregation until the
            # failure limit halts the federation
            raise ValueError(
                "staleness_decay is incompatible with masking secure "
                "aggregation (masks only cancel under uniform scales). "
                "Deadline stragglers compose with masking the other way: "
                "leave staleness_decay at 0 and let the mask settlement "
                "recover expired learners via seed-share disclosure "
                "(secure.min_recovery_parties)")
        if self.secure.mask_neighbors < 0:
            raise ValueError("secure.mask_neighbors must be >= 0 (0 = "
                             "complete pairwise mask graph)")
        if (self.train.dp_noise_multiplier > 0.0
                and self.train.dp_clip_norm <= 0.0):
            # the noise std is noise_multiplier * clip_norm — without a
            # clip bound the mechanism has no sensitivity and no guarantee
            raise ValueError(
                "dp_noise_multiplier > 0 requires dp_clip_norm > 0 "
                "(noise scales with the clip bound)")
        from metisfl_tpu.tensor.quantize import SHIP_INT8Q
        from metisfl_tpu.tensor.sparse import parse_topk
        from metisfl_tpu.tensor.spec import resolve_ship_dtype

        if self.train.ship_dtype:
            # a typo here would otherwise fail only after round 1's full
            # local training, on every learner, every round
            topk_denom = parse_topk(self.train.ship_dtype)
            if (self.train.ship_dtype.lower() != SHIP_INT8Q
                    and topk_denom is None):
                resolve_ship_dtype(self.train.ship_dtype)
            if ((self.train.ship_dtype.lower() == SHIP_INT8Q
                 or topk_denom is not None) and self.secure.enabled):
                # secure payloads carry their own fixed-point encoding
                raise ValueError(
                    f"ship_dtype={self.train.ship_dtype!r} is incompatible "
                    "with secure aggregation (HE/masking payloads have "
                    "their own fixed-point encoding)")
            if (topk_denom is not None
                    and self.protocol.lower().startswith("asynchronous")):
                # the controller densifies a topk update against ITS
                # community model; under async that model advances between
                # dispatch and completion, so the reconstruction reference
                # would be wrong
                raise ValueError(
                    "ship_dtype='topk...' requires a synchronous or "
                    "semi_synchronous protocol (async advances the "
                    "community model mid-task, breaking sparse-update "
                    "reconstruction)")
        if self.train.local_tensor_regex:
            import re as _re

            try:
                _re.compile(self.train.local_tensor_regex)
            except _re.error as exc:
                raise ValueError(
                    f"local_tensor_regex does not compile: {exc}") from None
            if self.secure.enabled:
                raise ValueError(
                    "local_tensor_regex is incompatible with secure "
                    "aggregation (partial trees break the uniform-shape "
                    "masking/HE payload contract)")
            stateful = ("fedavgm", "fedadam", "fedyogi", "fednova",
                        "scaffold")
            if self.aggregation.rule.lower() in stateful:
                raise ValueError(
                    f"local_tensor_regex is incompatible with rule="
                    f"{self.aggregation.rule!r}: stateful server rules "
                    "track a full model tree, but local tensors drop out "
                    "of the aggregate after round 1")
            if self.train.dp_clip_norm > 0.0:
                raise ValueError(
                    "local_tensor_regex is incompatible with client-level "
                    "DP: the clip norm is computed over the full update, "
                    "so never-shipped local tensors (e.g. BatchNorm "
                    "running stats) would consume the sensitivity budget "
                    "and silently crush the shipped update")
        if self.train.ship_tensor_regex:
            import re as _re

            try:
                _re.compile(self.train.ship_tensor_regex)
            except _re.error as exc:
                raise ValueError(
                    f"ship_tensor_regex does not compile: {exc}") from None
            if self.train.local_tensor_regex:
                # both partition the tensor tree (one retains, one
                # selects); composing them invites silent misconfiguration
                # — a name matching neither or both has no defined owner
                raise ValueError(
                    "ship_tensor_regex and local_tensor_regex cannot "
                    "combine: one selects the federated subset, the other "
                    "retains a local subset — pick one partition")
            # secure aggregation COMPOSES with ship_tensor_regex: unlike
            # FedBN's local tensors (each learner's own diverging values),
            # the shipped subset is identical across parties (same regex x
            # same architecture), so the uniform-shape masking/HE payload
            # contract holds — and encrypting 50 MB of adapters instead of
            # a 17 GB model is what makes secure LoRA federations practical
            if self.aggregation.rule.lower() == "scaffold":
                # the control variate c spans the full params tree; a
                # subset-resident controller cannot fold or broadcast it
                raise ValueError(
                    "ship_tensor_regex is incompatible with rule="
                    "'scaffold' (control variates span the full model "
                    "tree)")
            if self.train.dp_clip_norm > 0.0:
                # same rationale as local_tensor_regex: the clip norm is
                # computed over the full update, so frozen tensors'
                # (nominally zero, but unfrozen-engine nonzero) deltas
                # would consume the sensitivity budget unaccountably
                raise ValueError(
                    "ship_tensor_regex is incompatible with client-level "
                    "DP: the clip norm covers the full update while only "
                    "the subset ships, so the guarantee would be "
                    "mis-accounted")
        if self.train.downlink_dtype:
            import numpy as _np

            target = _np.dtype(resolve_ship_dtype(self.train.downlink_dtype))
            # bf16/f8 are ml_dtypes extension types (not np.floating
            # subtypes) — reject only genuinely non-float wire dtypes
            if _np.issubdtype(target, _np.integer) or target == _np.bool_:
                raise ValueError(
                    f"downlink_dtype {self.train.downlink_dtype!r} must be "
                    "a float dtype (integer state never narrows)")
            if self.secure.enabled:
                raise ValueError(
                    "downlink_dtype is incompatible with secure aggregation "
                    "(the broadcast is an opaque ciphertext payload)")
            if parse_topk(self.train.ship_dtype or "") is not None:
                raise ValueError(
                    "downlink_dtype cannot combine with ship_dtype='topk...'"
                    ": sparse updates reconstruct against the controller's "
                    "exact f32 community model, and a narrowed downlink "
                    "changes the learner's reference")

    # -- wire/launch serialization ----------------------------------------
    def to_wire(self) -> bytes:
        return dumps(_to_plain(self))

    @classmethod
    def from_wire(cls, buf) -> "FederationConfig":
        return _from_plain(cls, loads(buf))

    def to_dict(self) -> dict:
        return _to_plain(self)

    @classmethod
    def from_dict(cls, data: dict) -> "FederationConfig":
        return _from_plain(cls, data)


def _to_plain(obj):
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: _to_plain(getattr(obj, f.name))
                for f in dataclasses.fields(obj)}
    if isinstance(obj, list):
        return [_to_plain(x) for x in obj]
    if isinstance(obj, dict):
        return {k: _to_plain(v) for k, v in obj.items()}
    return obj


def _from_plain(cls, data):
    if not dataclasses.is_dataclass(cls):
        return data
    import typing

    hints = typing.get_type_hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name not in data:
            continue
        value = data[f.name]
        hint = hints.get(f.name)
        if dataclasses.is_dataclass(hint) and isinstance(value, dict):
            value = _from_plain(hint, value)
        elif isinstance(value, list):
            args = typing.get_args(hint)
            if args and dataclasses.is_dataclass(args[0]):
                value = [_from_plain(args[0], v) for v in value]
        kwargs[f.name] = value
    return cls(**kwargs)


def load_config(path: str) -> FederationConfig:
    """Load a federation environment from YAML."""
    import yaml

    with open(path) as f:
        data = yaml.safe_load(f) or {}
    return _from_plain(FederationConfig, data)
