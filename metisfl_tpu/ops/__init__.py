"""Pallas TPU kernels for the hot ops."""

from metisfl_tpu.ops.flash_attention import (FLASH_MIN_SEQ, attention,
                                             flash_attention)

__all__ = ["flash_attention", "attention", "FLASH_MIN_SEQ"]
