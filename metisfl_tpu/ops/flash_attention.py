"""Pallas flash attention (TPU kernel, interpret-mode on CPU).

Blockwise attention with online softmax in VMEM: the (L, L) score matrix
never reaches HBM — each grid step holds one (BLK_Q, D) query block and
streams K/V blocks through VMEM, accumulating flash-style m/l/o statistics.
Score/value products hit the MXU as dense (BLK_Q, BLK_K) @ (BLK_K, D)
matmuls. The reference framework has no custom kernels at all (its hot loop
is byte-blob C++ arithmetic, SURVEY.md §2.1 C3); this is the TPU-native hot
path for the transformer ladder.

Scope: forward pass is the pallas kernel; the backward pass (custom VJP)
recomputes attention densely with XLA einsums — "flash forward, dense
backward". For long-context training memory, use the ring-attention path
(parallel/ringattn.py); this kernel targets single-chip speed at moderate L.

Best on TPU with head_dim a multiple of 128 (lane width) and block sizes a
multiple of 8 (f32 sublanes); any shape works in interpret mode.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

_NEG = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, blk_k: int, causal: bool,
                  scale: float):
    qi = pl.program_id(1)
    q = q_ref[0] * scale                       # (BLK_Q, D)
    blk_q, D = q.shape
    L = k_ref.shape[1]
    nk = L // blk_k

    def body(j, carry):
        o, m, l = carry
        k = k_ref[0, pl.dslice(j * blk_k, blk_k), :]      # (BLK_K, D)
        v = v_ref[0, pl.dslice(j * blk_k, blk_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * blk_q + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 0)
            k_pos = j * blk_k + jax.lax.broadcasted_iota(
                jnp.int32, (blk_q, blk_k), 1)
            mask = q_pos >= k_pos
            s = jnp.where(mask, s, _NEG)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1, keepdims=True)
        o_new = o * corr + jax.lax.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        return o_new, m_new, l_new

    o0 = jnp.zeros((blk_q, D), jnp.float32)
    m0 = jnp.full((blk_q, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((blk_q, 1), jnp.float32)
    o, _, l = jax.lax.fori_loop(0, nk, body, (o0, m0, l0))
    o_ref[0] = (o / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal: bool, blk_q: int, blk_k: int,
                   interpret: bool):
    B, H, L, D = q.shape
    blk_q = min(blk_q, L)
    blk_k = min(blk_k, L)
    if L % blk_q or L % blk_k:
        raise ValueError(f"sequence length {L} must divide into blocks "
                         f"({blk_q}, {blk_k})")
    scale = float(1.0 / np.sqrt(D))
    qf = q.reshape(B * H, L, D)
    kf = k.reshape(B * H, L, D)
    vf = v.reshape(B * H, L, D)
    kernel = functools.partial(_flash_kernel, blk_k=blk_k, causal=causal,
                               scale=scale)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B * H, L, D), q.dtype),
        grid=(B * H, L // blk_q),
        in_specs=[
            pl.BlockSpec((1, blk_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, L, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, L, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, blk_q, D), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(B, H, L, D)


def _dense_attention(q, k, v, causal: bool):
    D = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * float(1.0 / np.sqrt(D))
    if causal:
        L = q.shape[2]
        mask = jnp.tril(jnp.ones((L, L), bool))
        s = jnp.where(mask, s, _NEG)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = False, blk_q: int = 128,
                    blk_k: int = 128, interpret: Optional[bool] = None):
    """Flash attention over (B, H, L, D). ``interpret=None`` auto-selects
    interpret mode off-TPU so the same call works in CI and on chip."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_forward(q, k, v, causal, blk_q, blk_k, interpret)


def _fwd(q, k, v, causal, blk_q, blk_k, interpret):
    return flash_attention(q, k, v, causal, blk_q, blk_k, interpret), (q, k, v)


def _bwd(causal, blk_q, blk_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: _dense_attention(q, k, v, causal),
                     q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
