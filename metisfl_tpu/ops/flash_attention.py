"""Pallas flash attention (TPU kernels, interpret-mode on CPU).

Blockwise attention with online softmax: the (L, L) score matrix never
reaches HBM. Forward and backward are Mosaic-native grid-accumulation
kernels — the KV (resp. Q) block index is a sequential GRID dimension,
running statistics live in VMEM scratch across grid steps, and causal
skipping is ``pl.when`` predication of whole blocks. No dynamic loop trip
counts anywhere (an earlier revision drove a ``fori_loop`` with a
program-id-dependent bound; grid predication is the pattern the TPU
toolchain is built for), and K/V stream through VMEM one block per step, so
VMEM stays bounded at any sequence length.

The backward is the FlashAttention-2 scheme: dQ accumulates over KV blocks,
dK/dV accumulate over Q blocks, both recomputing probabilities from the
forward's saved logsumexp — training memory is O(L·D) end to end. The
forward accumulator is FA2's unnormalized numerator (one alpha rescale per
step, a single divide at the store). Causal mode skips fully-masked blocks
in all three kernels (~half the FLOPs), and the skipped steps' block
index maps clamp to the last valid block so the pipeline elides their
DMAs too (~half the HBM traffic).

Where it wins: the kernel's value is O(L·D) memory (the (L, L) score
matrix never materializes), which is what makes long sequences fit at all;
on raw speed XLA's fused dense attention is competitive at moderate L
(measured on v5e at seq 2048: dense 74.2 ms vs flash 77.6 ms fwd —
bench_results/tpu_v5e_round3b.json), with the kernel's causal block skip
paying off as L grows past the score-matrix memory wall. Use
:func:`attention` to route between the two on sequence length instead of
hand-picking.

Sequence lengths that do not divide the block size are zero-padded up to
the next block boundary and masked inside the kernels (padded rows are
sliced off on the way out), so any L works on both paths.

The reference framework has no custom kernels at all (its hot loop is
byte-blob C++ arithmetic, SURVEY.md §2.1 C3); this is the TPU-native hot
path for the transformer ladder. Best with head_dim a multiple of 128
(lane width); block sizes are multiples of 8 (f32 sublanes).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30
_LANES = 128      # statistics SCRATCH: one value replicated across a vreg
_STAT_LANES = 8   # lse/delta in HBM: minimal tile-legal lane replication
# ((blk_q, 8) blocks satisfy Mosaic's tiling because the minor dim equals
# the full array dim; 128-lane replication in HBM would put the VJP's lse
# residual on par with Q itself at long sequence lengths)


def _causal_overlap(qi, blk_q, kj, blk_k):
    """True when key block kj has any unmasked column for query block qi."""
    return kj * blk_k <= (qi + 1) * blk_q - 1


def _mask_for(qi, blk_q, kj, blk_k, kv_len, causal):
    q_pos = qi * blk_q + jax.lax.broadcasted_iota(
        jnp.int32, (blk_q, blk_k), 0)
    k_pos = kj * blk_k + jax.lax.broadcasted_iota(
        jnp.int32, (blk_q, blk_k), 1)
    mask = k_pos < kv_len                       # tail-padding mask
    if causal:
        mask &= q_pos >= k_pos
    return mask


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_s, l_s, acc_s, *,
                causal: bool, scale: float, kv_len: int, nk: int):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    blk_q, D = q_ref.shape[1], q_ref.shape[2]
    blk_k = k_ref.shape[1]

    @pl.when(kj == 0)
    def _init():
        m_s[...] = jnp.full(m_s.shape, _NEG, jnp.float32)
        l_s[...] = jnp.zeros(l_s.shape, jnp.float32)
        acc_s[...] = jnp.zeros(acc_s.shape, jnp.float32)

    run = _causal_overlap(qi, blk_q, kj, blk_k) if causal else True

    @pl.when(run)
    def _attend():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        mask = _mask_for(qi, blk_q, kj, blk_k, kv_len, causal)
        s = jnp.where(mask, s, _NEG)
        m_prev = m_s[...]                       # (blk_q, LANES), lanes equal
        l_prev = l_s[...]
        m_curr = jnp.max(s, axis=1)[:, None]    # (blk_q, 1)
        m_next = jnp.maximum(m_prev, m_curr)    # (blk_q, LANES)
        p = jnp.exp(s - m_next[:, :1])
        p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_next)        # (blk_q, LANES)
        m_s[...] = m_next
        l_s[...] = alpha * l_prev + jnp.sum(p, axis=1)[:, None]
        # acc holds the UNNORMALIZED running numerator (FlashAttention-2):
        # one alpha rescale per step, a single divide at the final store —
        # two fewer vector multiplies per grid step than keeping the
        # running average normalized
        acc_s[...] = acc_s[...] * alpha[:, :1] + jax.lax.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)

    @pl.when(kj == nk - 1)
    def _store():
        l_fin = l_s[...]
        # fully-masked rows (tail padding) have l == 0: emit 0, not nan
        l_inv = jnp.where(l_fin == 0.0, 0.0, 1.0 / jnp.maximum(l_fin, 1e-30))
        o_ref[0] = (acc_s[...] * l_inv[:, :1]).astype(o_ref.dtype)
        lse_ref[0] = (m_s[...] + jnp.log(jnp.maximum(l_fin, 1e-30)))[
            :, :_STAT_LANES]


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_s, *, causal: bool, scale: float, kv_len: int, nk: int):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    blk_q = q_ref.shape[1]
    blk_k = k_ref.shape[1]

    @pl.when(kj == 0)
    def _init():
        dq_s[...] = jnp.zeros(dq_s.shape, jnp.float32)

    run = _causal_overlap(qi, blk_q, kj, blk_k) if causal else True

    @pl.when(run)
    def _accumulate():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]                 # (blk_q, 1)
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        mask = _mask_for(qi, blk_q, kj, blk_k, kv_len, causal)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_s[...] += jax.lax.dot(ds.astype(k.dtype), k,
                                 preferred_element_type=jnp.float32)

    @pl.when(kj == nk - 1)
    def _store():
        dq_ref[0] = dq_s[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_s, dv_s, *, causal: bool, scale: float,
                kv_len: int, nq: int, g_size: int = 1):
    kj = pl.program_id(1)
    # sequential dim enumerates (group member × q block), MEMBER-MAJOR
    # (t = member * nq + qi): the dK/dV of one KV head accumulates over
    # every query head in its group, and within one member's segment the
    # head component of the block index is constant — so the causal
    # clamp's repeated indices actually elide DMAs (q-block-major would
    # cycle heads every step and never repeat an index)
    t = pl.program_id(2)
    qi = t % nq
    blk_k = k_ref.shape[1]
    blk_q = q_ref.shape[1]

    @pl.when(t == 0)
    def _init():
        dk_s[...] = jnp.zeros(dk_s.shape, jnp.float32)
        dv_s[...] = jnp.zeros(dv_s.shape, jnp.float32)

    run = _causal_overlap(qi, blk_q, kj, blk_k) if causal else True

    @pl.when(run)
    def _accumulate():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        mask = _mask_for(qi, blk_q, kj, blk_k, kv_len, causal)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        # dV += P^T @ dO
        dv_s[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        # dK += dS^T @ Q
        dk_s[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(t == nq * g_size - 1)
    def _store():
        dk_ref[0] = dk_s[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_s[...].astype(dv_ref.dtype)


def _dense_attention(q, k, v, causal: bool):
    """XLA reference implementation (tests oracle + the routed dense path).

    Softmax in fp32 regardless of compute dtype — bf16 exp/normalize loses
    too much precision (same policy as the flash kernel's fp32 online
    statistics and the model zoo's dense branch); probabilities cast back
    so the PV matmul stays on the MXU's native path."""
    D = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * float(
        1.0 / np.sqrt(D))
    if causal:
        L = q.shape[2]
        mask = jnp.tril(jnp.ones((L, L), bool))
        s = jnp.where(mask, s, _NEG)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _pad_len(L: int, blk: int) -> int:
    return (L + blk - 1) // blk * blk


def _auto_blk(L: int) -> int:
    """Largest block edge in {512, 256, 128} that divides the 8-aligned
    sequence length. Bigger blocks cut grid steps (less per-step predication
    / scratch traffic, larger MXU matmuls) and stay well inside VMEM —
    q/k/v/do blocks at 512x128 bf16 are 128 KB each, the f32 scratch
    accumulators 256 KB — but an edge that does NOT divide L would pad the
    grid up to the next multiple and burn the padding as masked FLOPs
    (e.g. L=640 at blk 512 pads to 1024: ~2.5x the work), so divisibility
    wins over size."""
    L8 = _pad_len(L, 8)
    for cand in (512, 256, 128):
        if cand <= L8 and L8 % cand == 0:
            return cand
    return min(128, L8)


def _resolve_blocks(L: int, blk_q: Optional[int], blk_k: Optional[int]):
    blk_q = min(blk_q or _auto_blk(L), _pad_len(L, 8))
    blk_k = min(blk_k or _auto_blk(L), _pad_len(L, 8))
    Lp = max(_pad_len(L, blk_q), _pad_len(L, blk_k))
    return blk_q, blk_k, Lp


_SEQ_PARAMS = pltpu.TPUCompilerParams(
    dimension_semantics=("parallel", "parallel", "arbitrary"))


def _kv_head_index(Hq: int, Hkv: int):
    """Flat (batch*q-head) grid index → flat (batch*kv-head) array index:
    query head h reads KV group h // (Hq // Hkv). The KV tensors stay at
    kv-head size in HBM — no repeat is ever materialized."""
    G = Hq // Hkv
    return lambda b: (b // Hq) * Hkv + (b % Hq) // G


def _kv_block_index(kv_ix, blk_q: int, blk_k: int, causal: bool):
    """K/V block index map for the forward and dQ kernels. In causal mode
    the index clamps to the last unmasked block for the current query
    block: skipped steps (`pl.when` predicated off) then re-request the
    SAME block and the Mosaic pipeline elides the copy — causal saves
    ~half the HBM traffic, not just half the FLOPs. The clamp bound must
    match `_causal_overlap`'s run predicate (identical on live steps)."""
    if causal:
        def ix(b, i, j):
            return (kv_ix(b), jnp.minimum(j, ((i + 1) * blk_q - 1)
                                          // blk_k), 0)
    else:
        def ix(b, i, j):
            return (kv_ix(b), j, 0)
    return ix


def _gqa_shapes(q, k):
    B, Hq, L, D = q.shape
    Hkv = k.shape[1]
    if Hq % Hkv:
        raise ValueError(
            f"query heads ({Hq}) must be a multiple of KV heads ({Hkv})")
    return B, Hq, Hkv, L, D


def _flash_forward(q, k, v, causal: bool, blk_q: int, blk_k: int,
                   interpret: bool):
    B, H, Hkv, L, D = _gqa_shapes(q, k)
    blk_q, blk_k, Lp = _resolve_blocks(L, blk_q, blk_k)
    scale = float(1.0 / np.sqrt(D))
    kv_ix = _kv_head_index(H, Hkv)
    qf = q.reshape(B * H, L, D)
    kf = k.reshape(B * Hkv, L, D)
    vf = v.reshape(B * Hkv, L, D)
    if Lp != L:
        pad = ((0, 0), (0, Lp - L), (0, 0))
        qf, kf, vf = (jnp.pad(x, pad) for x in (qf, kf, vf))
    nk = Lp // blk_k
    kernel = functools.partial(_fwd_kernel, causal=causal, scale=scale,
                               kv_len=L, nk=nk)
    kv_index = _kv_block_index(kv_ix, blk_q, blk_k, causal)
    out, lse = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Lp, D), q.dtype),
            # logsumexp replicated across the lane dim (2D-tiled layout;
            # callers slice [:, :, 0])
            jax.ShapeDtypeStruct((B * H, Lp, _STAT_LANES), jnp.float32),
        ],
        grid=(B * H, Lp // blk_q, nk),
        in_specs=[
            pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, blk_k, D), kv_index),
            pl.BlockSpec((1, blk_k, D), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, blk_q, _STAT_LANES), lambda b, i, j: (b, i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_q, _LANES), jnp.float32),   # m
            pltpu.VMEM((blk_q, _LANES), jnp.float32),   # l
            pltpu.VMEM((blk_q, D), jnp.float32),        # acc
        ],
        compiler_params=None if interpret else _SEQ_PARAMS,
        interpret=interpret,
    )(qf, kf, vf)
    return out[:, :L].reshape(B, H, L, D), lse


def _flash_backward(q, k, v, out, lse, g, causal: bool, blk_q: int,
                    blk_k: int, interpret: bool, delta=None):
    """``lse`` (and the optional precomputed ``delta``) arrive in LOGICAL
    layout — (B, H, L) fp32; the kernel HBM layout (padded, lane-
    replicated) is produced here so callers never touch it. Padded query
    rows get a large lse sentinel: their g/delta are zero, but a small pad
    value could overflow p = exp(s - lse) into inf·0 = nan."""
    B, H, Hkv, L, D = _gqa_shapes(q, k)
    G = H // Hkv
    blk_q, blk_k, Lp = _resolve_blocks(L, blk_q, blk_k)
    scale = float(1.0 / np.sqrt(D))
    kv_ix = _kv_head_index(H, Hkv)
    flat = lambda x: x.reshape(-1, L, D)
    qf, kf, vf, of, gf = map(flat, (q, k, v, out, g))
    if delta is None:
        # delta_i = rowsum(dO_i * O_i)
        delta = jnp.sum(gf.astype(jnp.float32) * of.astype(jnp.float32),
                        axis=-1)
    delta = jnp.asarray(delta, jnp.float32).reshape(B * H, L)
    lse = jnp.asarray(lse, jnp.float32).reshape(B * H, L)
    if Lp != L:
        pad3 = ((0, 0), (0, Lp - L), (0, 0))
        qf, kf, vf, gf = (jnp.pad(x, pad3) for x in (qf, kf, vf, gf))
        delta = jnp.pad(delta, ((0, 0), (0, Lp - L)))
        lse = jnp.pad(lse, ((0, 0), (0, Lp - L)), constant_values=1e30)
    delta = jnp.broadcast_to(delta[..., None], (B * H, Lp, _STAT_LANES))
    lse = jnp.broadcast_to(lse[..., None], (B * H, Lp, _STAT_LANES))
    nq = Lp // blk_q
    nk = Lp // blk_k

    kv_index = _kv_block_index(kv_ix, blk_q, blk_k, causal)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, scale=scale,
                          kv_len=L, nk=nk),
        out_shape=jax.ShapeDtypeStruct((B * H, Lp, D), q.dtype),
        grid=(B * H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, blk_k, D), kv_index),
            pl.BlockSpec((1, blk_k, D), kv_index),
            pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, blk_q, _STAT_LANES), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, blk_q, _STAT_LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[pltpu.VMEM((blk_q, D), jnp.float32)],
        compiler_params=None if interpret else _SEQ_PARAMS,
        interpret=interpret,
    )(qf, kf, vf, gf, lse, delta)

    # dK/dV accumulate over (group member × q block), member-major
    # (t = member * nq + qi): grid b runs over B*Hkv KV heads. In causal
    # mode, Q blocks strictly above the diagonal are skipped — clamp
    # their index up to the first contributing block; within a member's
    # segment the head component is constant, so those repeated indices
    # elide the leading DMAs of every segment.
    def q_ix(b, j, t):
        qi = t % nq
        if causal:
            qi = jnp.maximum(qi, (j * blk_k) // blk_q)
        return ((b // Hkv) * H + (b % Hkv) * G + t // nq, qi, 0)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, scale=scale,
                          kv_len=L, nq=nq, g_size=G),
        out_shape=[
            jax.ShapeDtypeStruct((B * Hkv, Lp, D), k.dtype),
            jax.ShapeDtypeStruct((B * Hkv, Lp, D), v.dtype),
        ],
        grid=(B * Hkv, nk, nq * G),
        in_specs=[
            pl.BlockSpec((1, blk_q, D), q_ix),
            pl.BlockSpec((1, blk_k, D), lambda b, j, t: (b, j, 0)),
            pl.BlockSpec((1, blk_k, D), lambda b, j, t: (b, j, 0)),
            pl.BlockSpec((1, blk_q, D), q_ix),
            pl.BlockSpec((1, blk_q, _STAT_LANES), q_ix),
            pl.BlockSpec((1, blk_q, _STAT_LANES), q_ix),
        ],
        out_specs=[
            pl.BlockSpec((1, blk_k, D), lambda b, j, t: (b, j, 0)),
            pl.BlockSpec((1, blk_k, D), lambda b, j, t: (b, j, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_k, D), jnp.float32),
            pltpu.VMEM((blk_k, D), jnp.float32),
        ],
        compiler_params=None if interpret else _SEQ_PARAMS,
        interpret=interpret,
    )(qf, kf, vf, gf, lse, delta)

    return (dq[:, :L].reshape(B, H, L, D),
            dk[:, :L].reshape(B, Hkv, L, D),
            dv[:, :L].reshape(B, Hkv, L, D))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = False,
                    blk_q: Optional[int] = None,
                    blk_k: Optional[int] = None,
                    interpret: Optional[bool] = None):
    """Flash attention over (B, H, L, D). Grouped-query attention is
    native: ``k``/``v`` may carry fewer heads than ``q`` (Hq a multiple of
    Hkv) and stay at kv-head size in HBM — block index maps route each
    query head to its KV group; dK/dV accumulate over the group in the
    backward. ``blk_q``/``blk_k=None`` auto-size blocks (512 capped to the
    padded sequence). ``interpret=None`` auto-selects interpret mode
    off-TPU so the same call works in CI and on chip."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    out, _ = _flash_forward(q, k, v, causal, blk_q, blk_k, interpret)
    return out


def _fwd(q, k, v, causal, blk_q, blk_k, interpret):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    out, lse = _flash_forward(q, k, v, causal, blk_q, blk_k, interpret)
    B, H, L, _ = q.shape
    # residual lse in logical layout: 8x smaller than the kernel's
    # lane-replicated padded buffer, and the layout knowledge stays here
    return out, (q, k, v, out, lse[:, :L, 0].reshape(B, H, L))


def _bwd(causal, blk_q, blk_k, interpret, res, g):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    q, k, v, out, lse = res
    return _flash_backward(q, k, v, out, lse, g, causal, blk_q, blk_k,
                           interpret)


flash_attention.defvjp(_fwd, _bwd)


# Flash-vs-dense crossover (sequence length). Below it XLA's fused dense
# attention is at least as fast and compiles quicker; at/above it the dense
# path's (L, L) score matrix starts to dominate memory and the kernel's
# causal block skip pays off. Seeded from the v5e round-3 capture (dense
# still ahead at 2048); the bench's block sweep re-measures every round.
FLASH_MIN_SEQ = 4096


def attention(q, k, v, causal: bool = False, *,
              min_flash_seq: Optional[int] = None,
              blk_q: Optional[int] = None,
              blk_k: Optional[int] = None):
    """Sequence-length-routed attention: the pallas flash kernel at
    ``L >= min_flash_seq`` (default :data:`FLASH_MIN_SEQ`), XLA's fused
    dense attention below. GQA inputs (fewer K/V heads) work on both paths
    — dense broadcasts the KV groups at compute time."""
    threshold = FLASH_MIN_SEQ if min_flash_seq is None else int(min_flash_seq)
    if q.shape[2] >= threshold:
        return flash_attention(q, k, v, causal, blk_q, blk_k)
    if k.shape[1] != q.shape[1]:
        group = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, group, axis=1)
        v = jnp.repeat(v, group, axis=1)
    return _dense_attention(q, k, v, causal)
