"""Experiment summary CLI: ``python -m metisfl_tpu.stats experiment.json``.

The reference ships convergence-plot helpers with its examples
(reference examples/utils/convergence_plots.py — hardcoded paper
figures; driver_session.py:408-418 dumps the raw lineage); this is the
rebuild's generic equivalent — a round-by-round table (wall-clock,
cohort, aggregation time, model size) and per-metric convergence
summaries from the ``experiment.json`` a driver writes, plus an optional
``--plot out.png`` convergence figure (metric curves over evaluated
rounds + per-round wall-clock/aggregation bars) when matplotlib is
available. Payloads from a health-enabled controller additionally carry
per-round ``health`` snapshots and per-learner ``train_metrics``/
``epoch_metrics``, rendered as a per-learner learning-health table
(:func:`learning_health_summary`, :func:`epoch_loss_series`); older
payloads render exactly as before. Usable as a library via
:func:`summarize` / :func:`metric_series` / :func:`plot_convergence`.
"""

from __future__ import annotations

import json
import sys
from statistics import median
from typing import Any, Dict, List


def _fmt_ms(ms: float) -> str:
    return f"{ms / 1e3:.2f}s" if ms >= 1e3 else f"{ms:.1f}ms"


def summarize(stats: Dict[str, Any]) -> str:
    """Human-readable summary of a ``get_statistics()`` / experiment.json
    payload; returns the text (the CLI prints it)."""
    lines: List[str] = []
    rounds = stats.get("round_metadata", [])
    lines.append(
        f"federation: {stats.get('global_iteration', len(rounds))} rounds, "
        f"{len(stats.get('learners', []))} learners registered")

    if rounds:
        lines.append("")
        # telemetry-era payloads carry a span-sourced phase breakdown
        # (dispatch / wait-for-uplinks; aggregate was always present);
        # pre-telemetry experiment.json renders byte-identically because
        # the extra columns only appear when some round has the keys
        has_phases = any("dispatch_duration_ms" in m
                         or "wait_duration_ms" in m for m in rounds)
        phase_header = f"{'disp':>8} {'wait':>8} " if has_phases else ""
        # model-lifecycle lineage (registry era): the version each round
        # registered and the stable head at round close. Pre-registry
        # payloads lack the keys (or carry zeros) and render unchanged.
        has_versions = any(m.get("registered_version") for m in rounds)
        ver_header = f"{'ver':>6} {'stable':>6} " if has_versions else ""
        lines.append(f"{'round':>5} {'wall':>8} {phase_header}"
                     f"{'cohort':>6} {'agg':>8} "
                     f"{'params':>10} {'uplink':>9} {ver_header}"
                     f"{'errors':>6}")
        for meta in rounds:
            wall_ms = 1e3 * max(
                0.0, meta.get("completed_at", 0) - meta.get("started_at", 0))
            up = sum(meta.get("uplink_bytes", {}).values())
            up_s = (f"{up / 1e6:.1f}MB" if up >= 1e6
                    else f"{up / 1e3:.0f}KB" if up >= 1e3
                    else f"{up}B" if up else "-")
            phase_cells = ""
            if has_phases:
                phase_cells = (
                    f"{_fmt_ms(meta.get('dispatch_duration_ms', 0.0)):>8} "
                    f"{_fmt_ms(meta.get('wait_duration_ms', 0.0)):>8} ")
            ver_cells = ""
            if has_versions:
                reg = meta.get("registered_version", 0)
                stable = meta.get("stable_version", 0)
                ver_cells = (
                    f"{(f'v{reg}' if reg else '-'):>6} "
                    f"{(f'v{stable}' if stable else '-'):>6} ")
            lines.append(
                f"{meta.get('global_iteration', '?'):>5} "
                f"{_fmt_ms(wall_ms):>8} "
                f"{phase_cells}"
                f"{len(meta.get('selected_learners', [])):>6} "
                f"{_fmt_ms(meta.get('aggregation_duration_ms', 0.0)):>8} "
                f"{meta.get('model_size', {}).get('values', 0):>10} "
                f"{up_s:>9} "
                f"{ver_cells}"
                f"{len(meta.get('errors', [])):>6}")
        # clamped like the table rows, so both views agree on skewed clocks
        walls = [1e3 * max(0.0, m.get("completed_at", 0)
                           - m.get("started_at", 0))
                 for m in rounds if m.get("completed_at")]
        aggs = [m.get("aggregation_duration_ms", 0.0) for m in rounds]
        if walls:
            lines.append(
                f"round wall-clock: median {_fmt_ms(median(walls))}, "
                f"max {_fmt_ms(max(walls))}; aggregation median "
                f"{_fmt_ms(median(aggs))}")
        errors = [e for m in rounds for e in m.get("errors", [])]
        if errors:
            lines.append(f"round errors ({len(errors)}):")
            lines.extend(f"  - {e}" for e in errors[:10])

        straggler = straggler_summary(stats)
        if straggler:
            lines.append("")
            lines.append("per-learner train durations (dispatch → uplink; "
                         "rel = mean over cohort median):")
            for row in straggler:
                lines.append(
                    f"  {row['learner']:<28} mean={row['mean_s']:.2f}s "
                    f"max={row['max_s']:.2f}s rel={row['rel']:.2f}x "
                    f"over {row['rounds']} round(s)")

        profiles = profile_summary(stats)
        if profiles:
            lines.append("")
            lines.append("per-round cost profile (phase share of wall-clock; "
                         "python -m metisfl_tpu.perf renders the full "
                         "waterfall):")
            for row in profiles:
                shares = " ".join(
                    f"{name}={share * 100:.0f}%"
                    for name, share in row["shares"])
                lines.append(
                    f"  round {row['round']:>3}: {shares} "
                    f"coverage={row['coverage'] * 100:.0f}% "
                    f"up={row['uplink_bytes'] / 1e6:.2f}MB "
                    f"down={row['downlink_bytes'] / 1e6:.2f}MB")

        health = learning_health_summary(stats)
        if health:
            lines.append("")
            lines.append("per-learner learning health (divergence score = "
                         "EWMA cohort-relative robust z; telemetry/health):")
            for row in health:
                loss = ""
                if row["first_loss"] is not None:
                    loss = (f" loss {row['first_loss']:.4f}"
                            f"→{row['last_loss']:.4f}")
                anom = (f" anomalous in {row['anomalous_rounds']} round(s)"
                        if row["anomalous_rounds"] else "")
                lines.append(
                    f"  {row['learner']:<28} div last={row['last_div']:.2f} "
                    f"max={row['max_div']:.2f} "
                    f"upd_norm mean={row['mean_update_norm']:.3g}"
                    f"{loss}{anom}")

    series = metric_series(stats)
    if series:
        lines.append("")
        lines.append("community-model evaluations (mean across learners):")
        for key in sorted(series):
            vals = series[key]
            # "best" follows the metric's direction: loss/error-like
            # metrics improve downward, everything else upward
            lower_better = any(tag in key.lower()
                               for tag in ("loss", "error", "mse", "mae"))
            best = min(vals) if lower_better else max(vals)
            lines.append(
                f"  {key}: first={vals[0]:.4f} best={best:.4f} "
                f"last={vals[-1]:.4f} over {len(vals)} evaluated rounds")
    return "\n".join(lines)


def straggler_summary(stats: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Post-hoc straggler analytics from round metadata: per-learner
    dispatch→uplink durations (``train_submitted_at`` vs
    ``train_received_at``), slowest first, with the mean normalized by
    the cohort median (the same round-relative score the live
    ``DescribeFederation`` snapshot reports as ``straggler_score``).
    Empty when the lineage has no paired timestamps."""
    per_learner: Dict[str, List[float]] = {}
    for meta in stats.get("round_metadata", []):
        submitted = meta.get("train_submitted_at", {}) or {}
        received = meta.get("train_received_at", {}) or {}
        for lid, t_in in received.items():
            t_out = submitted.get(lid)
            if t_out is None:
                continue
            dur = float(t_in) - float(t_out)
            if dur >= 0:
                per_learner.setdefault(lid, []).append(dur)
    if not per_learner:
        return []
    means = {lid: sum(v) / len(v) for lid, v in per_learner.items()}
    med = median(means.values())
    rows = [
        {"learner": lid, "mean_s": means[lid],
         "max_s": max(per_learner[lid]),
         "rel": (means[lid] / med) if med > 0 else 0.0,
         "rounds": len(per_learner[lid])}
        for lid in per_learner
    ]
    rows.sort(key=lambda r: -r["mean_s"])
    return rows


def learning_health_summary(stats: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Post-hoc per-learner convergence/health table from round metadata:
    divergence scores and update norms (``health`` snapshots written by
    telemetry/health.py) joined with the per-learner train-loss
    trajectory (``train_metrics``/``epoch_metrics`` — the fields
    TaskResult always shipped and the controller now records). Sorted by
    last divergence score, highest first. Backward compatible: payloads
    written before the health plane (no ``health``/``train_metrics``
    keys) return []."""
    per: Dict[str, Dict[str, Any]] = {}

    def row(lid: str) -> Dict[str, Any]:
        return per.setdefault(lid, {
            "learner": lid, "last_div": 0.0, "max_div": 0.0,
            "update_norms": [], "anomalous_rounds": 0,
            "first_loss": None, "last_loss": None})

    for meta in stats.get("round_metadata", []):
        health = meta.get("health") or {}
        for lid, score in (health.get("divergence_score") or {}).items():
            r = row(lid)
            r["last_div"] = float(score)
            r["max_div"] = max(r["max_div"], float(score))
        for lid, norm in (health.get("update_norms") or {}).items():
            row(lid)["update_norms"].append(float(norm))
        for lid in health.get("anomalous") or []:
            row(lid)["anomalous_rounds"] += 1
        # train-loss trajectory: prefer the per-epoch records (finest
        # resolution); the task-level train_metrics (a MEAN over the
        # whole task) only fills in for learners with no epoch data
        # this round — it must not overwrite the final-epoch loss
        had_epochs = set()
        for lid, epochs in (meta.get("epoch_metrics") or {}).items():
            losses = [e["loss"] for e in epochs if "loss" in e]
            if losses:
                had_epochs.add(lid)
                r = row(lid)
                if r["first_loss"] is None:
                    r["first_loss"] = float(losses[0])
                r["last_loss"] = float(losses[-1])
        for lid, tm in (meta.get("train_metrics") or {}).items():
            if "loss" in tm and lid not in had_epochs:
                r = row(lid)
                if r["first_loss"] is None:
                    r["first_loss"] = float(tm["loss"])
                r["last_loss"] = float(tm["loss"])
    if not per:
        return []
    rows = []
    for r in per.values():
        norms = r.pop("update_norms")
        r["mean_update_norm"] = (sum(norms) / len(norms)) if norms else 0.0
        rows.append(r)
    rows.sort(key=lambda r: -r["last_div"])
    return rows


def profile_summary(stats: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Post-hoc per-round cost-profile rows from round metadata (the
    ``profile`` dicts the performance observatory records): phase shares
    of round wall-clock (largest first), waterfall coverage, and the
    round's wire-byte totals. Empty for pre-profile payloads (backward
    compatible)."""
    rows: List[Dict[str, Any]] = []
    for meta in stats.get("round_metadata", []):
        prof = meta.get("profile") or {}
        if not prof:
            continue
        wall = float(prof.get("wall_ms", 0.0))
        phases = prof.get("phases") or {}
        shares = sorted(
            ((name, (float(ms) / wall) if wall > 0 else 0.0)
             for name, ms in phases.items()),
            key=lambda kv: -kv[1])
        totals = prof.get("totals") or {}
        rows.append({
            "round": int(prof.get("round",
                                  meta.get("global_iteration", 0))),
            "wall_ms": wall,
            "shares": shares,
            "coverage": float(prof.get("coverage", 0.0)),
            "uplink_bytes": float(totals.get("uplink_bytes", 0.0)),
            "downlink_bytes": float(totals.get("downlink_bytes", 0.0)),
        })
    return rows


def version_lineage(stats: Dict[str, Any]) -> List[Dict[str, int]]:
    """Model-lifecycle lineage from round metadata: one row per round
    that registered a version (``{"round", "registered", "stable"}``).
    Empty for pre-registry payloads (backward compatible)."""
    rows = []
    for meta in stats.get("round_metadata", []):
        reg = int(meta.get("registered_version", 0) or 0)
        if not reg:
            continue
        rows.append({"round": int(meta.get("global_iteration", 0)),
                     "registered": reg,
                     "stable": int(meta.get("stable_version", 0) or 0)})
    return rows


def epoch_loss_series(stats: Dict[str, Any]) -> Dict[str, List[float]]:
    """``{learner: [per-epoch train losses across all rounds, in round
    order]}`` from the ``epoch_metrics`` now recorded in round metadata.
    Empty for pre-health payloads (backward compatible)."""
    series: Dict[str, List[float]] = {}
    for meta in stats.get("round_metadata", []):
        for lid, epochs in (meta.get("epoch_metrics") or {}).items():
            series.setdefault(lid, []).extend(
                float(e["loss"]) for e in epochs if "loss" in e)
    return series


def metric_series(stats: Dict[str, Any]) -> Dict[str, List[float]]:
    """``{"dataset/metric": [per-evaluated-round mean across learners]}``
    from a statistics payload — the series both the text summary and the
    plot draw."""
    series: Dict[str, List[float]] = {}
    for entry in stats.get("community_evaluations", []):
        if not entry.get("evaluations"):
            continue
        per_metric: Dict[str, List[float]] = {}
        for learner_metrics in entry["evaluations"].values():
            for dataset, metrics in learner_metrics.items():
                for name, value in metrics.items():
                    try:
                        per_metric.setdefault(
                            f"{dataset}/{name}", []).append(float(value))
                    except (TypeError, ValueError):
                        continue
        for key, values in per_metric.items():
            series.setdefault(key, []).append(sum(values) / len(values))
    return series


def plot_convergence(stats: Dict[str, Any], path: str) -> str:
    """Write a convergence figure (the reference convergence_plots.py
    role, generalized): one panel of community-metric curves over
    evaluated rounds, one of per-round wall-clock with the aggregation
    share. Requires matplotlib; raises ImportError where unavailable."""
    import matplotlib

    # force=False: a library caller's interactive backend (Jupyter, Qt)
    # must not be clobbered; headless processes resolve to Agg anyway
    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    # align every metric to the evaluated-round ordinal it was OBSERVED
    # at (a metric first reported in a later round must not shift left)
    aligned: Dict[str, List[tuple]] = {}
    eval_idx = 0
    for entry in stats.get("community_evaluations", []):
        if not entry.get("evaluations"):
            continue
        eval_idx += 1
        per_metric: Dict[str, List[float]] = {}
        for learner_metrics in entry["evaluations"].values():
            for dataset, metrics in learner_metrics.items():
                for name, value in metrics.items():
                    try:
                        per_metric.setdefault(
                            f"{dataset}/{name}", []).append(float(value))
                    except (TypeError, ValueError):
                        continue
        for key, values in per_metric.items():
            aligned.setdefault(key, []).append(
                (eval_idx, sum(values) / len(values)))
    rounds = stats.get("round_metadata", [])
    fig, axes = plt.subplots(1, 2 if rounds else 1,
                             figsize=(12 if rounds else 7, 4.5))
    ax0 = axes[0] if rounds else axes
    if aligned:
        for key in sorted(aligned):
            xs, ys = zip(*aligned[key])
            ax0.plot(xs, ys, marker="o", label=key)
        ax0.legend(fontsize=8)
    ax0.set_xlabel("evaluated round")
    ax0.set_ylabel("mean across learners")
    ax0.set_title("community-model convergence")
    ax0.grid(alpha=0.3)
    if rounds:
        idx = [m.get("global_iteration", i) for i, m in enumerate(rounds)]
        walls = [max(0.0, m.get("completed_at", 0) - m.get("started_at", 0))
                 for m in rounds]
        aggs = [m.get("aggregation_duration_ms", 0.0) / 1e3 for m in rounds]
        axes[1].bar(idx, walls, label="round wall-clock (s)", alpha=0.7)
        axes[1].bar(idx, aggs, label="aggregation (s)", alpha=0.9)
        axes[1].set_xlabel("round")
        axes[1].set_ylabel("seconds")
        axes[1].set_title("round timing")
        axes[1].legend(fontsize=8)
        axes[1].grid(alpha=0.3)
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return path


def main(argv: List[str]) -> int:
    plot_path = None
    if "--plot" in argv:
        i = argv.index("--plot")
        try:
            plot_path = argv[i + 1]
        except IndexError:
            print("--plot requires an output path", file=sys.stderr)
            return 2
        argv = argv[:i] + argv[i + 2:]
    if len(argv) != 1 or argv[0] in ("-h", "--help"):
        print("usage: python -m metisfl_tpu.stats <experiment.json> "
              "[--plot out.png]", file=sys.stderr)
        return 2
    try:
        with open(argv[0]) as fh:
            stats = json.load(fh)
    except (OSError, json.JSONDecodeError) as exc:
        print(f"cannot read {argv[0]}: {exc}", file=sys.stderr)
        return 1
    print(summarize(stats))
    if plot_path:
        try:
            print(f"plot written: {plot_convergence(stats, plot_path)}")
        except ImportError:
            print("matplotlib unavailable; no plot written",
                  file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
