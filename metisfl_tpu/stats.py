"""Experiment summary CLI: ``python -m metisfl_tpu.stats experiment.json``.

The reference ships convergence-plot helpers with its examples
(reference examples/analysis, driver_session.py:408-418 dumps the raw
lineage); this is the rebuild's text equivalent — a round-by-round table
(wall-clock, cohort, aggregation time, model size) and per-metric
convergence summaries from the ``experiment.json`` a driver writes, with no
plotting dependencies. Usable as a library via :func:`summarize`.
"""

from __future__ import annotations

import json
import sys
from statistics import median
from typing import Any, Dict, List


def _fmt_ms(ms: float) -> str:
    return f"{ms / 1e3:.2f}s" if ms >= 1e3 else f"{ms:.1f}ms"


def summarize(stats: Dict[str, Any]) -> str:
    """Human-readable summary of a ``get_statistics()`` / experiment.json
    payload; returns the text (the CLI prints it)."""
    lines: List[str] = []
    rounds = stats.get("round_metadata", [])
    lines.append(
        f"federation: {stats.get('global_iteration', len(rounds))} rounds, "
        f"{len(stats.get('learners', []))} learners registered")

    if rounds:
        lines.append("")
        lines.append(f"{'round':>5} {'wall':>8} {'cohort':>6} {'agg':>8} "
                     f"{'params':>10} {'uplink':>9} {'errors':>6}")
        for meta in rounds:
            wall_ms = 1e3 * max(
                0.0, meta.get("completed_at", 0) - meta.get("started_at", 0))
            up = sum(meta.get("uplink_bytes", {}).values())
            up_s = (f"{up / 1e6:.1f}MB" if up >= 1e6
                    else f"{up / 1e3:.0f}KB" if up >= 1e3
                    else f"{up}B" if up else "-")
            lines.append(
                f"{meta.get('global_iteration', '?'):>5} "
                f"{_fmt_ms(wall_ms):>8} "
                f"{len(meta.get('selected_learners', [])):>6} "
                f"{_fmt_ms(meta.get('aggregation_duration_ms', 0.0)):>8} "
                f"{meta.get('model_size', {}).get('values', 0):>10} "
                f"{up_s:>9} "
                f"{len(meta.get('errors', [])):>6}")
        # clamped like the table rows, so both views agree on skewed clocks
        walls = [1e3 * max(0.0, m.get("completed_at", 0)
                           - m.get("started_at", 0))
                 for m in rounds if m.get("completed_at")]
        aggs = [m.get("aggregation_duration_ms", 0.0) for m in rounds]
        if walls:
            lines.append(
                f"round wall-clock: median {_fmt_ms(median(walls))}, "
                f"max {_fmt_ms(max(walls))}; aggregation median "
                f"{_fmt_ms(median(aggs))}")
        errors = [e for m in rounds for e in m.get("errors", [])]
        if errors:
            lines.append(f"round errors ({len(errors)}):")
            lines.extend(f"  - {e}" for e in errors[:10])

    evals = [e for e in stats.get("community_evaluations", [])
             if e.get("evaluations")]
    if evals:
        # metric → per-round mean across learners and datasets
        series: Dict[str, List[float]] = {}
        for entry in evals:
            per_metric: Dict[str, List[float]] = {}
            for learner_metrics in entry["evaluations"].values():
                for dataset, metrics in learner_metrics.items():
                    for name, value in metrics.items():
                        try:
                            per_metric.setdefault(
                                f"{dataset}/{name}", []).append(float(value))
                        except (TypeError, ValueError):
                            continue
            for key, values in per_metric.items():
                series.setdefault(key, []).append(
                    sum(values) / len(values))
        lines.append("")
        lines.append("community-model evaluations (mean across learners):")
        for key in sorted(series):
            vals = series[key]
            # "best" follows the metric's direction: loss/error-like
            # metrics improve downward, everything else upward
            lower_better = any(tag in key.lower()
                               for tag in ("loss", "error", "mse", "mae"))
            best = min(vals) if lower_better else max(vals)
            lines.append(
                f"  {key}: first={vals[0]:.4f} best={best:.4f} "
                f"last={vals[-1]:.4f} over {len(vals)} evaluated rounds")
    return "\n".join(lines)


def main(argv: List[str]) -> int:
    if len(argv) != 1 or argv[0] in ("-h", "--help"):
        print("usage: python -m metisfl_tpu.stats <experiment.json>",
              file=sys.stderr)
        return 2
    try:
        with open(argv[0]) as fh:
            stats = json.load(fh)
    except (OSError, json.JSONDecodeError) as exc:
        print(f"cannot read {argv[0]}: {exc}", file=sys.stderr)
        return 1
    print(summarize(stats))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
