"""Versioned community-model registry (model lifecycle plane).

Every successful aggregation mints a candidate version; eval-gated
promotion moves it to the ``stable`` channel; the serving gateway
(:mod:`metisfl_tpu.serving`) hot-swaps onto promoted versions. See
docs/DEPLOYMENT.md for the schema, gate semantics, and the rollback
runbook.
"""

from metisfl_tpu.registry.registry import (
    CHANNEL_CANDIDATE,
    CHANNEL_STABLE,
    ModelRegistry,
    VersionInfo,
)

__all__ = [
    "ModelRegistry",
    "VersionInfo",
    "CHANNEL_CANDIDATE",
    "CHANNEL_STABLE",
]
