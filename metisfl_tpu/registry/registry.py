"""Versioned community-model registry with eval-gated channel promotion.

The paper's pipeline ends at aggregation: the community model is produced,
checkpointed, and nothing consumes it. This module turns every aggregated
round into a *versioned, promotable, servable artifact*:

- :meth:`ModelRegistry.register` mints a monotonic version id for a round's
  community blob, recording round, parent version, config hash, and the
  round's learning-health snapshot; the blob itself persists through the
  existing store layer (one lineage slot per version id).
- Channels are named heads: a fresh version enters ``candidate``;
  :meth:`promote` moves it to ``stable``. Promotion is gated
  (:meth:`evaluate_gate`): eval-metric threshold vs the current stable,
  no anomalous updates in the source round, and a bounded divergence-score
  quantile from the health plane. With ``promotion.auto`` the gate runs
  whenever a candidate's eval metrics arrive (:meth:`note_eval`).
- :meth:`rollback` restores the previous stable head (the runbook's one
  command); :meth:`gc` retires and erases versions beyond ``retention``
  and prunes their per-version gauge series (bounded exposition
  cardinality, the PR-4 learner-series posture).

Thread-safety: one lock over the metadata maps; blob bytes live in the
store (which has its own lock). The whole state round-trips through
:meth:`export_state`/:meth:`restore_state` so lineage survives controller
``--resume`` failover inside the controller checkpoint.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from metisfl_tpu import telemetry as _tel
from metisfl_tpu.store import make_store
from metisfl_tpu.telemetry import events as _tevents
from metisfl_tpu.telemetry import metrics as _tmetrics

logger = logging.getLogger("metisfl_tpu.registry")

CHANNEL_CANDIDATE = "candidate"
CHANNEL_STABLE = "stable"

_REG = _tmetrics.registry()
_M_VERSIONS = _REG.counter(
    _tel.M_REGISTRY_VERSIONS_TOTAL, "Model versions registered")
_M_STATE = _REG.gauge(
    _tel.M_REGISTRY_VERSION_STATE,
    "Per-version lifecycle state (2 = stable head, 1 = candidate head, "
    "0 = retained, series removed at GC)", ("version",))
_M_PROMOTIONS = _REG.counter(
    _tel.M_REGISTRY_PROMOTIONS_TOTAL, "Versions promoted to stable")
_M_ROLLBACKS = _REG.counter(
    _tel.M_REGISTRY_ROLLBACKS_TOTAL, "Stable-channel rollbacks")

# metric keys whose value improves downward (matches stats.py's direction
# heuristic so the gate and the summary table never disagree)
_LOWER_BETTER_TAGS = ("loss", "error", "mse", "mae")


def _lower_better(metric_key: str) -> bool:
    return any(tag in metric_key.lower() for tag in _LOWER_BETTER_TAGS)


@dataclass
class VersionInfo:
    """One registered community-model version (metadata only — the blob
    lives in the store under ``v<version>``)."""

    version: int
    round: int = 0
    parent: int = 0                  # 0 = no parent (first version)
    config_hash: str = ""
    created_at: float = 0.0
    channel: str = ""                # candidate | stable | "" (retained)
    # the source round's RoundMetadata.health snapshot at registration
    health: Dict[str, Any] = field(default_factory=dict)
    # folded community evaluation, {"<dataset>/<metric>": mean-across-
    # learners}; empty until the round's eval tasks report back
    eval_metrics: Dict[str, float] = field(default_factory=dict)
    # last gate decision for operators: {"passed": bool, "reasons": [...]}
    gate: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return asdict(self)


class ModelRegistry:
    """See module docstring. ``config`` is a
    :class:`metisfl_tpu.config.RegistryConfig`."""

    def __init__(self, config, config_hash: str = "", store=None):
        self.config = config
        self.config_hash = config_hash
        self._lock = threading.RLock()
        self._versions: Dict[int, VersionInfo] = {}
        self._next_version = 1
        self._heads: Dict[str, int] = {}     # channel -> version id
        self._previous_stable = 0            # rollback target
        # blob bytes ride the existing store layer: one "learner" id per
        # version, lineage length 1 (a version's bytes never change)
        self._store = store if store is not None else make_store(
            "in_memory", lineage_length=1)

    # ------------------------------------------------------------------ #
    # registration
    # ------------------------------------------------------------------ #

    def register(self, round_id: int, blob: bytes,
                 health: Optional[Dict[str, Any]] = None) -> VersionInfo:
        """Mint a candidate version for an aggregated round's community
        blob. The parent is whatever the stable head was when the version
        was created (the model it will be judged against)."""
        with self._lock:
            version = self._next_version
            self._next_version += 1
            info = VersionInfo(
                version=version,
                round=int(round_id),
                parent=self._heads.get(CHANNEL_STABLE, 0),
                config_hash=self.config_hash,
                created_at=round(time.time(), 6),
                channel=CHANNEL_CANDIDATE,
                health=dict(health or {}),
            )
            self._versions[version] = info
            previous_candidate = self._heads.get(CHANNEL_CANDIDATE, 0)
            self._heads[CHANNEL_CANDIDATE] = version
            if previous_candidate and previous_candidate in self._versions:
                # superseded, never promoted: plain retained version now
                self._versions[previous_candidate].channel = ""
        self._store.insert(self._blob_key(version), bytes(blob))
        _M_VERSIONS.inc()
        self._refresh_state_gauges()
        _tevents.emit(_tevents.VersionRegistered, version=version,
                      round=int(round_id), parent=info.parent)
        logger.info("registered model version v%d (round %d, parent v%d)",
                    version, round_id, info.parent)
        self.gc()
        return info

    def note_eval(self, round_id: int, metrics: Dict[str, float],
                  gate: bool = True) -> Optional[VersionInfo]:
        """Fold a round's community evaluation into the version registered
        from that round (metrics keys: ``"<dataset>/<metric>"``). Under
        ``promotion.auto`` (and ``gate=True`` — the controller passes
        False while the cohort's digests are still partial, so a single
        fast learner's mean never tips a promotion) the gate re-runs —
        returns the promoted VersionInfo when this fold tipped a
        candidate to stable, else None. Idempotent per arriving digest:
        later results refresh the fold and re-evaluate."""
        with self._lock:
            # latest version for the round: a --resume failover re-runs
            # the abandoned round number, so two versions may share it
            matches = [v for v in self._versions.values()
                       if v.round == int(round_id)]
            info = max(matches, key=lambda v: v.version, default=None)
            if info is None:
                return None
            info.eval_metrics = {k: float(v) for k, v in metrics.items()}
            is_candidate = self._heads.get(CHANNEL_CANDIDATE) == info.version
        if not (gate and self.config.promotion.auto and is_candidate):
            return None
        passed, reasons = self.evaluate_gate(info.version)
        if passed:
            return self.promote(info.version)
        with self._lock:
            info.gate = {"passed": False, "reasons": reasons}
        return None

    # ------------------------------------------------------------------ #
    # promotion gate
    # ------------------------------------------------------------------ #

    def evaluate_gate(self, version: int) -> Tuple[bool, List[str]]:
        """Run the configured promotion rules for ``version`` against the
        current stable head. Returns (passed, failure reasons)."""
        p = self.config.promotion
        with self._lock:
            info = self._versions.get(version)
            stable = self._versions.get(self._heads.get(CHANNEL_STABLE, 0))
        if info is None:
            return False, [f"unknown version v{version}"]
        reasons: List[str] = []
        if p.require_eval and not info.eval_metrics:
            reasons.append("no eval metrics reported yet")
        if p.forbid_anomalies and info.health.get("anomalous"):
            reasons.append(
                "source round flagged anomalous updates: "
                f"{sorted(info.health['anomalous'])}")
        if p.max_divergence > 0.0:
            scores = sorted(
                float(s) for s in
                (info.health.get("divergence_score") or {}).values())
            if scores:
                # nearest-rank quantile: ceil(q*n)-1, not int(q*n) — the
                # latter evaluates p100 for q=0.9 at n=10
                import math

                idx = min(len(scores) - 1,
                          max(0, math.ceil(
                              p.divergence_quantile * len(scores)) - 1))
                q = scores[idx]
                if q > p.max_divergence:
                    reasons.append(
                        f"divergence p{int(p.divergence_quantile * 100)}"
                        f"={q:.3f} > {p.max_divergence:.3f}")
        if p.metric and stable is not None:
            mine = info.eval_metrics.get(p.metric)
            theirs = stable.eval_metrics.get(p.metric)
            if mine is None and info.eval_metrics:
                reasons.append(f"candidate lacks gate metric {p.metric!r}")
            elif mine is not None and theirs is None:
                # the stable head never reported the gate metric (e.g. a
                # force-promote before its eval landed): refusing beats a
                # vacuous pass that would let a regressing candidate
                # auto-promote unchecked — operators can still force
                reasons.append(
                    f"stable v{stable.version} lacks gate metric "
                    f"{p.metric!r}; comparison impossible (force to "
                    "override)")
            elif mine is not None and theirs is not None:
                improvement = (theirs - mine if _lower_better(p.metric)
                               else mine - theirs)
                if improvement < p.min_delta:
                    reasons.append(
                        f"{p.metric} {mine:.4f} vs stable {theirs:.4f} "
                        f"(needs delta >= {p.min_delta})")
        return not reasons, reasons

    def promote(self, version: int, force: bool = False) -> VersionInfo:
        """Move ``version`` to the stable channel. ``force`` bypasses the
        gate (operator override); otherwise a failing gate raises so the
        RPC surface reports the reasons instead of silently promoting."""
        if not force:
            passed, reasons = self.evaluate_gate(version)
            if not passed:
                with self._lock:
                    info = self._versions.get(version)
                    if info is not None:
                        info.gate = {"passed": False, "reasons": reasons}
                raise ValueError(
                    f"promotion gate rejected v{version}: "
                    + "; ".join(reasons))
        with self._lock:
            info = self._versions.get(version)
            if info is None:
                raise ValueError(f"unknown version v{version}")
            previous = self._heads.get(CHANNEL_STABLE, 0)
            if previous == version:
                return info
            self._previous_stable = previous
            if previous and previous in self._versions:
                self._versions[previous].channel = ""
            self._heads[CHANNEL_STABLE] = version
            if self._heads.get(CHANNEL_CANDIDATE) == version:
                del self._heads[CHANNEL_CANDIDATE]
            info.channel = CHANNEL_STABLE
            info.gate = {"passed": True, "reasons": [],
                         "forced": bool(force)}
            round_id = info.round
        _M_PROMOTIONS.inc()
        self._refresh_state_gauges()
        _tevents.emit(_tevents.VersionPromoted, version=version,
                      round=round_id, previous_stable=previous,
                      forced=bool(force))
        logger.info("promoted model version v%d to stable (was v%d)",
                    version, previous)
        self.gc()
        return info

    def rollback(self) -> Optional[VersionInfo]:
        """Restore the previous stable head (one level — the runbook's
        emergency lever, docs/DEPLOYMENT.md). Returns the restored
        VersionInfo, or None when there is nothing to roll back to."""
        with self._lock:
            target = self._previous_stable
            current = self._heads.get(CHANNEL_STABLE, 0)
            info = self._versions.get(target)
            if not target or info is None or target == current:
                return None
            if current and current in self._versions:
                self._versions[current].channel = ""
            self._heads[CHANNEL_STABLE] = target
            self._previous_stable = 0  # one level: no rollback ping-pong
            info.channel = CHANNEL_STABLE
        _M_ROLLBACKS.inc()
        self._refresh_state_gauges()
        _tevents.emit(_tevents.VersionRolledBack, version=target,
                      rolled_back_from=current)
        logger.warning("rolled stable back to v%d (was v%d)", target,
                       current)
        return info

    # ------------------------------------------------------------------ #
    # retention GC
    # ------------------------------------------------------------------ #

    def gc(self) -> List[int]:
        """Erase versions beyond ``retention``, never a channel head or
        the rollback target. Blobs leave the store and the per-version
        gauge series is pruned (bounded exposition cardinality)."""
        with self._lock:
            protected = set(self._heads.values()) | {self._previous_stable}
            retire = [
                v for v in sorted(self._versions)
                if v not in protected
            ][:-self.config.retention or None]
            if len(self._versions) - len(retire) < 1:
                retire = []
            for v in retire:
                del self._versions[v]
        for v in retire:
            self._store.erase([self._blob_key(v)])
            _M_STATE.remove(version=f"v{v}")
            logger.info("registry GC retired model version v%d", v)
        return retire

    # ------------------------------------------------------------------ #
    # lookup
    # ------------------------------------------------------------------ #

    def _blob_key(self, version: int) -> str:
        return f"v{version}"

    def head(self, channel: str) -> Optional[VersionInfo]:
        with self._lock:
            return self._versions.get(self._heads.get(channel, 0))

    def info(self, version: int) -> Optional[VersionInfo]:
        with self._lock:
            return self._versions.get(version)

    def blob(self, version: int) -> Optional[bytes]:
        picked = self._store.select([self._blob_key(version)], k=1)
        lineage = picked.get(self._blob_key(version))
        return lineage[0] if lineage else None

    def versions(self) -> List[VersionInfo]:
        with self._lock:
            return [self._versions[v] for v in sorted(self._versions)]

    def describe(self) -> Dict[str, Any]:
        """Registry snapshot for DescribeFederation / DescribeRegistry /
        the status CLI: channel heads + full retained lineage."""
        with self._lock:
            return {
                "enabled": True,
                "stable": self._heads.get(CHANNEL_STABLE, 0),
                "candidate": self._heads.get(CHANNEL_CANDIDATE, 0),
                "previous_stable": self._previous_stable,
                "next_version": self._next_version,
                "versions": [self._versions[v].to_dict()
                             for v in sorted(self._versions)],
            }

    # ------------------------------------------------------------------ #
    # checkpoint persistence
    # ------------------------------------------------------------------ #

    def export_state(self) -> Dict[str, Any]:
        """Full metadata lineage, but blobs ONLY for the servable set
        (channel heads + the rollback target): the checkpoint runs every
        round AND on every join, so shipping all ``retention`` blobs
        would multiply its write cost for versions nothing can serve.
        A restored retained-but-headless version keeps its metadata;
        promoting it again requires re-registration (by design)."""
        with self._lock:
            versions = [self._versions[v].to_dict()
                        for v in sorted(self._versions)]
            heads = dict(self._heads)
            protected = sorted(
                {v for v in list(heads.values()) + [self._previous_stable]
                 if v})
            state = {
                "next_version": self._next_version,
                "previous_stable": self._previous_stable,
                "heads": heads,
                "versions": versions,
            }
        state["blobs"] = {str(v): self.blob(v) or b"" for v in protected}
        return state

    def restore_state(self, state: Dict[str, Any]) -> None:
        with self._lock:
            self._next_version = int(state.get("next_version", 1))
            self._previous_stable = int(state.get("previous_stable", 0))
            self._heads = {str(k): int(v)
                           for k, v in (state.get("heads") or {}).items()}
            self._versions = {}
            for entry in state.get("versions", []):
                info = VersionInfo(**entry)
                self._versions[info.version] = info
        for key, blob in (state.get("blobs") or {}).items():
            if blob:
                self._store.insert(self._blob_key(int(key)), bytes(blob))
        self._refresh_state_gauges()
        logger.info("restored registry: %d version(s), stable=v%d, "
                    "candidate=v%d", len(self._versions),
                    self._heads.get(CHANNEL_STABLE, 0),
                    self._heads.get(CHANNEL_CANDIDATE, 0))

    def _refresh_state_gauges(self) -> None:
        with self._lock:
            stable = self._heads.get(CHANNEL_STABLE, 0)
            candidate = self._heads.get(CHANNEL_CANDIDATE, 0)
            versions = list(self._versions)
        for v in versions:
            _M_STATE.set(2 if v == stable else 1 if v == candidate else 0,
                         version=f"v{v}")

    def shutdown(self) -> None:
        self._store.shutdown()
