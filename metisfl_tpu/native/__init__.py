"""Native (C++) components, built on demand with the system toolchain.

The reference builds its native layer with Bazel + pybind11
(reference WORKSPACE:1-120, controller/pybind/controller_pybind.cc:17-50);
this rebuild compiles a small C-ABI shared library with ``g++`` on first use
(pybind11 is not available here — Python binds via ctypes) and caches the
``.so`` next to the source. Concurrent builders (learner subprocesses) race
safely: the compile goes to a unique temp file then ``os.replace``s into
place atomically.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "ckks.cc")
_SO = os.path.join(_DIR, "libmetisfl_ckks.so")
_HASH = _SO + ".srchash"
_lock = threading.Lock()
_lib = None


def _src_hash() -> str:
    with open(_SRC, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def _needs_build() -> bool:
    """The binary is never committed — it is identified by the sha256 of the
    source it was built from (mtimes are meaningless after a fresh clone)."""
    if not os.path.exists(_SO) or not os.path.exists(_HASH):
        return True
    try:
        with open(_HASH) as f:
            return f.read().strip() != _src_hash()
    except OSError:
        return True


def _build() -> None:
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
    os.close(fd)
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-fopenmp",
           "-o", tmp, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, _SO)
        fd, tmp_hash = tempfile.mkstemp(dir=_DIR)
        with os.fdopen(fd, "w") as f:
            f.write(_src_hash())
        os.replace(tmp_hash, _HASH)
    except subprocess.CalledProcessError as exc:
        raise RuntimeError(
            f"native CKKS build failed:\n{exc.stderr}") from exc
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load_ckks() -> ctypes.CDLL:
    """Build (if stale) and load the CKKS library with typed signatures."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if _needs_build():
            _build()
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            # stale/foreign-platform binary (e.g. copied checkout):
            # rebuild from source once and retry
            _build()
            lib = ctypes.CDLL(_SO)
        lib.ckks_n.restype = ctypes.c_long
        lib.ckks_ciphertext_size.restype = ctypes.c_long
        lib.ckks_ciphertext_size.argtypes = [ctypes.c_long]
        lib.ckks_keygen.restype = ctypes.c_int
        lib.ckks_keygen.argtypes = [ctypes.c_char_p]
        lib.ckks_open.restype = ctypes.c_void_p
        lib.ckks_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.ckks_close.argtypes = [ctypes.c_void_p]
        lib.ckks_has_secret.restype = ctypes.c_int
        lib.ckks_has_secret.argtypes = [ctypes.c_void_p]
        lib.ckks_encrypt.restype = ctypes.c_long
        lib.ckks_encrypt.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_double), ctypes.c_long,
            ctypes.POINTER(ctypes.c_ubyte), ctypes.c_long]
        lib.ckks_weighted_sum.restype = ctypes.c_long
        lib.ckks_weighted_sum.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_double), ctypes.c_long,
            ctypes.POINTER(ctypes.c_ubyte), ctypes.c_long]
        lib.ckks_decrypt.restype = ctypes.c_long
        lib.ckks_decrypt.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_ubyte), ctypes.c_long,
            ctypes.POINTER(ctypes.c_double), ctypes.c_long]
        lib.ckks_selftest.restype = ctypes.c_int
        _lib = lib
        return _lib
