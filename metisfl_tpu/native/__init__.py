"""Native (C++) components, built on demand with the system toolchain.

The reference builds its native layer with Bazel + pybind11
(reference WORKSPACE:1-120, controller/pybind/controller_pybind.cc:17-50);
this rebuild compiles small C-ABI shared libraries with ``g++`` on first use
(pybind11 is not available here — Python binds via ctypes) and caches each
``.so`` next to its source, keyed by the sha256 of that source (mtimes are
meaningless after a fresh clone; binaries are never committed). Concurrent
builders (learner subprocesses) race safely: the compile goes to a unique
temp file then ``os.replace``s into place atomically.

Libraries:
- ``ckks.cc``     — coefficient-packed RLWE CKKS (secure aggregation).
- ``hostfold.cc`` — streaming weighted fold for host-path aggregation.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_lock = threading.Lock()
_libs: dict = {}


_BUILD_FLAGS = ["-O3", "-std=c++17", "-march=native", "-shared", "-fPIC",
                "-fopenmp"]


def _host_cpu_id() -> str:
    """CPU feature identity of THIS host. With -march=native in the flags,
    a .so built elsewhere (image build host, rsynced tree) may use
    instructions this CPU lacks — reusing it would SIGILL in the modular
    hot loops. The feature-flags line identifies compatible hosts."""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    return hashlib.sha256(line.encode()).hexdigest()[:16]
    except OSError:
        pass
    import platform

    return platform.machine()


def _cache_key(src: str) -> str:
    """Source + build flags + host CPU identity: any of the three changing
    invalidates the cached binary."""
    h = hashlib.sha256()
    with open(src, "rb") as f:
        h.update(f.read())
    h.update(" ".join(_BUILD_FLAGS).encode())
    h.update(_host_cpu_id().encode())
    return h.hexdigest()


def _needs_build(src: str, so: str) -> bool:
    hash_path = so + ".srchash"
    if not os.path.exists(so) or not os.path.exists(hash_path):
        return True
    try:
        with open(hash_path) as f:
            return f.read().strip() != _cache_key(src)
    except OSError:
        return True


def _build(src: str, so: str) -> None:
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_DIR)
    os.close(fd)
    # -march=native unlocks mulx/BMI2 for the modular-arithmetic hot loops;
    # it is safe because the cache key embeds the host CPU identity
    # (_cache_key) so a binary never outlives the CPU family it targets.
    # Retried without it for toolchains that reject the flag.
    cmd = ["g++", *_BUILD_FLAGS, "-o", tmp, src]
    try:
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except subprocess.CalledProcessError:
            cmd = [arg for arg in cmd if arg != "-march=native"]
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, so)
        fd, tmp_hash = tempfile.mkstemp(dir=_DIR)
        with os.fdopen(fd, "w") as f:
            f.write(_cache_key(src))
        os.replace(tmp_hash, so + ".srchash")
    except subprocess.CalledProcessError as exc:
        raise RuntimeError(
            f"native build of {os.path.basename(src)} failed:\n"
            f"{exc.stderr}") from exc
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _load(name: str) -> ctypes.CDLL:
    """Build (if stale) and dlopen ``<name>.cc`` → ``libmetisfl_<name>.so``.
    Call with ``_lock`` held."""
    src = os.path.join(_DIR, f"{name}.cc")
    so = os.path.join(_DIR, f"libmetisfl_{name}.so")
    if _needs_build(src, so):
        _build(src, so)
    try:
        return ctypes.CDLL(so)
    except OSError:
        # stale/foreign-platform binary (e.g. copied checkout):
        # rebuild from source once and retry
        _build(src, so)
        return ctypes.CDLL(so)


def load_ckks() -> ctypes.CDLL:
    """The CKKS library with typed signatures."""
    with _lock:
        if "ckks" in _libs:
            return _libs["ckks"]
        lib = _load("ckks")
        lib.ckks_n.restype = ctypes.c_long
        lib.ckks_ciphertext_size.restype = ctypes.c_long
        lib.ckks_ciphertext_size.argtypes = [ctypes.c_long]
        lib.ckks_keygen.restype = ctypes.c_int
        lib.ckks_keygen.argtypes = [ctypes.c_char_p]
        lib.ckks_open.restype = ctypes.c_void_p
        lib.ckks_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.ckks_close.argtypes = [ctypes.c_void_p]
        lib.ckks_has_secret.restype = ctypes.c_int
        lib.ckks_has_secret.argtypes = [ctypes.c_void_p]
        lib.ckks_encrypt.restype = ctypes.c_long
        lib.ckks_encrypt.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_double), ctypes.c_long,
            ctypes.POINTER(ctypes.c_ubyte), ctypes.c_long]
        lib.ckks_weighted_sum.restype = ctypes.c_long
        lib.ckks_weighted_sum.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_double), ctypes.c_long,
            ctypes.POINTER(ctypes.c_ubyte), ctypes.c_long]
        lib.ckks_decrypt.restype = ctypes.c_long
        lib.ckks_decrypt.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_ubyte), ctypes.c_long,
            ctypes.POINTER(ctypes.c_double), ctypes.c_long]
        lib.ckks_selftest.restype = ctypes.c_int
        _libs["ckks"] = lib
        return lib


def load_hostfold() -> ctypes.CDLL:
    """The host-aggregation fold library with typed signatures."""
    with _lock:
        if "hostfold" in _libs:
            return _libs["hostfold"]
        lib = _load("hostfold")
        lib.hostfold_f32.restype = None
        lib.hostfold_f32.argtypes = [
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_long, ctypes.c_long, ctypes.c_int]
        lib.hostfold_f64.restype = None
        lib.hostfold_f64.argtypes = [
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_long, ctypes.c_long, ctypes.c_int]
        lib.hostfold_selftest.restype = ctypes.c_int
        _libs["hostfold"] = lib
        return lib
