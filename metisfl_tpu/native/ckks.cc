// Coefficient-packed CKKS-style RLWE homomorphic encryption (C++17).
//
// TPU-native redesign of the reference's Palisade CKKS scheme
// (reference metisfl/encryption/palisade/ckks_scheme.cc:13-252,
// he_scheme.h:20-42). The reference's aggregation path uses exactly two
// homomorphic ops — EvalMult by a plaintext scalar and EvalAdd
// (private_weighted_average.cc:22-111) — so this implementation packs
// values into polynomial *coefficients* instead of canonical-embedding
// slots: both required ops are coefficient-wise, no rotation/relinearization
// keys are needed, every ciphertext packs N (not N/2) values, and the
// ciphertext expansion is 2 u64 per value (~16x denser than the reference's
// observed ~100 MB CIFAR models, controller.cc:594-604). Security is
// standard RLWE (the encoding does not affect hardness): ring Z_q[X]/(X^N+1),
// N = 8192, log2 q ≈ 59, ternary secret, centered-binomial noise (sigma ~ 3.2),
// ChaCha20 CSPRNG keyed from the OS entropy pool. Parameter justification
// (HE-standard table comparison: log2 q is ~half the 256-bit classical
// ceiling at N=8192/ternary) and the full noise-budget derivation live in
// docs/SECURITY.md; tests/test_ckks.py::test_noise_budget_at_max_scalar_scale
// checks the worst-case bound.
//
// Weighted average: ct_out = sum_i round(2^S_BITS * s_i) * ct_i  (mod q).
// Fresh ciphertexts carry plaintext scale 2^V_BITS; the sum carries
// 2^(V_BITS+S_BITS); decrypt divides by the scale in the payload header.
//
// C ABI at the bottom; Python binds via ctypes (pybind11 is not available
// in this environment).

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <random>
#include <string>
#include <vector>

namespace {

constexpr int LOGN = 13;
constexpr int N = 1 << LOGN;                     // 8192 coefficients/values
constexpr uint64_t Q = 576460752303439873ULL;    // prime, Q ≡ 1 (mod 2N), 2^59+2^14+1
constexpr uint64_t PSI = 572686754113469876ULL;  // primitive 2N-th root of unity
constexpr uint64_t PSI_INV = 509288606595595249ULL;
constexpr uint64_t N_INV = 576390383559262207ULL;

constexpr int V_BITS = 32;  // fresh-ciphertext plaintext scale 2^32
constexpr int S_BITS = 20;  // scalar scale in weighted sums (quantization ~1e-6)

constexpr uint32_t MAGIC = 0x31544b43u;  // "CKT1"

inline uint64_t addmod(uint64_t a, uint64_t b) {
  uint64_t r = a + b;
  return r >= Q ? r - Q : r;
}
inline uint64_t submod(uint64_t a, uint64_t b) {
  return a >= b ? a - b : a + Q - b;
}
inline uint64_t mulmod(uint64_t a, uint64_t b) {
  return (uint64_t)((unsigned __int128)a * b % Q);
}

// Shoup modular multiplication: for a fixed factor w < Q, precompute
// w' = floor(w * 2^64 / Q); then a*w mod Q costs two 64x64 multiplies and
// one conditional subtract instead of a 128-bit division (~8x faster —
// this is the NTT hot path; the same precomputed-quotient trick every
// lattice library uses). Correct for ANY a < 2^64: the estimated quotient
// q is off by at most 1, so r = a*w - q*Q lands in [0, 2Q).
inline uint64_t shoup_of(uint64_t w) {
  return (uint64_t)(((unsigned __int128)w << 64) / Q);
}
inline uint64_t mulmod_shoup(uint64_t a, uint64_t w, uint64_t w_shoup) {
  uint64_t q = (uint64_t)(((unsigned __int128)a * w_shoup) >> 64);
  uint64_t r = a * w - q * Q;
  return r >= Q ? r - Q : r;
}

// any 64-bit word -> [0, Q) without a division (Shoup multiply by 1);
// used to sanitize untrusted ciphertext words before addmod/submod
inline uint64_t reduce64(uint64_t a) {
  static const uint64_t ONE_SH = shoup_of(1);
  return mulmod_shoup(a, 1, ONE_SH);
}

// ---------------------------------------------------------------------- //
// negacyclic NTT (iterative CT/GS with merged psi powers)
// ---------------------------------------------------------------------- //

struct Tables {
  uint64_t psi_rev[N];            // psi^brv(i)
  uint64_t psi_inv_rev[N];        // psi^-brv(i)
  uint64_t psi_rev_sh[N];         // Shoup quotients of the above
  uint64_t psi_inv_rev_sh[N];
  uint64_t n_inv_sh;
  Tables() {
    uint64_t pow_psi[N], pow_psi_inv[N];
    pow_psi[0] = pow_psi_inv[0] = 1;
    for (int i = 1; i < N; i++) {
      pow_psi[i] = mulmod(pow_psi[i - 1], PSI);
      pow_psi_inv[i] = mulmod(pow_psi_inv[i - 1], PSI_INV);
    }
    for (int i = 0; i < N; i++) {
      uint32_t r = 0, x = (uint32_t)i;
      for (int b = 0; b < LOGN; b++) { r = (r << 1) | (x & 1); x >>= 1; }
      psi_rev[i] = pow_psi[r];
      psi_inv_rev[i] = pow_psi_inv[r];
      psi_rev_sh[i] = shoup_of(psi_rev[i]);
      psi_inv_rev_sh[i] = shoup_of(psi_inv_rev[i]);
    }
    n_inv_sh = shoup_of(N_INV);
  }
};
const Tables& tables() { static Tables t; return t; }

// Both transforms use Harvey-style lazy reduction: butterfly values live in
// [0, 4Q) (forward) / [0, 2Q) (inverse) — Q < 2^60 leaves headroom — and the
// per-butterfly conditional subtracts collapse into one final pass. The
// lazy Shoup product returns a value in [0, 2Q) for ANY 64-bit input.
inline uint64_t mulmod_shoup_lazy(uint64_t a, uint64_t w, uint64_t w_shoup) {
  uint64_t q = (uint64_t)(((unsigned __int128)a * w_shoup) >> 64);
  return a * w - q * Q;
}

constexpr uint64_t Q2 = 2 * Q;

void ntt(uint64_t* a) {  // inputs < Q, outputs < Q
  const Tables& T = tables();
  int t = N;
  for (int m = 1; m < N; m <<= 1) {
    t >>= 1;
    for (int i = 0; i < m; i++) {
      const uint64_t S = T.psi_rev[m + i];
      const uint64_t Ssh = T.psi_rev_sh[m + i];
      const int j1 = 2 * i * t;
      for (int j = j1; j < j1 + t; j++) {
        uint64_t U = a[j];                                 // < 4Q
        if (U >= Q2) U -= Q2;                              // < 2Q
        const uint64_t V = mulmod_shoup_lazy(a[j + t], S, Ssh);  // < 2Q
        a[j] = U + V;                                      // < 4Q
        a[j + t] = U + Q2 - V;                             // < 4Q
      }
    }
  }
  for (int j = 0; j < N; j++) {
    uint64_t v = a[j];
    if (v >= Q2) v -= Q2;
    if (v >= Q) v -= Q;
    a[j] = v;
  }
}

void intt(uint64_t* a) {  // inputs < Q, outputs < Q
  const Tables& T = tables();
  int t = 1;
  for (int m = N; m > 1; m >>= 1) {
    const int h = m >> 1;
    int j1 = 0;
    for (int i = 0; i < h; i++) {
      const uint64_t S = T.psi_inv_rev[h + i];
      const uint64_t Ssh = T.psi_inv_rev_sh[h + i];
      for (int j = j1; j < j1 + t; j++) {
        const uint64_t U = a[j];                           // < 2Q
        const uint64_t V = a[j + t];                       // < 2Q
        const uint64_t s = U + V;                          // < 4Q
        a[j] = s >= Q2 ? s - Q2 : s;                       // < 2Q
        a[j + t] = mulmod_shoup_lazy(U + Q2 - V, S, Ssh);  // < 2Q
      }
      j1 += 2 * t;
    }
    t <<= 1;
  }
  // the strict Shoup product both scales by N^-1 and lands in [0, Q)
  for (int j = 0; j < N; j++) a[j] = mulmod_shoup(a[j], N_INV, T.n_inv_sh);
}

// ---------------------------------------------------------------------- //
// ChaCha20 CSPRNG (RFC 8439 block function), keyed from std::random_device
// ---------------------------------------------------------------------- //

struct ChaCha {
  uint32_t key[8];
  uint64_t counter = 0;
  uint8_t buf[64];
  int pos = 64;
  uint64_t tern_bits = 0;  // batched 2-bit pool for ternary()
  int tern_left = 0;

  explicit ChaCha() {
    std::random_device rd;  // /dev/urandom on Linux
    for (int i = 0; i < 8; i++) key[i] = (uint32_t)rd();
  }

  static inline uint32_t rotl(uint32_t x, int n) {
    return (x << n) | (x >> (32 - n));
  }
  static inline void qr(uint32_t& a, uint32_t& b, uint32_t& c, uint32_t& d) {
    a += b; d ^= a; d = rotl(d, 16);
    c += d; b ^= c; b = rotl(b, 12);
    a += b; d ^= a; d = rotl(d, 8);
    c += d; b ^= c; b = rotl(b, 7);
  }

  void block() {
    uint32_t s[16] = {0x61707865, 0x3320646e, 0x79622d32, 0x6b206574,
                      key[0], key[1], key[2], key[3],
                      key[4], key[5], key[6], key[7],
                      (uint32_t)counter, (uint32_t)(counter >> 32), 0, 0};
    uint32_t x[16];
    std::memcpy(x, s, sizeof(x));
    for (int r = 0; r < 10; r++) {
      qr(x[0], x[4], x[8], x[12]);  qr(x[1], x[5], x[9], x[13]);
      qr(x[2], x[6], x[10], x[14]); qr(x[3], x[7], x[11], x[15]);
      qr(x[0], x[5], x[10], x[15]); qr(x[1], x[6], x[11], x[12]);
      qr(x[2], x[7], x[8], x[13]);  qr(x[3], x[4], x[9], x[14]);
    }
    for (int i = 0; i < 16; i++) x[i] += s[i];
    std::memcpy(buf, x, 64);
    counter++;
    pos = 0;
  }

  uint64_t u64() {
    if (pos > 56) block();
    uint64_t v;
    std::memcpy(&v, buf + pos, 8);
    pos += 8;
    return v;
  }

  // uniform in [0, Q) by rejection
  uint64_t uniform_q() {
    constexpr uint64_t LIMIT = UINT64_MAX - (UINT64_MAX % Q);
    uint64_t v;
    do { v = u64(); } while (v >= LIMIT);
    return v % Q;
  }

  // uniform ternary {-1, 0, 1} as residues mod Q; draws 2-bit chunks from
  // a batched 64-bit pool (32 chunks per CSPRNG word instead of one)
  uint64_t ternary() {
    for (;;) {
      if (tern_left == 0) { tern_bits = u64(); tern_left = 32; }
      uint64_t v = tern_bits & 3;
      tern_bits >>= 2;
      tern_left--;
      if (v != 3) return v == 2 ? Q - 1 : v;  // 0, 1, or -1 mod Q
    }
  }

  // centered binomial with eta=21: sigma = sqrt(21/2) ~= 3.24
  uint64_t cbd() {
    uint64_t bits = u64();
    int a = __builtin_popcountll(bits & ((1ULL << 21) - 1));
    int b = __builtin_popcountll((bits >> 21) & ((1ULL << 21) - 1));
    int e = a - b;
    return e >= 0 ? (uint64_t)e : Q - (uint64_t)(-e);
  }
};

thread_local ChaCha g_rng;

// ---------------------------------------------------------------------- //
// keys and context
// ---------------------------------------------------------------------- //

struct Ctx {
  bool has_public = false;
  bool has_secret = false;
  std::vector<uint64_t> b_ntt;  // pk0 = -(a*s) + e, NTT domain
  std::vector<uint64_t> a_ntt;  // pk1, NTT domain
  std::vector<uint64_t> s_ntt;  // secret, NTT domain
  std::vector<uint64_t> b_sh;   // Shoup quotients for the pointwise products
  std::vector<uint64_t> a_sh;
  std::vector<uint64_t> s_sh;
};

std::vector<uint64_t> shoup_table(const std::vector<uint64_t>& w) {
  std::vector<uint64_t> sh(w.size());
  for (size_t i = 0; i < w.size(); i++) sh[i] = shoup_of(w[i]);
  return sh;
}

bool write_file(const std::string& path, const void* data, size_t size) {
  std::ofstream f(path, std::ios::binary | std::ios::trunc);
  if (!f) return false;
  f.write((const char*)data, (std::streamsize)size);
  return (bool)f;
}

bool read_file(const std::string& path, std::vector<uint64_t>& out, size_t n) {
  std::ifstream f(path, std::ios::binary);
  if (!f) return false;
  out.resize(n);
  f.read((char*)out.data(), (std::streamsize)(n * 8));
  return (bool)f;
}

// payload header
struct Header {
  uint32_t magic;
  uint32_t scale_bits;
  uint64_t n_values;
  uint32_t n_blocks;
  uint32_t reserved;
};
static_assert(sizeof(Header) == 24, "header layout");

inline long payload_size(long n_values) {
  long blocks = (n_values + N - 1) / N;
  return (long)sizeof(Header) + blocks * 2L * N * 8L;
}

}  // namespace

// ---------------------------------------------------------------------- //
// C ABI
// ---------------------------------------------------------------------- //

extern "C" {

long ckks_n() { return N; }

long ckks_ciphertext_size(long n_values) { return payload_size(n_values); }

// Generate (pk, sk) into dir/{pk.bin, sk.bin}. pk.bin = b||a (2N u64);
// sk.bin = s (N u64). Mirrors GenCryptoContextAndKeys writing key files
// (ckks_scheme.cc:13-75) minus the eval-mult key (not needed: no ct*ct).
int ckks_keygen(const char* dir) {
  std::vector<uint64_t> s(N), a(N), e(N), b(N);
  for (int i = 0; i < N; i++) s[i] = g_rng.ternary();
  for (int i = 0; i < N; i++) a[i] = g_rng.uniform_q();
  for (int i = 0; i < N; i++) e[i] = g_rng.cbd();

  std::vector<uint64_t> s_ntt(s), a_ntt(a);
  ntt(s_ntt.data());
  ntt(a_ntt.data());
  std::vector<uint64_t> as(N);
  for (int i = 0; i < N; i++) as[i] = mulmod(a_ntt[i], s_ntt[i]);
  intt(as.data());
  for (int i = 0; i < N; i++) b[i] = addmod(submod(0, as[i]), e[i]);

  std::string d(dir);
  std::vector<uint64_t> pk(2 * N);
  std::memcpy(pk.data(), b.data(), N * 8);
  std::memcpy(pk.data() + N, a.data(), N * 8);
  if (!write_file(d + "/pk.bin", pk.data(), 2 * N * 8)) return -1;
  if (!write_file(d + "/sk.bin", s.data(), N * 8)) return -2;
  return 0;
}

void* ckks_open(const char* dir, int load_secret) {
  auto* ctx = new Ctx();
  std::string d(dir);
  std::vector<uint64_t> pk;
  if (read_file(d + "/pk.bin", pk, 2 * N)) {
    ctx->b_ntt.assign(pk.begin(), pk.begin() + N);
    ctx->a_ntt.assign(pk.begin() + N, pk.end());
    ntt(ctx->b_ntt.data());
    ntt(ctx->a_ntt.data());
    ctx->b_sh = shoup_table(ctx->b_ntt);
    ctx->a_sh = shoup_table(ctx->a_ntt);
    ctx->has_public = true;
  }
  if (load_secret) {
    std::vector<uint64_t> s;
    if (read_file(d + "/sk.bin", s, N)) {
      ctx->s_ntt = s;
      ntt(ctx->s_ntt.data());
      ctx->s_sh = shoup_table(ctx->s_ntt);
      ctx->has_secret = true;
    }
  }
  if (!ctx->has_public && !(load_secret && ctx->has_secret)) {
    delete ctx;
    return nullptr;
  }
  return ctx;
}

void ckks_close(void* ctx) { delete (Ctx*)ctx; }

int ckks_has_secret(void* ctx) { return ((Ctx*)ctx)->has_secret ? 1 : 0; }

// Encrypt n doubles -> payload. Returns bytes written or <0 on error.
long ckks_encrypt(void* vctx, const double* vals, long n,
                  unsigned char* out, long cap) {
  auto* ctx = (Ctx*)vctx;
  if (!ctx->has_public) return -1;
  const long need = payload_size(n);
  if (cap < need) return -2;
  const long blocks = (n + N - 1) / N;

  Header h{MAGIC, V_BITS, (uint64_t)n, (uint32_t)blocks, 0};
  std::memcpy(out, &h, sizeof(h));
  uint64_t* body = (uint64_t*)(out + sizeof(Header));
  const double scale = (double)(1ULL << V_BITS);

  std::atomic<int> fail{0};
#pragma omp parallel for schedule(static)
  for (long blk = 0; blk < blocks; blk++) {
    uint64_t m[N], u[N], c[N];
    const long base = blk * N;
    for (int i = 0; i < N; i++) {
      double v = (base + i < n) ? vals[base + i] : 0.0;
      double sv = v * scale;
      // |v| <= 63 keeps sum_i round(2^S_BITS s_i) * m_i inside (-q/2, q/2)
      // for any convex weights, so every encryptable payload is safely
      // weighted-summable; model weights are orders of magnitude smaller
      if (sv > 63.0 * scale || sv < -63.0 * scale) { fail.store(1); sv = 0.0; }
      long long iv = (long long)(sv >= 0 ? sv + 0.5 : sv - 0.5);
      m[i] = iv >= 0 ? (uint64_t)iv % Q : Q - (uint64_t)(-iv) % Q;
    }
    for (int i = 0; i < N; i++) u[i] = g_rng.ternary();
    ntt(u);
    uint64_t* c0 = body + blk * 2 * N;
    uint64_t* c1 = c0 + N;
    for (int i = 0; i < N; i++)
      c[i] = mulmod_shoup(u[i], ctx->b_ntt[i], ctx->b_sh[i]);
    intt(c);
    for (int i = 0; i < N; i++)
      c0[i] = addmod(addmod(c[i], g_rng.cbd()), m[i]);
    for (int i = 0; i < N; i++)
      c[i] = mulmod_shoup(u[i], ctx->a_ntt[i], ctx->a_sh[i]);
    intt(c);
    for (int i = 0; i < N; i++) c1[i] = addmod(c[i], g_rng.cbd());
  }
  return fail.load() ? -3 : need;
}

// ct_out = sum_i round(2^S_BITS * scales[i]) * ct_i. Keyless.
long ckks_weighted_sum(const unsigned char* const* payloads, const long* sizes,
                       const double* scales, long k,
                       unsigned char* out, long cap) {
  if (k <= 0) return -1;
  Header h0;
  std::memcpy(&h0, payloads[0], sizeof(h0));
  if (h0.magic != MAGIC || h0.scale_bits != V_BITS) return -2;
  const long need = payload_size((long)h0.n_values);
  if (cap < need) return -3;
  for (long i = 0; i < k; i++) {
    Header hi;
    if (sizes[i] < (long)sizeof(Header)) return -4;
    std::memcpy(&hi, payloads[i], sizeof(hi));
    if (hi.magic != MAGIC || hi.n_values != h0.n_values ||
        hi.scale_bits != V_BITS || sizes[i] != need)
      return -4;
  }
  std::vector<uint64_t> fp(k), fp_sh(k);
  for (long i = 0; i < k; i++) {
    double s = scales[i] * (double)(1 << S_BITS);
    long long iv = (long long)(s >= 0 ? s + 0.5 : s - 0.5);
    fp[i] = iv >= 0 ? (uint64_t)iv % Q : Q - (uint64_t)(-iv) % Q;
    fp_sh[i] = shoup_of(fp[i]);
  }

  Header h{MAGIC, V_BITS + S_BITS, h0.n_values, h0.n_blocks, 0};
  std::memcpy(out, &h, sizeof(h));
  uint64_t* obody = (uint64_t*)(out + sizeof(Header));
  const long words = (long)h0.n_blocks * 2L * N;

#pragma omp parallel for schedule(static)
  for (long w = 0; w < words; w++) {
    uint64_t acc = 0;
    for (long i = 0; i < k; i++) {
      const uint64_t* body = (const uint64_t*)(payloads[i] + sizeof(Header));
      // mulmod_shoup reduces any 64-bit word mod Q — malformed (>= Q)
      // payload words stay correctly reduced
      acc = addmod(acc, mulmod_shoup(body[w], fp[i], fp_sh[i]));
    }
    obody[w] = acc;
  }
  return need;
}

// Decrypt payload -> n doubles. Divides by the header's plaintext scale.
long ckks_decrypt(void* vctx, const unsigned char* payload, long size,
                  double* out, long n) {
  auto* ctx = (Ctx*)vctx;
  if (!ctx->has_secret) return -1;
  if (size < (long)sizeof(Header)) return -2;
  Header h;
  std::memcpy(&h, payload, sizeof(h));
  if (h.magic != MAGIC) return -2;
  if ((long)h.n_values < n) return -3;
  if (size != payload_size((long)h.n_values)) return -2;
  // The header travels through the (honest-but-curious) aggregator; only
  // the two scales the protocol can legitimately produce are accepted —
  // a fresh ciphertext (2^V_BITS) or a weighted sum (2^(V_BITS+S_BITS)).
  // Anything else would let a malicious aggregator rescale the recovered
  // model undetected. (No MAC/freshness beyond this: the threat model is
  // the reference's honest-but-curious controller, he_scheme.h.)
  if (h.scale_bits != V_BITS && h.scale_bits != V_BITS + S_BITS) return -4;
  const double inv_scale = 1.0 / (double)(1ULL << h.scale_bits);
  const uint64_t* body = (const uint64_t*)(payload + sizeof(Header));
  const long blocks = h.n_blocks;

#pragma omp parallel for schedule(static)
  for (long blk = 0; blk < blocks; blk++) {
    const long base = blk * N;
    if (base >= n) continue;
    uint64_t t[N];
    const uint64_t* c0 = body + blk * 2 * N;
    const uint64_t* c1 = c0 + N;
    // untrusted payload words may be >= Q; sanitize into the ring first
    for (int i = 0; i < N; i++) t[i] = reduce64(c1[i]);
    ntt(t);
    for (int i = 0; i < N; i++)
      t[i] = mulmod_shoup(t[i], ctx->s_ntt[i], ctx->s_sh[i]);
    intt(t);
    for (int i = 0; i < N; i++) {
      if (base + i >= n) break;
      uint64_t m = addmod(reduce64(c0[i]), t[i]);
      // centered representative in (-q/2, q/2]
      double signed_m = (m > Q / 2) ? -(double)(Q - m) : (double)m;
      out[base + i] = signed_m * inv_scale;
    }
  }
  return n;
}

// NTT + encrypt/decrypt self-check without touching the filesystem.
// Returns 0 on success.
int ckks_selftest() {
  // NTT roundtrip
  std::vector<uint64_t> a(N), ref;
  for (int i = 0; i < N; i++) a[i] = g_rng.uniform_q();
  ref = a;
  ntt(a.data());
  intt(a.data());
  if (a != ref) return 1;
  // negacyclic convolution vs schoolbook on a sparse pair:
  // p = x^3 + 2, r = 5x^(N-1) + 7 -> p*r mod (x^N+1):
  //   35 x^2 (wrap of 5x^(N+2), negated twice? compute directly below)
  std::vector<uint64_t> p(N, 0), r(N, 0);
  p[3] = 1; p[0] = 2;
  r[N - 1] = 5; r[0] = 7;
  std::vector<uint64_t> want(N, 0);
  // (x^3 + 2)(5x^(N-1) + 7) = 5x^(N+2) + 7x^3 + 10x^(N-1) + 14
  // x^(N+2) = -x^2  ->  -5x^2
  want[2] = submod(0, 5);
  want[3] = 7;
  want[N - 1] = 10;
  want[0] = 14;
  ntt(p.data());
  ntt(r.data());
  for (int i = 0; i < N; i++) p[i] = mulmod(p[i], r[i]);
  intt(p.data());
  if (p != want) return 2;
  return 0;
}

}  // extern "C"
