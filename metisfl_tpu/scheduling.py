"""Round scheduling policies: synchronous, semi-synchronous, asynchronous,
and FedBuff-style buffered asynchronous.

Equivalent of the reference's ``Scheduler`` strategies
(reference metisfl/controller/scheduling/synchronous_scheduler.h:13-40,
asynchronous_scheduler.h:12-20) plus the semi-synchronous per-learner step
recomputation the reference keeps inside the controller
(controller.cc:520-569), extended for the cross-device regime: quorum
barriers (release at K reporters out of an over-provisioned dispatch) and
buffered asynchronous aggregation (Nguyen et al., AISTATS 2022). Pure
in-memory policy objects — no I/O.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Sequence, Set


class SynchronousScheduler:
    """Release the round cohort only when every dispatched learner reports.

    The barrier is the set of learners the controller actually dispatched
    train tasks to this round (``notify_dispatched``) — not all active
    learners — so participation_ratio < 1 cannot deadlock a round on
    learners that were never asked to train. When no dispatch was recorded
    (e.g. the policy object is driven directly in tests) the barrier falls
    back to all active learners, matching the reference's semantics
    (synchronous_scheduler.h:13-40).

    ``quorum`` (scheduling.quorum) turns the full barrier into a K-of-N
    one: the round releases the moment K dispatched learners reported,
    with the reporters as the cohort — the cross-device answer to
    per-round dropout (over-provision the dispatch, take the first K).
    ``quorum=0`` (default) and any quorum >= the dispatched-cohort size
    are IDENTICAL to the full barrier — the target clamps to the barrier
    size, so every release decision reduces to "all reported" (the
    bit-identity pin in tests/test_churn.py).
    """

    name = "synchronous"

    def __init__(self, quorum: int = 0):
        self.quorum = int(quorum)
        self._completed: Set[str] = set()
        self._dispatched: Set[str] = set()

    def notify_dispatched(self, learner_ids: Sequence[str]) -> None:
        self._dispatched.update(learner_ids)

    def dispatched_ids(self) -> Set[str]:
        """The current round's dispatched barrier set (read-only copy) —
        the dispatch-retry path samples replacements outside it."""
        return set(self._dispatched)

    def _barrier(self, active: Sequence[str]) -> List[str]:
        # Only count learners that are still active (a learner leaving
        # mid-round must not stall the federation forever).
        if self._dispatched:
            return [lid for lid in active if lid in self._dispatched]
        return list(active)

    def _target(self, barrier: Sequence[str]) -> int:
        """How many reporters release the round: the full barrier, or the
        quorum when one is configured and the barrier is larger."""
        if self.quorum <= 0:
            return len(barrier)
        return min(self.quorum, len(barrier))

    def _release(self, active: Sequence[str]) -> List[str]:
        cohort = [lid for lid in self._barrier(active) if lid in self._completed]
        self._completed.clear()
        self._dispatched.clear()
        return cohort

    def schedule_next(self, learner_id: str, active: Sequence[str]) -> List[str]:
        self._completed.add(learner_id)
        barrier = self._barrier(active)
        done = sum(1 for lid in barrier if lid in self._completed)
        if not barrier or done < self._target(barrier):
            return []
        return self._release(active)

    def handle_leave(self, active: Sequence[str]) -> List[str]:
        """Re-evaluate the barrier after membership shrinks: if the departed
        learner was the last pending one (or the shrunk barrier now meets
        quorum), release the round now (no later completion event would
        ever re-check)."""
        if not self._completed:
            return []
        barrier = self._barrier(active)
        # An empty barrier means every dispatched learner left — nothing to
        # aggregate; keep state so round_stalled() reports it for re-dispatch.
        if not barrier:
            return []
        done = sum(1 for lid in barrier if lid in self._completed)
        if done < self._target(barrier):
            return []
        return self._release(active)

    def drop_dispatched(self, learner_id: str,
                        active: Sequence[str]) -> List[str]:
        """A dispatch to this learner provably failed (unreachable
        endpoint): remove it from the round barrier so the round never
        waits on a task that was never delivered, and release the round
        if the shrunk barrier is now satisfied. Only the dispatch-retry
        plane calls this — with retries off, a failed dispatch keeps
        today's stall-until-deadline behavior."""
        if learner_id not in self._dispatched:
            return []
        if self._dispatched == {learner_id}:
            # never empty the barrier: round_stalled()/the deadline own
            # the no-survivors case, and an empty dispatched set would
            # silently fall back to the all-active barrier
            return []
        self._dispatched.discard(learner_id)
        return self.handle_leave(active)

    def round_stalled(self, active: Sequence[str]) -> bool:
        """True when a dispatched round can never complete because no
        dispatched learner is still active — the caller should reset and
        dispatch a fresh round to the surviving learners."""
        return bool(self._dispatched) and not any(
            lid in active for lid in self._dispatched)

    def expire_pending(self, active: Sequence[str]) -> List[str]:
        """Straggler deadline: drop dispatched-but-unreported learners from
        the round barrier and release whoever did report (possibly nobody —
        the caller then re-dispatches). Closes the stall the reference never
        handles (SURVEY.md §5.3: failed/hung learners stall a sync round
        forever, controller.cc:683-687)."""
        return self._release(active)

    def reset(self) -> None:
        self._completed.clear()
        self._dispatched.clear()


class AsynchronousScheduler:
    """Immediately reschedule the reporting learner (no round barrier)."""

    name = "asynchronous"

    def notify_dispatched(self, learner_ids: Sequence[str]) -> None:
        pass

    def schedule_next(self, learner_id: str, active: Sequence[str]) -> List[str]:
        return [learner_id]

    def handle_leave(self, active: Sequence[str]) -> List[str]:
        return []

    def round_stalled(self, active: Sequence[str]) -> bool:
        return False

    def expire_pending(self, active: Sequence[str]) -> List[str]:
        return []  # no barrier — a hung learner cannot stall anyone else

    def reset(self) -> None:
        pass


class BufferedAsynchronousScheduler:
    """FedBuff-style buffered asynchronous aggregation (Nguyen et al.,
    AISTATS 2022): uplinks fold into a size-K buffer and aggregation
    triggers per buffer-fill. Learners never barrier on each other — a
    reporter is re-dispatched immediately (``redispatch_on_completion``,
    consumed by the controller), so slow learners keep training while
    fast ones fill buffers; their eventual uplinks carry dispatch-version
    staleness that ``aggregation.staleness_decay`` damps.

    The effective fill target is ``min(buffer_size, active)`` so a
    federation smaller than the buffer (or one that shrank mid-fill)
    still aggregates. The buffer holds REPORTER IDS in arrival order —
    each learner's latest uplink is what the store/streaming path
    aggregates, and a duplicate arrival before the fill simply keeps the
    learner's newest contribution (one buffer slot per learner).
    """

    name = "asynchronous_buffered"
    # the controller re-dispatches each reporter immediately on completion
    # (instead of waiting for the buffer-fill aggregation) so no learner
    # ever idles on the buffer barrier
    redispatch_on_completion = True

    def __init__(self, buffer_size: int = 10):
        self.buffer_size = max(1, int(buffer_size))
        self._buffer: Dict[str, None] = {}  # ordered set: arrival order

    def notify_dispatched(self, learner_ids: Sequence[str]) -> None:
        pass

    def _target(self, active: Sequence[str]) -> int:
        return min(self.buffer_size, max(1, len(active)))

    def _flush(self, active: Sequence[str]) -> List[str]:
        act = set(active)
        cohort = [lid for lid in self._buffer if lid in act]
        self._buffer.clear()
        return cohort

    def schedule_next(self, learner_id: str, active: Sequence[str]) -> List[str]:
        self._buffer[learner_id] = None
        act = set(active)
        live = sum(1 for lid in self._buffer if lid in act)
        if live < self._target(active):
            return []
        return self._flush(active)

    def handle_leave(self, active: Sequence[str]) -> List[str]:
        """Membership shrank: drop departed reporters from the buffer
        (their store lineage is erased with them) and release the buffer
        if the shrunk fill target is now met — the same no-later-event
        rationale as the synchronous barrier re-evaluation."""
        act = set(active)
        for lid in [l for l in self._buffer if l not in act]:
            del self._buffer[lid]
        if self._buffer and len(self._buffer) >= self._target(active):
            return self._flush(active)
        return []

    def round_stalled(self, active: Sequence[str]) -> bool:
        return False  # a partial buffer is progress, not a stall

    def expire_pending(self, active: Sequence[str]) -> List[str]:
        """Deadline fallback: flush whatever the buffer holds (possibly
        nothing — the caller then re-dispatches) so a partial fill cannot
        sit forever when the remaining reporters died."""
        return self._flush(active)

    def pending(self) -> int:
        return len(self._buffer)

    def reset(self) -> None:
        self._buffer.clear()


class SemiSynchronousScheduler(SynchronousScheduler):
    """Synchronous release + per-learner step budget matched to the slowest.

    After each round, every learner's local-step count is recomputed so all
    learners train for ``lambda_ × (slowest learner's epoch wall-clock)``:
    ``steps_i = lambda_ · t_slowest_epoch / t_step_i``. Mirrors the
    reference's ``UpdateLearnersTaskTemplates`` (controller.cc:529-567).
    """

    name = "semi_synchronous"

    def __init__(self, lambda_: float = 1.0, recompute_every_round: bool = False,
                 quorum: int = 0):
        super().__init__(quorum=quorum)
        self.lambda_ = float(lambda_)
        self.recompute_every_round = recompute_every_round
        self._recomputed_once = False

    def recompute_steps(
        self,
        timings: Dict[str, Dict[str, float]],
    ) -> Dict[str, int]:
        """``timings[lid] = {"ms_per_step": float, "steps_per_epoch": float}``
        → per-learner local-step budgets for the next round."""
        if self.recompute_every_round is False and self._recomputed_once:
            return {}
        usable = {
            lid: t
            for lid, t in timings.items()
            if t.get("ms_per_step", 0) > 0 and t.get("steps_per_epoch", 0) > 0
        }
        if not usable:
            return {}
        slowest_epoch_ms = max(
            t["ms_per_step"] * t["steps_per_epoch"] for t in usable.values()
        )
        budget_ms = self.lambda_ * slowest_epoch_ms
        self._recomputed_once = True
        return {
            lid: max(1, int(budget_ms / t["ms_per_step"]))
            for lid, t in usable.items()
        }


SCHEDULERS = {
    "synchronous": SynchronousScheduler,
    "semi_synchronous": SemiSynchronousScheduler,
    "asynchronous": AsynchronousScheduler,
    "asynchronous_buffered": BufferedAsynchronousScheduler,
}


def make_scheduler(name: str, **kwargs):
    try:
        cls = SCHEDULERS[name.lower()]
    except KeyError:
        raise ValueError(f"unknown scheduler {name!r}; have {sorted(SCHEDULERS)}") from None
    return cls(**kwargs)
