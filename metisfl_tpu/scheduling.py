"""Round scheduling policies: synchronous, semi-synchronous, asynchronous.

Equivalent of the reference's ``Scheduler`` strategies
(reference metisfl/controller/scheduling/synchronous_scheduler.h:13-40,
asynchronous_scheduler.h:12-20) plus the semi-synchronous per-learner step
recomputation the reference keeps inside the controller
(controller.cc:520-569). Pure in-memory policy objects — no I/O.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Sequence, Set


class SynchronousScheduler:
    """Release the full cohort only when every active learner has reported."""

    name = "synchronous"

    def __init__(self):
        self._completed: Set[str] = set()

    def schedule_next(self, learner_id: str, active: Sequence[str]) -> List[str]:
        self._completed.add(learner_id)
        # Only count learners that are still active (a learner leaving
        # mid-round must not stall the federation forever).
        pending = [lid for lid in active if lid not in self._completed]
        if pending:
            return []
        self._completed.clear()
        return list(active)

    def reset(self) -> None:
        self._completed.clear()


class AsynchronousScheduler:
    """Immediately reschedule the reporting learner (no round barrier)."""

    name = "asynchronous"

    def schedule_next(self, learner_id: str, active: Sequence[str]) -> List[str]:
        return [learner_id]

    def reset(self) -> None:
        pass


class SemiSynchronousScheduler(SynchronousScheduler):
    """Synchronous release + per-learner step budget matched to the slowest.

    After each round, every learner's local-step count is recomputed so all
    learners train for ``lambda_ × (slowest learner's epoch wall-clock)``:
    ``steps_i = lambda_ · t_slowest_epoch / t_step_i``. Mirrors the
    reference's ``UpdateLearnersTaskTemplates`` (controller.cc:529-567).
    """

    name = "semi_synchronous"

    def __init__(self, lambda_: float = 1.0, recompute_every_round: bool = False):
        super().__init__()
        self.lambda_ = float(lambda_)
        self.recompute_every_round = recompute_every_round
        self._recomputed_once = False

    def recompute_steps(
        self,
        timings: Dict[str, Dict[str, float]],
    ) -> Dict[str, int]:
        """``timings[lid] = {"ms_per_step": float, "steps_per_epoch": float}``
        → per-learner local-step budgets for the next round."""
        if self.recompute_every_round is False and self._recomputed_once:
            return {}
        usable = {
            lid: t
            for lid, t in timings.items()
            if t.get("ms_per_step", 0) > 0 and t.get("steps_per_epoch", 0) > 0
        }
        if not usable:
            return {}
        slowest_epoch_ms = max(
            t["ms_per_step"] * t["steps_per_epoch"] for t in usable.values()
        )
        budget_ms = self.lambda_ * slowest_epoch_ms
        self._recomputed_once = True
        return {
            lid: max(1, int(budget_ms / t["ms_per_step"]))
            for lid, t in usable.items()
        }


SCHEDULERS = {
    "synchronous": SynchronousScheduler,
    "semi_synchronous": SemiSynchronousScheduler,
    "asynchronous": AsynchronousScheduler,
}


def make_scheduler(name: str, **kwargs):
    try:
        cls = SCHEDULERS[name.lower()]
    except KeyError:
        raise ValueError(f"unknown scheduler {name!r}; have {sorted(SCHEDULERS)}") from None
    return cls(**kwargs)
