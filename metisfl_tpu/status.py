"""Live federation status watch: ``python -m metisfl_tpu.status``.

Polls the controller's ``DescribeFederation`` RPC and renders a
refreshing terminal table — the live counterpart of
``python -m metisfl_tpu.stats`` (post-hoc) and the round-5 verdict's ask
that a stalled run say *where* it is stuck while it is stuck:

    python -m metisfl_tpu.status --port 50051                 # live watch
    python -m metisfl_tpu.status --port 50051 --once          # one snapshot
    python -m metisfl_tpu.status --port 50051 --probe         # + ListMethods

Each refresh shows the current round + phase, per-learner liveness and
straggler analytics (EWMA train/eval durations and the round-relative
``straggler_score`` also exported as the ``learner_straggler_score``
gauge), learning-health analytics when the controller runs the health
plane (a ``health:`` line with the latest round's update norm /
effective step / participation entropy / cohort loss, plus per-learner
``diverg``/``upd_norm`` columns mirroring the
``learner_divergence_score`` gauge), in-flight tasks with ages, store
occupancy, and the tail of the controller's event journal. ``--probe`` additionally reflects each
registered endpoint's RPC surface over the ``ListMethods`` RPC
(service-discovery parity with the reference's gRPC reflection).

Telemetry at scale (docs/OBSERVABILITY.md): with
``telemetry.cardinality_budget`` armed and the fleet above it, the
snapshot ships a ``learners_digest`` instead of the O(fleet) table and
this CLI renders quantile columns plus the top offenders; with
``telemetry.alerts`` configured it adds an ``alerts:`` line (firing
rules, lifecycle counts) and live sparklines from the controller's
bounded time-series ring. Sub-budget snapshots render byte-identically
to the per-learner table (test-pinned).
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import Any, Dict, List, Optional


def _fmt_s(seconds: float) -> str:
    if seconds <= 0:
        return "-"
    return f"{seconds:.1f}s" if seconds < 120 else f"{seconds / 60:.1f}m"


def render_snapshot(snap: Dict[str, Any], target: str = "",
                    events: int = 10) -> str:
    """One DescribeFederation snapshot as the watch screen's text."""
    lines: List[str] = []
    epoch = (snap.get("controller_epoch") or "?")[:8]
    learners = snap.get("learners", [])
    live = sum(1 for l in learners if l.get("live"))
    started = snap.get("round_started_at") or 0.0
    age = f"  round_age={_fmt_s(max(0.0, snap.get('time', 0.0) - started))}" \
        if started else ""
    lines.append(
        f"federation{' @ ' + target if target else ''}  epoch={epoch}  "
        f"round={snap.get('round', '?')}  phase={snap.get('phase', '?')}"
        f"{age}  protocol={snap.get('protocol', '?')}  "
        f"rule={snap.get('aggregation_rule', '?')}  "
        f"learners={live}/{len(learners)} live")
    health = snap.get("health") or {}
    if health:
        # learning-health line (telemetry/health.py round snapshot);
        # pre-health controllers ship no "health" key and render as before
        loss = health.get("cohort_loss") or {}
        loss_cell = f"  loss_p50={loss['p50']:.4f}" if "p50" in loss else ""
        anomalous = health.get("anomalous") or []
        anom_cell = f"  ANOMALOUS={','.join(anomalous)}" if anomalous else ""
        lines.append(
            f"health: upd_norm={health.get('round_update_norm', 0.0):.4g}  "
            f"eff_step={health.get('effective_step', 0.0):.4g}  "
            f"entropy={health.get('participation_entropy', 0.0):.2f}"
            f"{loss_cell}{anom_cell}")
    reg = snap.get("registry") or {}
    if reg.get("enabled"):
        # model-lifecycle line (registry/registry.py): channel heads +
        # retained lineage; pre-registry controllers ship no "registry"
        # key and render as before
        stable = reg.get("stable", 0)
        cand = reg.get("candidate", 0)
        versions = reg.get("versions", [])
        gates = [v for v in versions
                 if v.get("gate") and not v["gate"].get("passed", True)]
        gate_cell = (f"  gate_rejected=v{gates[-1]['version']}"
                     if gates else "")
        lines.append(
            f"registry: stable={f'v{stable}' if stable else '-'}  "
            f"candidate={f'v{cand}' if cand else '-'}  "
            f"versions={len(versions)}{gate_cell}")
    sched = snap.get("scheduling") or {}
    if sched:
        # churn-tolerant scheduling line (quorum / FedBuff / retry /
        # quarantine); silo-regime controllers ship no "scheduling" key
        # and render as before
        cells = []
        if "quorum" in sched:
            cells.append(f"quorum={sched['quorum']}"
                         f" overprov={sched.get('overprovision', 0.0):g}")
        if "buffer_size" in sched:
            cells.append(f"buffer={sched.get('buffer_pending', 0)}"
                         f"/{sched['buffer_size']}")
        if "dispatch_retries" in sched:
            cells.append(f"retries={sched.get('dispatch_retries_used', 0)}"
                         f"/{sched['dispatch_retries']}")
        quarantined = sched.get("quarantined") or []
        if quarantined:
            cells.append(f"QUARANTINED={','.join(quarantined)}")
        lines.append("scheduling: " + "  ".join(cells))
    slices = snap.get("slices") or {}
    if slices.get("enabled"):
        # distributed slice-aggregation tier (aggregation/distributed.py);
        # controllers without it ship no "slices" key and render as before
        cells = []
        for row in slices.get("slices", []):
            state = ("DEAD→" + row["rehomed_to"] if row.get("rehomed_to")
                     else ("DEAD" if row.get("dead") else "up"))
            cells.append(f"{row.get('name', '?')}={state}"
                         f"({row.get('held', 0)})")
        rollup = slices.get("uplink_bytes") or {}
        rollup_cell = (f"  uplink_p50={rollup.get('p50', 0):g}B"
                       f" p99={rollup.get('p99', 0):g}B" if rollup else "")
        lines.append(
            f"slices: {slices.get('alive', 0)}/"
            f"{len(slices.get('slices', []))} up  "
            f"rehomed={slices.get('rehomed_total', 0)}  "
            f"root_residual={slices.get('root_residual', 0)}  "
            + "  ".join(cells) + rollup_cell)
    alerts = snap.get("alerts") or {}
    if alerts.get("enabled"):
        # SLO alerting plane (telemetry/alerts.py); controllers without
        # an engine ship no "alerts" key and render as before
        active = alerts.get("active") or []
        if active:
            cells = ", ".join(
                f"{a.get('name', '?')}[{a.get('severity', '?')}] "
                f"{a.get('expr', '')} value={a.get('value', 0.0):g} "
                f"for {_fmt_s(float(a.get('active_s', 0.0)))}"
                for a in active)
            lines.append(f"alerts: FIRING {len(active)}: {cells}")
        else:
            lines.append(
                f"alerts: none firing  rules={alerts.get('rules', 0)}  "
                f"fired={alerts.get('fired_total', 0)}  "
                f"resolved={alerts.get('resolved_total', 0)}")
    series = snap.get("timeseries") or {}
    if series:
        # live time-series sparklines from the controller's bounded ring
        # (telemetry/timeseries.py): newest sample on the right
        from metisfl_tpu.telemetry.timeseries import sparkline
        shown = 0
        for name in sorted(series):
            if shown >= 6:
                break
            points = (series[name] or {}).get("points") or []
            if len(points) < 2:
                continue
            shown += 1
            lines.append(f"  {name:<34} {sparkline(points):<24} "
                         f"last={points[-1]:g}")
    prof = snap.get("profile") or {}
    if prof.get("enabled") and prof.get("rounds_profiled"):
        # performance-observatory line (telemetry/profile.py): the latest
        # round's cost waterfall in one glance; pre-profile controllers
        # ship no "profile" key and render as before
        phases = prof.get("phases") or {}
        top = max(phases, key=phases.get) if phases else "-"
        wall = float(prof.get("wall_ms", 0.0))
        lines.append(
            f"perf: round={prof.get('last_round', '?')}  "
            f"wall={_fmt_s(wall / 1e3)}  "
            f"coverage={float(prof.get('coverage', 0.0)) * 100:.0f}%  "
            f"top_phase={top}"
            + (f" ({phases.get(top, 0.0) / 1e3:.2f}s)" if phases else "")
            + f"  up={float(prof.get('uplink_bytes', 0.0)) / 1e6:.2f}MB"
            f"  down={float(prof.get('downlink_bytes', 0.0)) / 1e6:.2f}MB")
    digest = snap.get("learners_digest") or {}
    if digest:
        # cardinality-safe snapshot (telemetry.cardinality_budget): the
        # fleet is above budget, so quantile columns replace the
        # per-learner table and only the top offenders list by name.
        # Sub-budget snapshots ship no "learners_digest" key and the
        # exact table below renders byte-identically (test-pinned).
        lines.append("")
        lines.append(
            f"fleet: {digest.get('live', 0)}/{digest.get('count', 0)} live"
            f"  (cardinality budget {digest.get('budget', 0)}: quantile "
            "digest replaces the per-learner table)"
            + (f"  quarantined={digest['quarantined']}"
               if digest.get("quarantined") else ""))
        columns = digest.get("columns") or {}
        if columns:
            lines.append(f"  {'metric':<20} {'p50':>9} {'p90':>9} "
                         f"{'p99':>9} {'max':>9}")
            for name in sorted(columns):
                cells = columns[name] or {}
                lines.append(
                    f"  {name:<20} {cells.get('p50', 0.0):>9.4g} "
                    f"{cells.get('p90', 0.0):>9.4g} "
                    f"{cells.get('p99', 0.0):>9.4g} "
                    f"{cells.get('max', 0.0):>9.4g}")
        if learners:
            lines.append(f"  top offenders by straggler score "
                         f"({len(learners)} of {digest.get('count', 0)}):")
    has_div = any("divergence_score" in l for l in learners)
    has_churn = any("churn_score" in l for l in learners)
    if learners:
        lines.append("")
        div_header = f"{'diverg':>7} {'upd_norm':>8} " if has_div else ""
        churn_header = f"{'churn':>6} " if has_churn else ""
        lines.append(f"{'learner':<28} {'live':>4} {'straggler':>9} "
                     f"{div_header}{churn_header}"
                     f"{'ewma_train':>10} {'ewma_eval':>9} {'fails':>5} "
                     f"{'last_round':>10} {'stored':>6}")
        stored = (snap.get("store") or {}).get("models", {})
        for l in learners:
            score = float(l.get("straggler_score", 0.0))
            div_cells = ""
            if has_div:
                div = float(l.get("divergence_score", 0.0))
                norm = float(l.get("last_update_norm", 0.0))
                div_cells = (
                    f"{(f'{div:.2f}' if div > 0 else '-'):>7} "
                    f"{(f'{norm:.3g}' if norm > 0 else '-'):>8} ")
            churn_cells = ""
            if has_churn:
                churn = float(l.get("churn_score", 0.0))
                cell = "QUAR" if l.get("quarantined") else (
                    f"{churn:.2f}" if churn > 0 else "-")
                churn_cells = f"{cell:>6} "
            lines.append(
                f"{l.get('learner_id', '?'):<28} "
                f"{'yes' if l.get('live') else 'NO':>4} "
                f"{(f'{score:.2f}x' if score > 0 else '-'):>9} "
                f"{div_cells}{churn_cells}"
                f"{_fmt_s(float(l.get('ewma_train_s', 0.0))):>10} "
                f"{_fmt_s(float(l.get('ewma_eval_s', 0.0))):>9} "
                f"{l.get('dispatch_failures', 0):>5} "
                f"{l.get('last_result_round', -1):>10} "
                f"{stored.get(l.get('learner_id'), 0):>6}")
    in_flight = snap.get("in_flight", [])
    if in_flight:
        lines.append("")
        cells = ", ".join(
            f"{t.get('learner_id', '?')}:{t.get('task_id', '?')[:8]}"
            f" ({_fmt_s(float(t.get('age_s', 0.0)))})"
            for t in sorted(in_flight,
                            key=lambda t: -float(t.get("age_s", 0.0))))
        lines.append(f"in-flight ({len(in_flight)}): {cells}")
    tail = snap.get("events", [])
    if tail and events > 0:
        from metisfl_tpu.telemetry import events as _events
        lines.append("")
        lines.append(f"events (last {min(events, len(tail))} of ring):")
        t0 = float(tail[0].get("ts", 0.0)) if tail else None
        for record in tail[-events:]:
            lines.append("  " + _events.format_record(record, t0=t0))
    return "\n".join(lines)


def render_serving_line(desc: Dict[str, Any]) -> str:
    """The ``serving:`` line from a ``GetServingStatus`` reply — a
    router's reply (serving/fleet.py) renders per-replica state/health/
    installed versions; a single gateway's reply renders its installed
    map."""
    if desc.get("router"):
        cells = []
        for row in desc.get("replicas", []):
            installed = row.get("installed") or {}
            vers = ",".join(f"{ch}=v{v}"
                            for ch, v in sorted(installed.items()))
            cells.append(f"{row.get('replica', '?')}="
                         f"{row.get('state', '?')}"
                         + (f"({vers})" if vers else ""))
        return (f"serving: {desc.get('live', 0)}/"
                f"{len(desc.get('replicas', []))} replicas up  "
                f"requests={desc.get('requests', 0)}  "
                + "  ".join(cells))
    installed = desc.get("installed") or {}
    vers = "  ".join(f"{ch}=v{v}" for ch, v in sorted(installed.items()))
    return (f"serving: 1 gateway  requests={desc.get('requests', 0)}  "
            f"{vers or 'nothing installed'}")


def render_fleet(snap: Dict[str, Any], span_tail: int = 25,
                 serving: Optional[Dict[str, Any]] = None) -> str:
    """One :meth:`FleetCollector.snapshot` as the ``--fleet`` screen:
    per-peer liveness/health/offset rows, the serving fleet's
    per-replica line (``serving`` = a GetServingStatus reply, router or
    gateway), the merged metric-family summary, and the unified
    skew-corrected span waterfall."""
    lines: List[str] = []
    peers = snap.get("peers", [])
    lines.append(f"fleet: {snap.get('live', 0)}/{len(peers)} peers live  "
                 f"polls={snap.get('polls', 0)}")
    if serving:
        lines.append(render_serving_line(serving))
    if peers:
        lines.append(f"{'peer':<28} {'role':<10} {'target':<22} "
                     f"{'health':<12} {'state':<8} {'offset':>9} "
                     f"{'rtt':>8} {'spans':>6} {'events':>6}")
        for p in sorted(peers, key=lambda p: (p.get("role", ""),
                                              p.get("peer", ""))):
            state = ("DISABLED" if p.get("disabled")
                     else "STALE" if p.get("stale")
                     else "live" if p.get("live") else "pending")
            lines.append(
                f"{p.get('peer', '?'):<28} {p.get('role', '?'):<10} "
                f"{p.get('target', '?'):<22} "
                f"{p.get('health') or '-':<12} {state:<8} "
                f"{p.get('offset_ms', 0.0):>+8.1f}ms "
                f"{p.get('rtt_ms', 0.0):>6.1f}ms "
                f"{p.get('spans', 0):>6} {p.get('events', 0):>6}")
    prof = snap.get("prof") or {}
    if prof:
        # continuous-profiling line(s) (telemetry/prof.py over the
        # CollectTelemetry prof section): each peer's hottest frame by
        # self time and its most contended lock site
        cells = []
        for name in sorted(prof):
            row = prof[name] or {}
            if not row.get("samples"):
                continue
            cell = (f"{name}: {row.get('top_frame', '?')} "
                    f"{row.get('top_frame_pct', 0.0):g}%")
            if row.get("top_lock"):
                cell += (f" lock={row['top_lock']} "
                         f"{row.get('top_lock_wait_ms', 0.0):g}ms/"
                         f"{row.get('contentions', 0)}w")
            cells.append(cell)
        if cells:
            lines.append("prof: " + "  |  ".join(cells))
    runtime = snap.get("runtime") or {}
    if runtime:
        # accelerator-runtime line (telemetry/runtime.py over the
        # CollectTelemetry runtime section): per-peer compile totals,
        # the worst recompile offender, and the latest memory sample
        cells = []
        for name in sorted(runtime):
            row = runtime[name] or {}
            if not row.get("compiles") and not row.get("mem_bytes"):
                continue
            cell = (f"{name}: {row.get('compiles', 0)}c/"
                    f"{row.get('recompiles', 0)}r")
            if row.get("storms"):
                cell += f" STORMS={row['storms']}"
            if row.get("top_offender"):
                cell += (f" worst={row['top_offender']}"
                         f"x{row.get('top_offender_recompiles', 0)}")
            if row.get("mem_bytes"):
                cell += f" mem={row['mem_bytes'] / 1e6:.0f}MB"
            cells.append(cell)
        if cells:
            lines.append("runtime: " + "  |  ".join(cells))
    families = snap.get("families") or {}
    wal_total = (families.get("controller_wal_records_total")
                 or {}).get("total")
    wal_lag = (families.get("controller_wal_lag_records")
               or {}).get("total")
    failovers = (families.get("controller_failover_total")
                 or {}).get("total")
    if wal_total is not None or wal_lag is not None:
        # hot-standby HA line (controller/wal.py + __main__ --standby):
        # WAL replication depth, the standby's tail lag, and how long
        # promotions took when any fired
        cell = (f"ha: wal={wal_total or 0:g} records "
                f"lag={wal_lag or 0:g}")
        if failovers:
            cell += f"  failovers={failovers:g}"
            promote = (families.get("controller_failover_promote_seconds")
                       or {}).get("sum")
            if promote:
                cell += f" promote={promote:g}s"
        lines.append(cell)
    crit = snap.get("crit") or {}
    if crit:
        # latest round's causal critical path (telemetry/causal.py via
        # the fleet collector): who the round actually waited on
        edges = "  ->  ".join(
            f"{e.get('label', '?')} {e.get('self_ms', 0.0):.0f}ms"
            for e in crit.get("edges", ())[:4])
        lines.append(
            f"crit: round {crit.get('round', '?')} "
            f"{crit.get('coverage', 0.0) * 100:.0f}% of "
            f"{crit.get('total_ms', 0.0) / 1e3:.2f}s"
            + (f" = {edges}" if edges else ""))
    families = snap.get("families") or {}
    if families:
        shown = []
        for name in ("rounds_total", "controller_active_learners",
                     "learner_tasks_total", "rpc_client_errors_total",
                     "serving_requests_total", "alerts_fired_total"):
            entry = families.get(name)
            if entry and entry.get("total") is not None:
                shown.append(f"{name}={entry['total']:g}")
        total_series = sum(int(f.get("series", 0))
                           for f in families.values())
        lines.append(
            f"merged metrics: {len(families)} families / "
            f"{total_series} series"
            + (f"  ({'  '.join(shown)})" if shown else ""))
    spans = snap.get("spans") or []
    if spans:
        tail = spans[-span_tail:]
        t0 = float(tail[0].get("start", 0.0))
        by_id = {s.get("span"): s for s in tail if s.get("span")}
        lines.append("")
        lines.append(f"span waterfall (last {len(tail)}, one corrected "
                     "clock; +s since first shown):")
        for s in tail:
            depth = 0
            parent = s.get("parent", "")
            seen = set()
            while parent and parent in by_id and parent not in seen:
                seen.add(parent)
                depth += 1
                parent = by_id[parent].get("parent", "")
            dur = float(s.get("dur_ms", 0.0))
            dur_cell = (f"{dur / 1e3:.2f}s" if dur >= 1e3
                        else f"{dur:.1f}ms")
            lines.append(
                f"  +{max(0.0, float(s.get('start', 0.0)) - t0):8.3f}s "
                f"{'  ' * depth}{s.get('name', '?')} ({dur_cell}) "
                f"[{s.get('service', '?')}"
                + (f"@{s['peer']}" if s.get("peer") else "") + "]")
    tail = snap.get("events") or []
    if tail:
        from metisfl_tpu.telemetry import events as _events
        lines.append("")
        lines.append(f"fleet events (last {len(tail)}):")
        t0 = float(tail[0].get("ts", 0.0)) if tail else None
        for record in tail:
            lines.append("  " + _events.format_record(record, t0=t0))
    return "\n".join(lines)


def _fleet_collector(args, ssl=None):
    """A FleetCollector dialing the controller + everything
    DescribeFederation knows about (the status CLI's --fleet source)."""
    from metisfl_tpu.controller.service import (CONTROLLER_SERVICE,
                                                LEARNER_SERVICE,
                                                ControllerClient)
    from metisfl_tpu.telemetry.fabric import FleetCollector

    client = ControllerClient(args.host, args.port, ssl=ssl)

    def _discover():
        specs = [{"name": "controller", "host": args.host,
                  "port": args.port, "service_name": CONTROLLER_SERVICE,
                  "role": "controller"}]
        if getattr(args, "standby_port", 0):
            # warm hot-standby (controller/__main__.py --standby): its
            # role-tagged methodless service answers the pulls, so the
            # table shows it live pre-promotion
            specs.append({"name": "standby",
                          "host": getattr(args, "standby_host", "")
                          or args.host,
                          "port": args.standby_port,
                          "service_name": CONTROLLER_SERVICE,
                          "role": "standby"})
        try:
            snap = client.describe_federation(event_tail=0, timeout=5.0,
                                              wait_ready=False)
        except Exception:  # noqa: BLE001 - known peers keep polling
            return specs
        for l in snap.get("learners", []):
            if not l.get("port"):
                continue
            specs.append({"name": l.get("learner_id")
                          or f"{l.get('hostname')}:{l.get('port')}",
                          "host": l.get("hostname", "localhost"),
                          "port": l["port"],
                          "service_name": LEARNER_SERVICE,
                          "role": "learner"})
        if getattr(args, "serving_port", 0):
            from metisfl_tpu.serving.service import (SERVING_SERVICE,
                                                     ServingClient)
            specs.append({"name": "serving", "host": args.host,
                          "port": args.serving_port,
                          "service_name": SERVING_SERVICE,
                          "role": "serving"})
            # a fleet ROUTER on that port names its replicas — pull each
            # as its own role="serving" peer so fabric spans/metrics/
            # prof: lines cover every replica, not just the front door
            try:
                sc = ServingClient(args.host, args.serving_port, ssl=ssl)
                try:
                    desc = sc.status(timeout=3.0, wait_ready=False)
                finally:
                    sc.close()
                for row in (desc.get("replicas") or []):
                    host, _, port = row.get("target", "").rpartition(":")
                    if host and port.isdigit():
                        specs.append({"name": row.get("replica", host),
                                      "host": host, "port": int(port),
                                      "service_name": SERVING_SERVICE,
                                      "role": "serving"})
            except Exception:  # noqa: BLE001 - a plain gateway, or down
                pass
        return specs

    collector = FleetCollector(ssl=ssl, discover_fn=_discover)
    return collector, client


def render_probe(reflection: Dict[str, Any]) -> str:
    methods = reflection.get("methods", [])
    # endpoint role (ListMethods reflection): a serving gateway's surface
    # is distinguishable from learner/controller ones at a glance
    role = reflection.get("role", "")
    role_cell = f" role={role}" if role else ""
    lines = [f"service {reflection.get('service', '?')}{role_cell} "
             f"({len(methods)} methods):"]
    for m in methods:
        flags = ",".join(m.get("transports", []))
        if m.get("oversize_unary_fallback"):
            flags += "+oversize_fallback"
        lines.append(f"  {m.get('name', '?'):<28} [{flags}]")
    return "\n".join(lines)


def _probe_learners(snap: Dict[str, Any], ssl=None) -> List[str]:
    """ListMethods against every registered learner endpoint (the status
    CLI's endpoint probe — dead endpoints report as unreachable instead
    of killing the watch)."""
    import json as _json

    from metisfl_tpu.comm.rpc import RpcClient
    from metisfl_tpu.controller.service import LEARNER_SERVICE

    from metisfl_tpu.comm.health import probe_health

    out: List[str] = []
    for l in snap.get("learners", []):
        host, port = l.get("hostname", "?"), int(l.get("port", 0) or 0)
        label = f"{l.get('learner_id', '?')} @ {host}:{port}"
        if not port:
            out.append(f"{label}: no registered port")
            continue
        # standard grpc.health.v1 probe first: a NOT_SERVING endpoint
        # (shutting down) is a different answer than an unreachable one
        health = probe_health(host, port, ssl=ssl)
        client = RpcClient(host, port, LEARNER_SERVICE, retries=0, ssl=ssl)
        try:
            raw = client.call("ListMethods", b"", timeout=5.0,
                              wait_ready=False)
            out.append(f"{label} [health={health}]:")
            out.append(render_probe(_json.loads(raw.decode("utf-8"))))
        except Exception as exc:  # noqa: BLE001 - probe is best-effort
            out.append(f"{label} [health={health}]: unreachable ({exc})")
        finally:
            client.close()
    return out


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        "metisfl_tpu.status",
        description="live federation status over DescribeFederation")
    parser.add_argument("--host", default="localhost")
    parser.add_argument("--port", type=int, required=True,
                        help="controller gRPC port")
    parser.add_argument("--interval", type=float, default=2.0,
                        help="refresh period in seconds")
    parser.add_argument("--once", action="store_true",
                        help="print one snapshot and exit (no refresh loop)")
    parser.add_argument("--events", type=int, default=10,
                        help="event-journal tail lines to show (0 = none)")
    parser.add_argument("--probe", action="store_true",
                        help="reflect every endpoint's RPC surface via "
                             "ListMethods (+ grpc.health.v1 probes)")
    parser.add_argument("--fleet", action="store_true",
                        help="merged fleet view over the telemetry fabric "
                             "(CollectTelemetry pulls against controller + "
                             "learners + gateway): per-peer liveness and "
                             "clock offset, merged metric families, one "
                             "skew-corrected span waterfall")
    parser.add_argument("--standby-host", default="",
                        help="--fleet: controller hot-standby host "
                             "(defaults to --host)")
    parser.add_argument("--standby-port", type=int, default=0,
                        help="--fleet: also pull the warm hot-standby on "
                             "this port — shown as a role=standby peer "
                             "until it promotes")
    parser.add_argument("--serving-port", type=int, default=0,
                        help="--fleet: also pull the serving plane on "
                             "this port (the fleet ROUTER when one runs "
                             "— its reply renders the per-replica "
                             "serving: line — or the single gateway)")
    parser.add_argument("--ssl-cert", default="",
                        help="federation TLS cert (a TLS-enabled run — the "
                             "driver's auto-generated pair lives in "
                             "<workdir>/tls — serves only over TLS)")
    parser.add_argument("--ssl-key", default="")
    args = parser.parse_args(argv)

    from metisfl_tpu.controller.service import ControllerClient

    ssl = None
    if args.ssl_cert:
        from metisfl_tpu.comm.ssl import SSLConfig
        ssl = SSLConfig(enabled=True, cert_path=args.ssl_cert,
                        key_path=args.ssl_key)
    target = f"{args.host}:{args.port}"
    if args.fleet:
        collector, client = _fleet_collector(args, ssl=ssl)

        def _serving_desc():
            """GetServingStatus off --serving-port (router or gateway);
            None keeps the screen serving-line-free."""
            if not args.serving_port:
                return None
            from metisfl_tpu.serving.service import ServingClient
            sc = ServingClient(args.host, args.serving_port, ssl=ssl)
            try:
                return sc.status(timeout=5.0, wait_ready=False)
            except Exception:  # noqa: BLE001 - best-effort line
                return None
            finally:
                sc.close()

        try:
            while True:
                collector.poll_once(timeout=10.0)
                if args.once:
                    # a second poll refines the first's offset estimate
                    # before the one-shot render
                    collector.poll_once(timeout=10.0)
                    print(render_fleet(collector.snapshot(),
                                       serving=_serving_desc()))
                    return 0
                sys.stdout.write("\x1b[2J\x1b[H"
                                 + render_fleet(collector.snapshot(),
                                                serving=_serving_desc())
                                 + "\n")
                sys.stdout.flush()
                time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0
        finally:
            collector.stop(final_poll=False)
            client.close()
    client = ControllerClient(args.host, args.port, ssl=ssl)
    try:
        while True:
            try:
                snap = client.describe_federation(
                    event_tail=max(args.events, 0),
                    timeout=10.0, wait_ready=False)
            except Exception as exc:  # noqa: BLE001 - report, don't crash
                print(f"controller {target} unreachable: {exc}",
                      file=sys.stderr)
                if args.once:
                    return 1
                time.sleep(args.interval)
                continue
            screen = render_snapshot(snap, target=target, events=args.events)
            if args.probe:
                from metisfl_tpu.comm.health import probe_health
                health = probe_health(args.host, args.port, ssl=ssl)
                try:
                    screen += (f"\n\ncontroller [health={health}] "
                               + render_probe(client.list_methods()))
                except Exception as exc:  # noqa: BLE001
                    screen += (f"\n\ncontroller [health={health}] "
                               f"ListMethods failed: {exc}")
                probe = _probe_learners(snap, ssl=ssl)
                if probe:
                    screen += "\n" + "\n".join(probe)
            if args.once:
                print(screen)
                return 0
            # ANSI clear + home: a refreshing table, not a scrolling log
            sys.stdout.write("\x1b[2J\x1b[H" + screen + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    finally:
        client.close()


if __name__ == "__main__":
    sys.exit(main())
