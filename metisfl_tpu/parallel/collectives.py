"""Federated collectives: aggregation that never leaves the device mesh.

The reference ships every model as a protobuf blob through gRPC and sums
byte-deserialized vectors on the controller's CPU (reference
controller.cc:795-950 + proto_tensor_serde.h). When learners co-reside on a
TPU pod slice, that entire path collapses into ONE jit-compiled weighted
``psum`` over the ``fed`` mesh axis riding ICI — no serialization, no host
round trip, no controller CPU in the loop. This module provides that kernel.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def to_varying(tree, axis_names):
    """Mark a replicated tree as device-varying over ``axis_names``.

    Required before ``jax.grad`` inside ``shard_map``: differentiating w.r.t.
    an *unvarying* (replicated) input transposes the implicit broadcast into
    a psum over the mesh — per-device gradients silently become cross-device
    sums. (jax ≥0.9 VMA semantics; fixed here by casting params to varying
    so the cotangent stays per-device.)"""
    def cast(t):
        try:
            return jax.lax.pcast(t, axis_names, to="varying")
        except AttributeError:  # pragma: no cover - older jax
            return jax.lax.pvary(t, axis_names)
    return jax.tree.map(cast, tree)


def federated_mean_psum(params, scale, axis_name: str = "fed"):
    """Inside shard_map/pjit: weighted mean of per-learner params over the
    federation axis. ``scale`` is this learner's normalized weight."""
    return jax.tree.map(
        lambda x: jax.lax.psum(x * scale, axis_name), params)


def make_pod_aggregator(mesh: Mesh, param_specs, axis_name: str = "fed"
                        ) -> Callable:
    """Compile ``(stacked_params, scales) → community_params``.

    ``stacked_params``: every leaf has a leading learner axis of size
    ``mesh.shape[axis_name]``, sharded over ``fed`` (learner *i*'s model
    lives on its own slice). ``scales``: (L,) normalized weights. The
    returned community model is fully replicated — each learner reads its
    next-round weights locally with zero transfer.
    """
    fed = mesh.shape[axis_name]

    def _in_spec(spec):
        inner = spec if isinstance(spec, P) else P()
        return P(axis_name, *inner)

    in_specs = jax.tree.map(_in_spec, param_specs,
                            is_leaf=lambda x: isinstance(x, P))
    out_specs = param_specs

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(in_specs, P(axis_name)),
        out_specs=out_specs,
    )
    def _aggregate(stacked, scales):
        # each fed shard holds its learner's model: leading axis length 1
        local = jax.tree.map(lambda x: x[0], stacked)
        scale = scales[0]
        return jax.tree.map(
            lambda x: jax.lax.psum(
                (x * scale).astype(_acc(x.dtype)), axis_name).astype(x.dtype),
            local)

    return jax.jit(_aggregate)


def _acc(dtype):
    dtype = jnp.dtype(dtype)
    if dtype in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16)):
        return jnp.float32
    return dtype


def make_robust_pod_combine(mesh: Mesh, rule: str, trim: int = 0,
                            axis_name: str = "fed") -> Callable:
    """Device-resident byzantine-robust combine for the ICI fast path.

    ``stacked`` trees carry a leading learner axis sharded over ``fed``
    (each learner's trained model on its own slice); the combine is a
    coordinate-wise median or trimmed mean over that axis — XLA inserts
    the all-gather over ICI, sorts on device, and the community model
    comes out replicated. Host-path parity: same f32 accumulation and the
    same trim count as :class:`aggregation.robust.TrimmedMean` (pass its
    ``_trim(L)``); scales are ignored by construction — robustness comes
    precisely from not letting any learner claim more weight
    (aggregation/robust.py module contract). Memory note: the gather
    materializes L models per device, the price of a sort none of the
    psum algebra can pay."""
    if rule not in ("median", "trimmed_mean"):
        raise ValueError(f"unknown robust pod rule {rule!r}")
    # the ONE leaf definition shared with the host rules — parity by
    # construction, not by synchronized copies
    from metisfl_tpu.aggregation.robust import median_leaf, trimmed_mean_leaf

    def combine(stacked):
        def leaf(s):
            acc = s.astype(_acc(s.dtype))
            r = (median_leaf(acc) if rule == "median"
                 else trimmed_mean_leaf(acc, trim))
            return r.astype(s.dtype)

        return jax.tree.map(leaf, stacked)

    return jax.jit(combine, out_shardings=NamedSharding(mesh, P()))


def replicate_to_fed(mesh: Mesh, params, axis_name: str = "fed"):
    """Place a host pytree fully replicated on the mesh."""
    sharding = NamedSharding(mesh, P())
    return jax.device_put(params, sharding)
