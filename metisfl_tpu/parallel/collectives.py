"""Federated collectives: aggregation that never leaves the device mesh.

The reference ships every model as a protobuf blob through gRPC and sums
byte-deserialized vectors on the controller's CPU (reference
controller.cc:795-950 + proto_tensor_serde.h). When learners co-reside on a
TPU pod slice, that entire path collapses into ONE jit-compiled weighted
``psum`` over the ``fed`` mesh axis riding ICI — no serialization, no host
round trip, no controller CPU in the loop. This module provides that kernel.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def to_varying(tree, axis_names):
    """Mark a replicated tree as device-varying over ``axis_names``.

    Required before ``jax.grad`` inside ``shard_map``: differentiating w.r.t.
    an *unvarying* (replicated) input transposes the implicit broadcast into
    a psum over the mesh — per-device gradients silently become cross-device
    sums. (jax ≥0.9 VMA semantics; fixed here by casting params to varying
    so the cotangent stays per-device.)"""
    def cast(t):
        try:
            return jax.lax.pcast(t, axis_names, to="varying")
        except AttributeError:  # pragma: no cover - older jax
            return jax.lax.pvary(t, axis_names)
    return jax.tree.map(cast, tree)


def federated_mean_psum(params, scale, axis_name: str = "fed"):
    """Inside shard_map/pjit: weighted mean of per-learner params over the
    federation axis. ``scale`` is this learner's normalized weight."""
    return jax.tree.map(
        lambda x: jax.lax.psum(x * scale, axis_name), params)


def make_pod_aggregator(mesh: Mesh, param_specs, axis_name: str = "fed"
                        ) -> Callable:
    """Compile ``(stacked_params, scales) → community_params``.

    ``stacked_params``: every leaf has a leading learner axis of size
    ``mesh.shape[axis_name]``, sharded over ``fed`` (learner *i*'s model
    lives on its own slice). ``scales``: (L,) normalized weights. The
    returned community model is fully replicated — each learner reads its
    next-round weights locally with zero transfer.
    """
    fed = mesh.shape[axis_name]

    def _in_spec(spec):
        inner = spec if isinstance(spec, P) else P()
        return P(axis_name, *inner)

    in_specs = jax.tree.map(_in_spec, param_specs,
                            is_leaf=lambda x: isinstance(x, P))
    out_specs = param_specs

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(in_specs, P(axis_name)),
        out_specs=out_specs,
    )
    def _aggregate(stacked, scales):
        # each fed shard holds its learner's model: leading axis length 1
        local = jax.tree.map(lambda x: x[0], stacked)
        scale = scales[0]
        return jax.tree.map(
            lambda x: jax.lax.psum(
                (x * scale).astype(_acc(x.dtype)), axis_name).astype(x.dtype),
            local)

    return jax.jit(_aggregate)


def _acc(dtype):
    dtype = jnp.dtype(dtype)
    if dtype in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16)):
        return jnp.float32
    return dtype


def make_robust_pod_combine(mesh: Mesh, rule: str, trim: int = 0,
                            byzantine_f: int = 0, multi: int = 0,
                            axis_name: str = "fed") -> Callable:
    """Device-resident byzantine-robust combine for the ICI fast path.

    ``stacked`` trees carry a leading learner axis sharded over ``fed``
    (each learner's trained model on its own slice); the combine is a
    coordinate-wise median / trimmed mean over that axis, or (Multi-)Krum
    distance selection — XLA inserts the all-gather over ICI, sorts (or
    runs Krum's single Gram matmul on the MXU) on device, and the
    community model comes out replicated. Host-path parity: the same leaf
    math and scoring as aggregation/robust.py (one definition each);
    scales are ignored by construction — robustness comes precisely from
    not letting any learner claim more weight (robust.py module
    contract). Memory note: the gather materializes L models per device,
    the price of a sort/selection none of the psum algebra can pay."""
    if rule not in ("median", "trimmed_mean", "krum", "multikrum"):
        raise ValueError(f"unknown robust pod rule {rule!r}")
    # the ONE leaf/scoring definition shared with the host rules — parity
    # by construction, not by synchronized copies
    from metisfl_tpu.aggregation.robust import (
        Krum,
        _krum_scores,
        median_leaf,
        trimmed_mean_leaf,
    )

    if rule in ("krum", "multikrum"):
        L = mesh.shape[axis_name]
        host_rule = Krum(byzantine_f=byzantine_f, multi=multi, name=rule)
        f = host_rule._effective_f(L)
        m = host_rule._select_count(L)

        def combine(stacked):
            flat = jnp.concatenate(
                [s.astype(jnp.float32).reshape(s.shape[0], -1)
                 for s in jax.tree.leaves(stacked)], axis=1)
            scores = _krum_scores(flat, f)
            picked = jnp.argsort(scores)[:m]

            def leaf(s):
                # take the m picked rows FIRST, then cast — touching m
                # models instead of an f32 copy of all L gathered ones
                sel = jnp.take(s, picked, axis=0).astype(_acc(s.dtype))
                return sel.mean(axis=0).astype(s.dtype)

            return jax.tree.map(leaf, stacked)
    else:
        def combine(stacked):
            def leaf(s):
                acc = s.astype(_acc(s.dtype))
                r = (median_leaf(acc) if rule == "median"
                     else trimmed_mean_leaf(acc, trim))
                return r.astype(s.dtype)

            return jax.tree.map(leaf, stacked)

    return jax.jit(combine, out_shardings=NamedSharding(mesh, P()))


def replicate_to_fed(mesh: Mesh, params, axis_name: str = "fed"):
    """Place a host pytree fully replicated on the mesh."""
    sharding = NamedSharding(mesh, P())
    return jax.device_put(params, sharding)
