"""Ulysses-style all-to-all sequence parallelism over the ``sp`` axis.

The second canonical long-context strategy next to ring attention
(parallel/ringattn.py — the reference has neither, SURVEY.md §5.7).
DeepSpeed-Ulysses (Jacobs et al.) re-shards INSIDE the attention op: the
inputs arrive sequence-sharded (each device holds L/sp of every head);
all_to_alls scatter heads and gather sequence so each device holds the
FULL sequence for H/sp heads, attention runs entirely locally (no
per-step communication), and a final all_to_all restores sequence
sharding. Exact attention, four collectives per call (q/k/v scatters +
the output gather), each moving one activation's worth of data once.

Trade-offs vs the ring:
- communication: 4 single-shot all-to-alls (q, k, v, o) vs ``sp - 1``
  ppermute hops of K/V — Ulysses moves less total data once
  ``2·(sp - 1) > 4`` per-activation transfers, i.e. sp ≥ 4 for MHA; the
  ring wins for GQA long-context (its K/V hops ride at kv-head size,
  while Ulysses' q/o legs are always full-width).
- memory: Ulysses holds the full L per device (O(L·D·H/sp)) — the local
  attention still avoids the (L, L) matrix via the routed flash kernel —
  while the ring keeps O(L/sp) activations end to end.
- parallel degree: Ulysses caps at the head count (sp must divide H);
  the ring scales with the sequence itself.

The local attention reuses :func:`metisfl_tpu.ops.flash_attention
.attention` (seq-length-routed dense/flash, GQA-native), so its FA2
accumulator and causal DMA elision apply here too. Grouped-query inputs
scatter at kv-head size when ``Hkv % sp == 0`` (the head ranges align with
the query groups); otherwise K/V are broadcast to query-head count first.

Differentiation is plain autodiff: all_to_all transposes to all_to_all and
the local attention brings its own VJP — no custom ring backward needed.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from metisfl_tpu.ops.flash_attention import attention


def make_ulysses_attention(mesh: Mesh, axis_name: str = "sp",
                           causal: bool = False,
                           min_flash_seq: Optional[int] = None):
    """shard_map-wrapped Ulysses attention over GLOBAL (B, H, L, D) arrays
    with the L dimension sharded over ``axis_name``. Same calling contract
    as :func:`parallel.ringattn.make_ring_attention` — the two strategies
    are drop-in alternatives."""
    sp = mesh.shape[axis_name]
    spec = P(None, None, axis_name, None)

    def fn(q, k, v):
        H, Hkv = q.shape[1], k.shape[1]
        if H % sp:
            raise ValueError(
                f"ulysses parallelism degree ({axis_name}={sp}) must "
                f"divide the query head count ({H}); use ring attention "
                "to scale past the head count")
        if Hkv % sp:
            # head ranges would not align with the query groups after the
            # scatter: broadcast K/V to query-head count (costs the GQA
            # bandwidth saving on this path; the ring keeps it)
            group = H // Hkv
            k_full = jnp.repeat(k, group, axis=1)
            v_full = jnp.repeat(v, group, axis=1)
        else:
            k_full, v_full = k, v

        def scatter_heads(x):
            # (B, H', L/sp, D) -> (B, H'/sp, L, D)
            return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                      concat_axis=2, tiled=True)

        qh = scatter_heads(q)
        kh = scatter_heads(k_full)
        vh = scatter_heads(v_full)
        o = attention(qh, kh, vh, causal, min_flash_seq=min_flash_seq)
        # (B, H/sp, L, D) -> (B, H, L/sp, D)
        return jax.lax.all_to_all(o, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)
