"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

Long-context training support the reference entirely lacks (SURVEY.md §5.7:
no sequence-parallel story; its zoo tops out at an LSTM). Design follows
blockwise ring attention (Liu et al.): the sequence dimension is sharded
over ``sp``; each device holds one Q chunk and rotates the K/V chunks around
the ring with ``ppermute`` (one hop per step — the transfer rides ICI and
overlaps with the local block matmul), accumulating exact softmax statistics
online (flash-attention style m/l/o carry). The result is mathematically
EXACT attention over the full sequence with per-device memory O(L/sp) —
attention never materializes an (L, L) matrix on any chip.

Differentiable with flash-style memory: the forward saves only the local
(q, k, v, o, logsumexp) — O(L/sp·D) per device — and a custom VJP re-runs
the ring in backward, rotating K/V again and shipping the dK/dV
accumulators around with their blocks. (Plain autodiff through the forward
scan would checkpoint the rotated K/V carries at every hop: O(L·D) per
device, defeating sequence parallelism exactly when it matters.)
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

_NEG = -1e30


def _axis_size(axis_name: str) -> int:
    return jax.lax.axis_size(axis_name)


def _group(q, kv_heads: int):
    """(B, Hq, Lc, D) → (B, Hkv, G, Lc, D); Hq = Hkv·G (grouped-query)."""
    B, Hq, Lc, D = q.shape
    if Hq % kv_heads:
        raise ValueError(
            f"query heads ({Hq}) must be a multiple of KV heads ({kv_heads})")
    return q.reshape(B, kv_heads, Hq // kv_heads, Lc, D)


def _ring_forward(q, k, v, axis_name: str, causal: bool):
    """Online-softmax ring forward → (normalized out [q.dtype], lse [f32]).

    Supports grouped-query attention natively: ``k``/``v`` may carry fewer
    heads than ``q`` (Hq a multiple of Hkv) — the K/V blocks rotate around
    the ring AT KV-HEAD SIZE, so GQA's bandwidth saving applies to the ICI
    traffic itself, not just the projections."""
    n = _axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    B, Hq, Lc, D = q.shape
    Hkv = k.shape[1]
    qg = _group(q, Hkv)                                     # (B,Hkv,G,Lc,D)
    scale = float(1.0 / np.sqrt(D))  # python float: weak type, no f64 promotion
    q_pos = my_idx * Lc + jnp.arange(Lc)                    # global q positions

    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, i):
        o, m, l, k_blk, v_blk = carry
        # after i forward rotations we hold the block produced by (my - i)
        owner = (my_idx - i) % n
        k_pos = owner * Lc + jnp.arange(Lc)

        def attend(args):
            o, m, l = args
            # scores + online statistics in fp32 regardless of the compute
            # dtype — bf16 exp/normalize across ring steps compounds; the
            # score/PV matmuls still run MXU-native on the input dtype
            s = jnp.einsum("bhgqd,bhkd->bhgqk", qg, k_blk,
                           preferred_element_type=jnp.float32) * scale
            if causal:
                mask = q_pos[:, None] >= k_pos[None, :]     # (Lc, Lc)
                s = jnp.where(mask, s, _NEG)
            m_new = jnp.maximum(m, s.max(axis=-1))
            p = jnp.exp(s - m_new[..., None])
            if causal:
                # rows whose whole block is masked would otherwise get
                # exp(NEG - NEG) = 1 contributions
                p = jnp.where(mask, p, 0.0)
            corr = jnp.exp(m - m_new)
            l_new = l * corr + p.sum(axis=-1)
            o_new = o * corr[..., None] + jnp.einsum(
                "bhgqk,bhkd->bhgqd", p.astype(v_blk.dtype), v_blk,
                preferred_element_type=jnp.float32)
            return o_new, m_new, l_new

        if causal:
            # blocks strictly in the future are entirely masked: skip their
            # matmuls (halves the causal ring's FLOPs; the K/V rotation
            # below still runs so the ring stays in step)
            o, m, l = jax.lax.cond(owner > my_idx,
                                   lambda args: args, attend, (o, m, l))
        else:
            o, m, l = attend((o, m, l))
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return (o, m, l, k_next, v_next), None

    o0 = jnp.zeros(qg.shape, jnp.float32)
    m0 = jnp.full(qg.shape[:4], _NEG, jnp.float32)
    l0 = jnp.zeros(qg.shape[:4], jnp.float32)
    (o, m, l, _, _), _ = jax.lax.scan(
        step, (o0, m0, l0, k, v), jnp.arange(n))
    l_safe = jnp.maximum(l, 1e-30)
    out = (o / l_safe[..., None]).astype(q.dtype).reshape(B, Hq, Lc, D)
    lse = (m + jnp.log(l_safe)).reshape(B, Hq, Lc)
    return out, lse


def _ring_backward(q, k, v, o, lse, g, axis_name: str, causal: bool):
    """Flash-style ring backward. dQ accumulates locally; dK/dV accumulators
    ride the ring WITH their K/V blocks (one extra ppermute pair per hop)
    and arrive home after the full rotation. Probabilities are recomputed
    from the forward's lse — nothing quadratic, and nothing O(L·D) beyond
    the local chunks, is ever stored."""
    n = _axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    B, Hq, Lc, D = q.shape
    Hkv = k.shape[1]
    qg = _group(q, Hkv)
    gg = _group(g, Hkv)
    scale = float(1.0 / np.sqrt(D))
    q_pos = my_idx * Lc + jnp.arange(Lc)
    # delta_i = rowsum(dO_i * O_i) — the softmax-normalization cotangent
    delta = jnp.sum(gg.astype(jnp.float32)
                    * _group(o, Hkv).astype(jnp.float32),
                    axis=-1)                                # (B,Hkv,G,Lc)
    lse_g = lse.reshape(delta.shape)

    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, i):
        dq, k_blk, v_blk, dk_blk, dv_blk = carry
        owner = (my_idx - i) % n
        k_pos = owner * Lc + jnp.arange(Lc)

        def compute(args):
            dq, dk_blk, dv_blk = args
            s = jnp.einsum("bhgqd,bhkd->bhgqk", qg, k_blk,
                           preferred_element_type=jnp.float32) * scale
            if causal:
                mask = q_pos[:, None] >= k_pos[None, :]
                s = jnp.where(mask, s, _NEG)
            # masked scores are exactly _NEG and lse is finite (every causal
            # row attends at least its diagonal), so exp underflows to 0.0
            # — no second mask needed, unlike the forward's exp(s - m_new)
            p = jnp.exp(s - lse_g[..., None])               # (B,Hkv,G,Lq,Lk)
            # dV_blk += sum over the group of P^T @ dO
            dv_blk = dv_blk + jnp.einsum(
                "bhgqk,bhgqd->bhkd", p.astype(g.dtype), gg,
                preferred_element_type=jnp.float32)
            dp = jnp.einsum("bhgqd,bhkd->bhgqk", gg, v_blk,
                            preferred_element_type=jnp.float32)
            ds = p * (dp - delta[..., None]) * scale
            ds_c = ds.astype(q.dtype)
            dq = dq + jnp.einsum("bhgqk,bhkd->bhgqd", ds_c, k_blk,
                                 preferred_element_type=jnp.float32)
            # dK_blk += sum over the group of dS^T @ Q
            dk_blk = dk_blk + jnp.einsum(
                "bhgqk,bhgqd->bhkd", ds_c, qg,
                preferred_element_type=jnp.float32)
            return dq, dk_blk, dv_blk

        if causal:
            # fully-masked future blocks contribute nothing to any gradient
            dq, dk_blk, dv_blk = jax.lax.cond(
                owner > my_idx, lambda args: args, compute,
                (dq, dk_blk, dv_blk))
        else:
            dq, dk_blk, dv_blk = compute((dq, dk_blk, dv_blk))
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        dk_next = jax.lax.ppermute(dk_blk, axis_name, perm)
        dv_next = jax.lax.ppermute(dv_blk, axis_name, perm)
        return (dq, k_next, v_next, dk_next, dv_next), None

    zeros_kv = jnp.zeros((B, Hkv, Lc, D), jnp.float32)
    (dq, _, _, dk, dv), _ = jax.lax.scan(
        step, (jnp.zeros(qg.shape, jnp.float32), k, v,
               zeros_kv, zeros_kv), jnp.arange(n))
    # n rotations = identity: each dK/dV accumulator is home again
    return (dq.astype(q.dtype).reshape(B, Hq, Lc, D),
            dk.astype(k.dtype), dv.astype(v.dtype))


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def ring_attention(q, k, v, axis_name: str = "sp", causal: bool = False):
    """Exact attention over the ring. Call INSIDE ``shard_map``.

    Args: ``q`` of shape (B, Hq, Lc, D); ``k``/``v`` of shape
    (B, Hkv, Lc, D) with Hq a multiple of Hkv (grouped-query attention —
    K/V rotate the ring at KV-head size, so GQA's bandwidth saving applies
    to the ICI traffic). The LOCAL sequence chunk: the global length is
    ``Lc * axis_size(sp)`` and chunk ``i`` holds positions
    ``[i*Lc, (i+1)*Lc)``. Training memory is O(Lc·D): the VJP re-rotates
    K/V instead of checkpointing ring carries.
    """
    out, _ = _ring_forward(q, k, v, axis_name, causal)
    return out


def _ring_fwd(q, k, v, axis_name, causal):
    out, lse = _ring_forward(q, k, v, axis_name, causal)
    return out, (q, k, v, out, lse)


def _ring_bwd(axis_name, causal, res, g):
    q, k, v, out, lse = res
    return _ring_backward(q, k, v, out, lse, g, axis_name, causal)


ring_attention.defvjp(_ring_fwd, _ring_bwd)


def _ring_forward_pallas(q, k, v, axis_name: str, causal: bool):
    """Blockwise-kernel ring forward: every hop's local attention runs the
    pallas flash kernel (ops/flash_attention.py) instead of XLA einsums, so
    no (Lc, Lc) score matrix is ever materialized — not even transiently —
    and per-hop results merge through their logsumexps:

        lse' = logaddexp(lse, lse_b)
        o'   = o·exp(lse−lse') + o_b·exp(lse_b−lse')

    The diagonal hop runs the causal kernel; prior-chunk hops run full
    attention; future chunks are skipped whole. GQA passes straight
    through (the flash kernels are GQA-native). Same (out, lse) contract
    as :func:`_ring_forward`, so the standard ring backward applies."""
    from metisfl_tpu.ops.flash_attention import _flash_forward

    n = _axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    B, Hq, Lc, D = q.shape
    interpret = jax.default_backend() != "tpu"
    perm = [(j, (j + 1) % n) for j in range(n)]

    def block(k_blk, v_blk, blk_causal: bool):
        o_b, lse_b = _flash_forward(q, k_blk, v_blk, blk_causal,
                                    None, None, interpret)
        # kernel lse layout: (B*Hq, Lp, STAT_LANES), lanes replicated
        lse_b = lse_b[:, :Lc, 0].reshape(B, Hq, Lc)
        return o_b.astype(jnp.float32), lse_b

    # hop 0: the diagonal chunk (causal iff the whole attention is), then
    # one rotation — the transfer overlaps the peeled hop's kernel
    o, lse = block(k, v, causal)
    k_blk = jax.lax.ppermute(k, axis_name, perm)
    v_blk = jax.lax.ppermute(v, axis_name, perm)

    def step(carry, i):
        # compute on the CARRIED block and rotate at the end: the kernel
        # and the next hop's ICI transfer consume the same block
        # independently, so they overlap (transfer-then-compute would
        # serialize every hop into comm + compute)
        o, lse, k_blk, v_blk = carry
        owner = (my_idx - i) % n

        def merge(args):
            o, lse = args
            o_b, lse_b = block(k_blk, v_blk, False)
            lse_new = jnp.logaddexp(lse, lse_b)
            w_old = jnp.exp(lse - lse_new)[..., None]
            w_new = jnp.exp(lse_b - lse_new)[..., None]
            return o * w_old + o_b * w_new, lse_new

        if causal:
            o, lse = jax.lax.cond(owner > my_idx, lambda args: args, merge,
                                  (o, lse))
        else:
            o, lse = merge((o, lse))
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return (o, lse, k_next, v_next), None

    (o, lse, _, _), _ = jax.lax.scan(step, (o, lse, k_blk, v_blk),
                                     jnp.arange(1, n))
    return o.astype(q.dtype), lse


def _ring_backward_pallas(q, k, v, o, lse, g, axis_name: str, causal: bool):
    """Blockwise-kernel ring backward: each hop runs the pallas dQ and
    dK/dV kernels (ops/flash_attention.py ``_flash_backward``) against the
    visiting K/V block, with the forward's GLOBAL logsumexp — so like the
    forward, no (Lc, Lc) tensor is ever materialized. dQ accumulates
    locally in fp32; per-block dK/dV accumulators ride the ring with their
    blocks (n rotations = identity)."""
    from metisfl_tpu.ops.flash_attention import _flash_backward

    n = _axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    B, Hq, Lc, D = q.shape
    interpret = jax.default_backend() != "tpu"
    perm = [(j, (j + 1) % n) for j in range(n)]

    # hop-invariant: precompute once instead of per hop inside the scan
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    def hop_grads(k_blk, v_blk, blk_causal: bool):
        dq_b, dk_b, dv_b = _flash_backward(q, k_blk, v_blk, o, lse, g,
                                           blk_causal, None, None, interpret,
                                           delta=delta)
        return (dq_b.astype(jnp.float32), dk_b.astype(jnp.float32),
                dv_b.astype(jnp.float32))

    # hop 0: diagonal block, then one rotation (overlaps the peeled hop)
    dq, dk_blk, dv_blk = hop_grads(k, v, causal)
    k_blk = jax.lax.ppermute(k, axis_name, perm)
    v_blk = jax.lax.ppermute(v, axis_name, perm)
    dk_blk = jax.lax.ppermute(dk_blk, axis_name, perm)
    dv_blk = jax.lax.ppermute(dv_blk, axis_name, perm)

    def step(carry, i):
        dq, k_blk, v_blk, dk_blk, dv_blk = carry
        owner = (my_idx - i) % n

        def compute(args):
            dq, dk_blk, dv_blk = args
            dq_b, dk_b, dv_b = hop_grads(k_blk, v_blk, False)
            return dq + dq_b, dk_blk + dk_b, dv_blk + dv_b

        if causal:
            dq, dk_blk, dv_blk = jax.lax.cond(
                owner > my_idx, lambda args: args, compute,
                (dq, dk_blk, dv_blk))
        else:
            dq, dk_blk, dv_blk = compute((dq, dk_blk, dv_blk))
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        dk_next = jax.lax.ppermute(dk_blk, axis_name, perm)
        dv_next = jax.lax.ppermute(dv_blk, axis_name, perm)
        return (dq, k_next, v_next, dk_next, dv_next), None

    (dq, _, _, dk, dv), _ = jax.lax.scan(
        step, (dq, k_blk, v_blk, dk_blk, dv_blk), jnp.arange(1, n))
    # hop 0's rotation + (n-1) scan rotations = n = identity: home again
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def ring_attention_pallas(q, k, v, axis_name: str = "sp",
                          causal: bool = False):
    """`ring_attention` with pallas flash kernels for each hop's block
    attention (O(blk·D) VMEM working set per hop instead of a transient
    (Lc, Lc) HBM score matrix) — in the FORWARD AND THE BACKWARD, which
    runs the pallas dQ/dKV kernels per hop. Call INSIDE ``shard_map``;
    same semantics as the einsum ring. Per-hop block outputs/gradients are
    rounded to the io dtype once per hop before the fp32 merge (the einsum
    ring carries unnormalized fp32 statistics instead), so bf16 error
    grows mildly with the ring size."""
    out, _ = _ring_forward_pallas(q, k, v, axis_name, causal)
    return out


def _ring_pallas_fwd(q, k, v, axis_name, causal):
    out, lse = _ring_forward_pallas(q, k, v, axis_name, causal)
    return out, (q, k, v, out, lse)


def _ring_pallas_bwd(axis_name, causal, res, g):
    q, k, v, out, lse = res
    return _ring_backward_pallas(q, k, v, out, lse, g, axis_name, causal)


ring_attention_pallas.defvjp(_ring_pallas_fwd, _ring_pallas_bwd)


def make_ring_attention(mesh: Mesh, axis_name: str = "sp",
                        causal: bool = False, block_kernels: bool = False):
    """shard_map-wrapped ring attention over GLOBAL (B, H, L, D) arrays with
    the L dimension sharded over ``axis_name``. Usable directly under jit —
    GSPMD handles the surrounding program, the shard_map island runs the
    ring schedule. ``block_kernels=True`` runs each hop's block attention
    as a pallas flash kernel (long-Lc configs where even one chunk's
    score matrix is too big to materialize)."""
    spec = P(None, None, axis_name, None)
    fn = ring_attention_pallas if block_kernels else ring_attention
    return jax.shard_map(
        # positional call: custom_vjp functions reject keyword arguments
        # under differentiation
        lambda q, k, v: fn(q, k, v, axis_name, causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)


def reference_attention(q, k, v, causal: bool = False):
    """Plain full attention (the correctness oracle for the ring path)."""
    D = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * float(1.0 / np.sqrt(D))
    if causal:
        L = q.shape[2]
        mask = jnp.tril(jnp.ones((L, L), bool))
        s = jnp.where(mask, s, _NEG)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)
