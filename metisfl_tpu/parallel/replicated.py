"""Multi-host learner execution: rank 0 leads, follower ranks replay.

A learner that owns a multi-host TPU slice runs ONE process per host under
``jax.distributed`` (env-configured — see ``platform.maybe_init_distributed``).
Every process must execute the same jit programs in the same order for the
slice's cross-host collectives to rendezvous. Only rank 0 talks to the
federation (gRPC servicer, controller RPCs); this module makes the other
ranks follow it:

- ``lead(model_ops, datasets)`` wraps rank 0's engine. Each compute call
  (``set_variables`` / ``train`` / ``evaluate`` / ``infer``) first
  broadcasts an opcode + its arguments to all ranks (over the JAX
  distributed runtime itself — no extra sockets), then runs locally; the
  global-mesh collectives inside the computation line up with the
  followers'.
- ``follower_loop(model_ops, datasets)`` is the whole life of a follower
  rank: receive, replay, repeat, until the leader broadcasts shutdown.

The reference has no multi-host execution at all (its learner is one
process per silo, SURVEY.md §2.3); this is the TPU-native scale-out for
the in-learner sharded configs (Llama-LoRA and up).

Constraints (asserted loudly, not silently wrong):
- every rank's recipe must build the same module/mesh/datasets-by-name,
  with per-name dataset lengths equal across ranks — step counts and eval
  batch shapes derive from them, and a mismatch would desynchronize the
  compiled programs;
- mid-task cancellation is disabled in multi-host mode (a rank-0-only
  cancel between steps would leave followers running ahead into a
  collective no one else joins).
"""

from __future__ import annotations

import dataclasses
import io
import logging
import threading
from typing import Dict, Optional

import numpy as np

logger = logging.getLogger("metisfl_tpu.parallel.replicated")

_SHUTDOWN = "shutdown"


def _world():
    import jax
    return jax.process_count(), jax.process_index()


def broadcast_bytes(data: Optional[bytes]) -> bytes:
    """Broadcast a byte string from rank 0 to every rank. All ranks must
    call this in step; followers pass ``None``. Two collective hops: the
    length (fixed shape), then the padded payload."""
    from jax.experimental import multihost_utils

    if data is not None and len(data) >= 2**31:
        # with jax_enable_x64 off (the default) the collective carries
        # int32 — a longer length would silently wrap
        raise ValueError(
            f"broadcast payload of {len(data)} bytes exceeds the int32 "
            "length limit; ship the model in parts")
    n_local = np.asarray([0 if data is None else len(data)], np.int64)
    n = int(multihost_utils.broadcast_one_to_all(n_local)[0])
    buf = np.zeros((n,), np.uint8)
    if data is not None:
        if len(data) != n:  # pragma: no cover - rank-0 invariant
            raise RuntimeError("broadcast length desync")
        buf = np.frombuffer(data, np.uint8).copy()
    out = multihost_utils.broadcast_one_to_all(buf)
    return out.tobytes()


def _send(msg: dict) -> None:
    from metisfl_tpu.comm.codec import dumps
    broadcast_bytes(dumps(msg))


def _recv() -> dict:
    from metisfl_tpu.comm.codec import loads
    return loads(broadcast_bytes(None))


def _np_dumps(arr: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, np.asarray(arr), allow_pickle=False)
    return buf.getvalue()


def _np_loads(data: bytes) -> np.ndarray:
    return np.load(io.BytesIO(data), allow_pickle=False)


class LeaderOps:
    """Rank-0 wrapper around ``FlaxModelOps``: broadcast, then compute."""

    def __init__(self, inner, datasets: Dict[str, object]):
        self.inner = inner
        # ONE lock serializes every (broadcast + compute) pair: followers
        # replay strictly in order, so concurrent leader calls (train on the
        # task executor, eval on a digest thread, shutdown from main) must
        # not interleave their collectives — interleaving desynchronizes
        # the ring and deadlocks gloo
        self._lock = threading.Lock()
        self._warned_cancel = False
        # a leader-side failure AFTER an op was broadcast means followers
        # completed work the leader did not (their dataset streams advanced
        # past the leader's) — the world is desynchronized. Per the module
        # contract, fail every subsequent call loudly instead of silently
        # training on mismatched batch streams.
        self._poisoned: Optional[str] = None
        # strong ref: keeps the object alive so the `is` identity check in
        # evaluate() can never alias a recycled id
        self._last_eval_vars = None
        self._datasets = {name: ds for name, ds in datasets.items()
                          if ds is not None}
        self._names_by_id = {id(ds): name for name, ds in
                             self._datasets.items()}

    # -- passthroughs ------------------------------------------------------
    @property
    def variables(self):
        return self.inner.variables

    def get_variables(self):
        return self.inner.get_variables()

    @property
    def module(self):
        return self.inner.module

    def _check_poisoned(self) -> None:
        if self._poisoned is not None:
            raise RuntimeError(
                "multi-host world desynchronized by an earlier leader-side "
                f"failure ({self._poisoned}); restart the learner world")

    def _run_replicated(self, fn, what: str):
        """Leader-local compute right after its broadcast: a failure here is
        a world desync (followers ran it, we did not) — poison the wrapper
        so nothing silently continues."""
        try:
            return fn()
        except BaseException as exc:
            self._poisoned = f"{what}: {exc!r}"[:300]
            logger.error("leader-side %s failed after broadcast; "
                         "poisoning the world", what)
            raise

    # -- replicated calls --------------------------------------------------
    def set_variables(self, variables) -> None:
        from metisfl_tpu.tensor.pytree import pack_model
        with self._lock:
            self._check_poisoned()
            _send({"op": "set_variables", "blob": pack_model(variables)})
            self._run_replicated(
                lambda: self.inner.set_variables(variables), "set_variables")

    def _dataset_name(self, ds) -> str:
        name = self._names_by_id.get(id(ds))
        if name is None:
            raise ValueError(
                "multi-host training requires datasets registered at wrap "
                f"time; got an unknown dataset object (have "
                f"{sorted(self._datasets)})")
        return name

    def train(self, dataset, params_cfg, cancel_event=None):
        name = self._dataset_name(dataset)
        if cancel_event is not None and not self._warned_cancel:
            # once per wrapper: the federation path passes a cancel event
            # on EVERY task, and a per-call warning would bury real ones
            self._warned_cancel = True
            logger.warning(
                "multi-host mode: mid-task cancellation disabled (a rank-0 "
                "cancel would desynchronize follower collectives)")
        with self._lock:
            self._check_poisoned()
            _send({"op": "train", "dataset": name,
                   "expected_len": len(dataset),
                   "params": dataclasses.asdict(params_cfg)})
            return self._run_replicated(
                lambda: self.inner.train(dataset, params_cfg,
                                         cancel_event=None), "train")

    def evaluate(self, dataset, batch_size: int = 256, metrics=None,
                 variables=None):
        from metisfl_tpu.tensor.pytree import pack_model
        name = self._dataset_name(dataset)
        with self._lock:
            self._check_poisoned()
            # an EvalTask evaluates the SAME variables once per dataset
            # (learner.py evaluate loop) — re-broadcasting a Llama-scale
            # blob per dataset would triple the cross-host bytes, so repeat
            # trees (checked by identity against a strong ref) ship as a
            # "reuse the last ones" marker instead
            cached = variables is not None and variables is self._last_eval_vars
            msg = {"op": "evaluate", "dataset": name,
                   "expected_len": len(dataset),
                   "batch_size": int(batch_size),
                   "metrics": list(metrics or []),
                   "vars_cached": cached,
                   "blob": b"" if (cached or variables is None)
                   else pack_model(variables)}
            _send(msg)
            if variables is not None:
                self._last_eval_vars = variables
            return self._run_replicated(
                lambda: self.inner.evaluate(dataset, batch_size, metrics,
                                            variables=variables), "evaluate")

    def infer(self, x, batch_size: int = 256, variables=None):
        from metisfl_tpu.tensor.pytree import pack_model
        with self._lock:
            self._check_poisoned()
            _send({"op": "infer", "x": _np_dumps(x),
                   "batch_size": int(batch_size),
                   "blob": pack_model(variables) if variables is not None
                   else b""})
            return self._run_replicated(
                lambda: self.inner.infer(x, batch_size, variables=variables),
                "infer")

    def generate(self, prompt, max_new_tokens: int, variables=None,
                 **sampling):
        """Replicated KV-cache decoding: every rank runs the same jitted
        decode program (sharded-model collectives must rendezvous), and the
        sampling rng comes from each rank's engine — identical seeds and
        identical call order keep the streams in lockstep."""
        from metisfl_tpu.tensor.pytree import pack_model
        with self._lock:
            self._check_poisoned()
            if sampling.get("rng") is not None:
                raise ValueError(
                    "multi-host generate cannot take an explicit rng (it is "
                    "not broadcast); seed the engines identically instead")
            _send({"op": "generate", "prompt": _np_dumps(prompt),
                   "max_new_tokens": int(max_new_tokens),
                   "sampling": {k: v for k, v in sampling.items()
                                if v is not None},
                   "blob": pack_model(variables) if variables is not None
                   else b""})
            return self._run_replicated(
                lambda: self.inner.generate(prompt, max_new_tokens,
                                            variables=variables, **sampling),
                "generate")

    def shutdown_replicas(self) -> None:
        """Release follower ranks (their loop returns). Waits for any
        in-flight replicated call so the shutdown broadcast cannot
        interleave with its collectives."""
        with self._lock:
            _send({"op": _SHUTDOWN})


def lead(model_ops, datasets: Dict[str, object]):
    """Wrap rank 0's engine for multi-host replay; identity in a
    single-process world (no broadcast overhead)."""
    count, index = _world()
    if count == 1:
        return model_ops
    if index != 0:
        raise RuntimeError("lead() is for rank 0; followers run "
                           "follower_loop()")
    return LeaderOps(model_ops, datasets)


def follower_loop(model_ops, datasets: Dict[str, object]) -> None:
    """Replay the leader's compute calls until shutdown. The entire life of
    a follower rank."""
    from metisfl_tpu.tensor.pytree import unpack_model

    count, index = _world()
    if index == 0:
        raise RuntimeError("follower_loop() is for ranks > 0")
    datasets = {name: ds for name, ds in datasets.items() if ds is not None}
    last_eval_vars = None   # mirrors the leader's eval-variables cache
    logger.info("follower rank %d/%d ready", index, count)
    while True:
        msg = _recv()
        op = msg["op"]
        if op == _SHUTDOWN:
            logger.info("follower rank %d shutting down", index)
            return
        if op == "set_variables":
            model_ops.set_variables(
                unpack_model(msg["blob"], model_ops.variables))
            continue
        ds = datasets.get(msg["dataset"]) if "dataset" in msg else None
        if "dataset" in msg:
            if ds is None:
                raise RuntimeError(
                    f"leader referenced dataset {msg['dataset']!r} that "
                    f"this rank does not hold (have {sorted(datasets)})")
            if len(ds) != msg["expected_len"]:
                raise RuntimeError(
                    f"dataset {msg['dataset']!r} length mismatch: leader "
                    f"{msg['expected_len']}, rank {index} {len(ds)} — "
                    "programs would desynchronize")
        if op == "train":
            from metisfl_tpu.comm.messages import TrainParams
            params = TrainParams(**msg["params"])
            if params.profile_dir:
                # leader-relative paths do not exist here
                params = dataclasses.replace(params, profile_dir="")
            model_ops.train(ds, params, cancel_event=None)
        elif op == "evaluate":
            if msg.get("vars_cached"):
                if last_eval_vars is None:
                    raise RuntimeError(
                        "leader marked eval variables as cached but this "
                        "rank holds none — replay desynchronized")
                variables = last_eval_vars
            elif msg["blob"]:
                variables = unpack_model(msg["blob"], model_ops.variables)
                last_eval_vars = variables
            else:
                variables = None
            model_ops.evaluate(ds, msg["batch_size"],
                               list(msg["metrics"]) or None,
                               variables=variables)
        elif op == "infer":
            variables = (unpack_model(msg["blob"], model_ops.variables)
                         if msg["blob"] else None)
            model_ops.infer(_np_loads(msg["x"]), msg["batch_size"],
                            variables=variables)
        elif op == "generate":
            variables = (unpack_model(msg["blob"], model_ops.variables)
                         if msg["blob"] else None)
            model_ops.generate(_np_loads(msg["prompt"]),
                               msg["max_new_tokens"],
                               variables=variables, **msg["sampling"])
        else:  # pragma: no cover - future ops
            raise RuntimeError(f"unknown replicated op {op!r}")
