"""Pod-mode federation: the whole round as one SPMD program.

When all learners co-reside on one TPU pod slice, a federation round —
N learners × K local optimizer steps, then weighted FedAvg — compiles to a
SINGLE jit-compiled XLA program shard_mapped over the ``fed`` mesh axis:

- learner *i*'s params/data live on mesh slice ``fed=i``;
- local training is a ``lax.scan`` of SGD steps (MXU-friendly, no host);
- aggregation is a weighted ``psum`` over ``fed`` riding ICI;
- the community model comes out replicated: next round starts immediately.

This is the TPU-native answer to the reference's proto-gRPC weight shipping
(BASELINE.json north star: ≤2 s aggregation/round @ 64 learners) — the
controller shrinks to round bookkeeping around one XLA call. An inner ``dp``
mesh axis composes: each learner's local batch shards over ``dp`` and its
gradients all-reduce over ``dp`` inside every local step (classic DP within
the federated round).

Semantics match the host path (`FlaxModelOps.train` + `FedAvg`): fresh
optimizer state per round (local SGD starts from the community model),
dropout rngs folded per learner and step, BatchNorm ``batch_stats``
aggregated with the weights.
"""

from __future__ import annotations

import functools
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from metisfl_tpu.comm.messages import TrainParams
from metisfl_tpu.models.ops import _LOSSES, _accuracy
from metisfl_tpu.models.optimizers import make_optimizer
from metisfl_tpu.parallel.collectives import to_varying
from metisfl_tpu.parallel.mesh import federation_mesh


class PodFederation:
    """N co-resident learners on one mesh; rounds are single XLA calls.

    ``mesh`` may carry an inner ``dp`` axis (e.g. ``federation_mesh(4,
    inner_axes=("dp",), inner_sizes=(2,))``): each learner's batch dimension
    shards over ``dp`` and gradients all-reduce over it per local step.
    """

    def __init__(
        self,
        module,
        sample_input: np.ndarray,
        num_learners: int,
        train_params: Optional[TrainParams] = None,
        loss: str | Callable = "softmax_cross_entropy",
        mesh: Optional[Mesh] = None,
        rng_seed: int = 0,
        rule: str = "fedavg",
        trim_ratio: float = 0.1,
        byzantine_f: int = 0,
        multi: int = 0,
    ):
        # rule="median"/"trimmed_mean"/"krum"/"multikrum": byzantine-robust
        # aggregation WITHOUT leaving the device mesh — the round's psum is
        # replaced by an all-gather + coordinate sort (or Krum's Gram-
        # matmul distance selection) over `fed` (collectives.
        # make_robust_pod_combine); scales are ignored by construction,
        # matching the host rules (aggregation/robust.py)
        if rule not in ("fedavg", "median", "trimmed_mean", "krum",
                        "multikrum"):
            raise ValueError(f"unknown pod aggregation rule {rule!r}")
        if rule not in ("krum", "multikrum") and (byzantine_f or multi):
            # silently-ignored tolerance knobs read as guarantees that are
            # not in effect
            raise ValueError(
                f"byzantine_f/multi only apply to the krum rules, not "
                f"rule={rule!r}")
        self.rule = rule
        self.module = module
        self.num_learners = num_learners
        self.train_params = train_params or TrainParams()
        self.loss_fn = _LOSSES[loss] if isinstance(loss, str) else loss
        self.mesh = mesh or federation_mesh(num_learners)
        if rule != "fedavg":
            from metisfl_tpu.aggregation.robust import TrimmedMean

            from metisfl_tpu.parallel.collectives import \
                make_robust_pod_combine

            trim = (TrimmedMean(trim_ratio)._trim(num_learners)
                    if rule == "trimmed_mean" else 0)
            self._robust_combine = make_robust_pod_combine(
                self.mesh, rule, trim=trim, byzantine_f=byzantine_f,
                multi=multi)
        else:
            self._robust_combine = None
        if self.mesh.shape["fed"] != num_learners:
            raise ValueError(
                f"mesh fed axis {self.mesh.shape['fed']} != {num_learners}")
        self._has_dp = "dp" in self.mesh.axis_names
        # x: (L, K, B, ...) — learner axis over fed, batch axis over dp;
        # single source of truth for both the shard_map in_specs and the
        # run_round device_put placements
        self._data_spec = (P("fed", None, "dp") if self._has_dp
                           else P("fed"))
        rng = jax.random.PRNGKey(rng_seed)
        variables = module.init({"params": rng,
                                 "dropout": jax.random.fold_in(rng, 1)},
                                jnp.asarray(sample_input))
        self.params = jax.device_put(
            variables["params"], NamedSharding(self.mesh, P()))
        self.batch_stats = jax.device_put(
            variables["batch_stats"], NamedSharding(self.mesh, P())
        ) if "batch_stats" in variables else None
        self._tx = make_optimizer(self.train_params.optimizer,
                                  self.train_params.learning_rate,
                                  self.train_params.optimizer_kwargs)
        self._round_fn = self._build_round()
        self._eval_fn = None
        self.global_iteration = 0

    # ------------------------------------------------------------------ #

    def _apply(self, variables, x, train: bool, rngs=None):
        kwargs = {}
        try:
            import inspect
            if "train" in inspect.signature(self.module.__call__).parameters:
                kwargs["train"] = train
        except (TypeError, ValueError):  # pragma: no cover
            pass
        mutable = ["batch_stats"] if (train and self.batch_stats is not None) \
            else False
        return self.module.apply(variables, x, rngs=rngs, mutable=mutable,
                                 **kwargs)

    def _build_round(self):
        tx = self._tx
        loss_fn = self.loss_fn
        mesh = self.mesh
        has_dp = self._has_dp
        has_bs = self.batch_stats is not None

        def local_train(params, batch_stats, x_steps, y_steps, rng):
            """K local steps via lax.scan. x_steps: (K, B_local, ...)"""
            opt_state = tx.init(params)

            def step(carry, batch):
                params, batch_stats, opt_state, rng = carry
                x, y = batch
                rng, dropout_rng = jax.random.split(rng)

                def loss_of(p, bs):
                    variables = {"params": p}
                    if has_bs:
                        variables["batch_stats"] = bs
                    out = self._apply(variables, x, train=True,
                                      rngs={"dropout": dropout_rng})
                    if has_bs:
                        logits, mutated = out
                        new_bs = mutated["batch_stats"]
                    else:
                        logits, new_bs = out, bs
                    return loss_fn(logits, y), new_bs

                (loss, new_bs), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(params, batch_stats)
                if has_dp:
                    # true data parallelism inside the learner: the batch is
                    # sharded over dp, so grads/loss all-reduce over dp
                    # (batch_stats stay per-replica during the scan, like
                    # standard DP BatchNorm; they sync at round end)
                    grads = jax.lax.pmean(grads, "dp")
                    loss = jax.lax.pmean(loss, "dp")
                updates, opt_state = tx.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, new_bs, opt_state, rng), loss

            (params, batch_stats, _, _), losses = jax.lax.scan(
                step, (params, batch_stats, opt_state, rng),
                (x_steps, y_steps))
            return params, batch_stats, losses

        data_spec = self._data_spec
        axis_names = tuple(mesh.axis_names)
        robust = self.rule != "fedavg"
        # robust rules sort across the cohort, so the round emits each
        # learner's trained model stacked over `fed` and a second jitted
        # combine (all-gather + sort, still device-resident) replaces the
        # psum; fedavg keeps the single-program weighted-psum fast path
        model_spec = P("fed") if robust else P()

        @functools.partial(
            jax.shard_map, mesh=mesh,
            in_specs=(P(), P(), data_spec, data_spec, P("fed"), P("fed")),
            out_specs=(model_spec, model_spec, P("fed")),
        )
        def fed_round(community, batch_stats, x, y, scales, seeds):
            # Cast the replicated community model to device-varying BEFORE
            # local training: jax.grad w.r.t. an unvarying input inside
            # shard_map would psum the per-learner gradients across the whole
            # mesh (see parallel.collectives.to_varying).
            community = to_varying(community, axis_names)
            batch_stats = to_varying(batch_stats, axis_names)
            # this shard sees its own learner's data: leading axis 1
            rng = jax.random.PRNGKey(seeds[0])
            trained, new_bs, losses = local_train(
                community, batch_stats, x[0], y[0], rng)
            if robust:
                if has_dp:
                    trained = jax.tree.map(
                        lambda t: jax.lax.pmean(t, "dp"), trained)
                    new_bs = jax.tree.map(
                        lambda t: jax.lax.pmean(t, "dp"), new_bs)
                # stacked over fed (leading axis 1 per shard); scales are
                # ignored — the robust contract
                return (jax.tree.map(lambda t: t[None], trained),
                        jax.tree.map(lambda t: t[None], new_bs),
                        losses[None])
            scale = scales[0]
            community = jax.tree.map(
                lambda t: jax.lax.psum(t * scale, "fed"), trained)
            new_bs = jax.tree.map(
                lambda t: jax.lax.psum(t * scale, "fed"), new_bs)
            if has_dp:
                # dp replicas hold identical trained params (grads pmean'd
                # per step); the pmean is a numeric no-op that reduces the
                # dp-varying type so the output is replicated
                community = jax.tree.map(
                    lambda t: jax.lax.pmean(t, "dp"), community)
                new_bs = jax.tree.map(
                    lambda t: jax.lax.pmean(t, "dp"), new_bs)
            return community, new_bs, losses[None]

        return jax.jit(fed_round, donate_argnums=(0, 1))

    # ------------------------------------------------------------------ #

    def run_round(self, x_batches: np.ndarray, y_batches: np.ndarray,
                  scales: Optional[np.ndarray] = None
                  ) -> Dict[str, Any]:
        """One federation round.

        ``x_batches``: (L, K, B, ...) per-learner K batches; ``scales``:
        (L,) normalized weights (default uniform).
        """
        L = self.num_learners
        if x_batches.shape[0] != L:
            raise ValueError(f"expected leading learner axis {L}, "
                             f"got {x_batches.shape[0]}")
        if scales is None:
            scales = np.full((L,), 1.0 / L, np.float32)
        scales = np.asarray(scales, np.float32)
        seeds = np.arange(L, dtype=np.uint32) + np.uint32(
            1 + self.global_iteration * L)
        x_sharded = jax.device_put(
            jnp.asarray(x_batches), NamedSharding(self.mesh, self._data_spec))
        y_sharded = jax.device_put(
            jnp.asarray(y_batches), NamedSharding(self.mesh, self._data_spec))
        s_sharded = jax.device_put(
            jnp.asarray(scales), NamedSharding(self.mesh, P("fed")))
        seeds_sharded = jax.device_put(
            jnp.asarray(seeds), NamedSharding(self.mesh, P("fed")))
        t0 = time.perf_counter()
        bs = self.batch_stats if self.batch_stats is not None else {}
        self.params, new_bs, losses = self._round_fn(
            self.params, bs, x_sharded, y_sharded, s_sharded, seeds_sharded)
        if self._robust_combine is not None:
            # second device-resident program: all-gather over fed + sort
            # (or Krum selection); the community model comes back
            # replicated for the next round. ONE call over params AND
            # batch_stats so Krum's per-learner selection stays coherent
            # across the whole model (its scores also span both, matching
            # the host rule's whole-tree flatten)
            packed = self._robust_combine({"p": self.params, "b": new_bs})
            self.params, new_bs = packed["p"], packed["b"]
        if self.batch_stats is not None:
            self.batch_stats = new_bs
        losses = np.asarray(losses)
        duration_ms = (time.perf_counter() - t0) * 1e3
        self.global_iteration += 1
        return {"per_learner_losses": losses,
                "mean_loss": float(np.mean(losses)),
                "round_duration_ms": duration_ms}

    # ------------------------------------------------------------------ #

    def evaluate(self, x: np.ndarray, y: np.ndarray,
                 batch_size: int = 256) -> Dict[str, float]:
        """Evaluate the community model (replicated, so this is one jit call
        per batch on the full mesh)."""
        if self._eval_fn is None:
            loss_fn = self.loss_fn

            def eval_step(params, batch_stats, x, y):
                variables = {"params": params}
                if self.batch_stats is not None:
                    variables["batch_stats"] = batch_stats
                logits = self._apply(variables, x, train=False)
                return loss_fn(logits, y), _accuracy(logits, y)

            self._eval_fn = jax.jit(eval_step)
        bs = self.batch_stats if self.batch_stats is not None else {}
        total_loss = total_acc = count = 0
        for i in range(0, len(x), batch_size):
            xb, yb = x[i:i + batch_size], y[i:i + batch_size]
            loss, acc = self._eval_fn(self.params, bs, jnp.asarray(xb),
                                      jnp.asarray(yb))
            total_loss += float(loss) * len(xb)
            total_acc += float(acc) * len(xb)
            count += len(xb)
        if not count:
            return {}
        return {"loss": total_loss / count, "accuracy": total_acc / count}

    def community_params(self):
        return jax.device_get(self.params)

    def community_variables(self):
        out = {"params": jax.device_get(self.params)}
        if self.batch_stats is not None:
            out["batch_stats"] = jax.device_get(self.batch_stats)
        return out
