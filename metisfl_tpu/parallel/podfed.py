"""Pod-mode federation: the whole round as one SPMD program.

When all learners co-reside on one TPU pod slice, a federation round —
N learners × K local optimizer steps, then weighted FedAvg — compiles to a
SINGLE jit-compiled XLA program shard_mapped over the ``fed`` mesh axis:

- learner *i*'s params/data live on mesh slice ``fed=i``;
- local training is a ``lax.scan`` of SGD steps (MXU-friendly, no host);
- aggregation is a weighted ``psum`` over ``fed`` riding ICI;
- the community model comes out replicated: next round starts immediately.

This is the TPU-native answer to the reference's proto-gRPC weight shipping
(BASELINE.json north star: ≤2 s aggregation/round @ 64 learners) — the
controller shrinks to round bookkeeping around one XLA call. Inner axes
(dp/tp/...) compose: pass a mesh with extra axes and per-param rules.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from metisfl_tpu.comm.messages import TrainParams
from metisfl_tpu.models.ops import _LOSSES
from metisfl_tpu.models.optimizers import make_optimizer
from metisfl_tpu.parallel.mesh import federation_mesh


class PodFederation:
    """N co-resident learners on one mesh; rounds are single XLA calls."""

    def __init__(
        self,
        module,
        sample_input: np.ndarray,
        num_learners: int,
        train_params: Optional[TrainParams] = None,
        loss: str | Callable = "softmax_cross_entropy",
        mesh: Optional[Mesh] = None,
        rng_seed: int = 0,
    ):
        self.module = module
        self.num_learners = num_learners
        self.train_params = train_params or TrainParams()
        self.loss_fn = _LOSSES[loss] if isinstance(loss, str) else loss
        self.mesh = mesh or federation_mesh(num_learners)
        if self.mesh.shape["fed"] != num_learners:
            raise ValueError(
                f"mesh fed axis {self.mesh.shape['fed']} != {num_learners}")
        rng = jax.random.PRNGKey(rng_seed)
        variables = module.init(rng, jnp.asarray(sample_input))
        self.params = jax.device_put(
            variables["params"], NamedSharding(self.mesh, P()))
        self._tx = make_optimizer(self.train_params.optimizer,
                                  self.train_params.learning_rate,
                                  self.train_params.optimizer_kwargs)
        self._round_fn = self._build_round()
        self.global_iteration = 0

    # ------------------------------------------------------------------ #

    def _build_round(self):
        tx = self._tx
        loss_fn = self.loss_fn
        module = self.module
        mesh = self.mesh

        def local_train(params, x_steps, y_steps):
            """K local steps via lax.scan. x_steps: (K, B, ...)"""
            opt_state = tx.init(params)

            def step(carry, batch):
                params, opt_state = carry
                x, y = batch

                def loss_of(p):
                    logits = module.apply({"params": p}, x)
                    return loss_fn(logits, y)

                loss, grads = jax.value_and_grad(loss_of)(params)
                updates, opt_state = tx.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), loss

            (params, _), losses = jax.lax.scan(step, (params, opt_state),
                                               (x_steps, y_steps))
            return params, losses

        @functools.partial(
            jax.shard_map, mesh=mesh,
            in_specs=(P(), P("fed"), P("fed"), P("fed")),
            out_specs=(P(), P("fed")),
        )
        def fed_round(community, x, y, scales):
            # this shard sees its own learner's data: leading axis 1
            params = community
            trained, losses = local_train(params, x[0], y[0])
            scale = scales[0]
            community = jax.tree.map(
                lambda t: jax.lax.psum(t * scale, "fed"), trained)
            return community, losses[None]

        return jax.jit(fed_round, donate_argnums=(0,))

    # ------------------------------------------------------------------ #

    def run_round(self, x_batches: np.ndarray, y_batches: np.ndarray,
                  scales: Optional[np.ndarray] = None
                  ) -> Dict[str, Any]:
        """One federation round.

        ``x_batches``: (L, K, B, ...) per-learner K batches; ``scales``:
        (L,) normalized weights (default uniform).
        """
        L = self.num_learners
        if x_batches.shape[0] != L:
            raise ValueError(f"expected leading learner axis {L}, "
                             f"got {x_batches.shape[0]}")
        if scales is None:
            scales = np.full((L,), 1.0 / L, np.float32)
        scales = np.asarray(scales, np.float32)
        x_sharded = jax.device_put(
            jnp.asarray(x_batches), NamedSharding(self.mesh, P("fed")))
        y_sharded = jax.device_put(
            jnp.asarray(y_batches), NamedSharding(self.mesh, P("fed")))
        s_sharded = jax.device_put(
            jnp.asarray(scales), NamedSharding(self.mesh, P("fed")))
        self.params, losses = self._round_fn(self.params, x_sharded,
                                             y_sharded, s_sharded)
        self.global_iteration += 1
        return {"per_learner_losses": np.asarray(losses),
                "mean_loss": float(np.mean(np.asarray(losses)))}

    def community_params(self):
        return jax.device_get(self.params)
