"""Model-level pipeline parallelism: LlamaLite's decoder stack over ``pp``.

Bridges the zoo transformer to :mod:`metisfl_tpu.parallel.pipeline`: the
depth-D block stack is cut into S equal stages (one per device along the
``pp`` mesh axis), each stage applying D/S decoder blocks; embedding, final
norm, and the LM head run replicated outside the pipeline (they are a small
fraction of the FLOPs — the per-block compute is what doesn't fit one
device). Stage parameters are the ORIGINAL LlamaLite parameters restacked,
so a checkpoint trained either way loads into both layouts.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

from metisfl_tpu.parallel.pipeline import pipeline_apply, stack_stage_params

Pytree = Any


def split_lm_params(variables: Pytree, num_stages: int) -> Tuple[Pytree, Pytree]:
    """LlamaLite variables → (non-block params, stage-stacked block params).

    Blocks ``block_i`` are grouped into ``num_stages`` contiguous stages;
    within a stage the per-block trees are stacked on a second leading axis
    so one ``stage_fn`` scan applies them in order.
    """
    params = variables["params"]
    block_names = sorted((k for k in params if k.startswith("block_")),
                        key=lambda k: int(k.split("_")[1]))
    depth = len(block_names)
    if depth % num_stages:
        raise ValueError(f"depth {depth} not divisible by {num_stages} stages")
    per_stage = depth // num_stages
    rest = {k: v for k, v in params.items() if not k.startswith("block_")}
    stages = []
    for s in range(num_stages):
        blocks = [params[block_names[s * per_stage + j]]
                  for j in range(per_stage)]
        stages.append(jax.tree.map(lambda *xs: jnp.stack(xs), *blocks))
    return rest, stack_stage_params(stages)


def pipelined_lm_apply(module, variables: Pytree, tokens, mesh,
                       num_microbatches: int, axis: str = "pp"):
    """Forward pass of a zoo ``LlamaLite`` with its block stack pipelined.

    Equals ``module.apply(variables, tokens)`` exactly (same parameters,
    same math, any compute dtype) — verified in tests — while each device
    only holds and runs its own stage's blocks. ``sp_mesh`` modules are
    rejected (pp x sp composition is not implemented).

    MoE caveat: expert capacity is computed over the routing pool, and the
    pipeline routes per MICROBATCH — with ``num_microbatches > 1`` a
    capacity-dropped token may differ from the full-batch apply (exact
    equality holds at ``num_microbatches=1``, and always for dense FFNs).
    """
    import flax.linen as nn

    from metisfl_tpu.models.zoo.transformer import DecoderBlock

    if module.sp_mesh is not None:
        raise ValueError(
            "pipelined_lm_apply does not support sp_mesh modules: the ring "
            "schedule's sp axis would be silently dropped (plain full "
            "attention per block). Pipeline with sp disabled, or use the "
            "sp path alone (parallel/ringattn.py).")
    rest, stacked = split_lm_params(variables, mesh.shape[axis])
    block = DecoderBlock(module.dim, module.heads,
                         lora_rank=module.lora_rank,
                         use_flash=module.use_flash,
                         moe_experts=module.moe_experts,
                         moe_top_k=module.moe_top_k,
                         kv_heads=module.kv_heads,
                         dtype=module.dtype)

    def stage_fn(stage_params, h):
        def apply_one(h, block_params):
            return block.apply({"params": block_params}, h), None
        h, _ = jax.lax.scan(apply_one, h, stage_params)
        return h

    embed = rest["embed"]["embedding"]
    x = jnp.take(embed, tokens, axis=0)
    if module.dtype is not None:
        x = x.astype(module.dtype)
    x = pipeline_apply(stage_fn, stacked, x, mesh, num_microbatches, axis)
    # final norm + fp32 head, via the SAME flax modules LlamaLite.__call__
    # uses (re-implementing the math inline would silently drift on any
    # flax default change)
    x = nn.RMSNorm(dtype=module.dtype).apply(
        {"params": rest["RMSNorm_0"]}, x)
    return x.astype(jnp.float32) @ rest["lm_head"]["kernel"]
