"""Tunnel watcher: probe the axon TPU tunnel until it serves, then capture
the device bench sections (headline MFU first), riding out mid-capture
wedges by falling back to probing and resuming the remaining work.

The axon tunnel in this environment serves in windows of minutes between
long outages (rounds 1-3 never landed a driver-channel TPU number because
of it). bench.py's own run probes opportunistically within one bench
window; this watcher turns that into a standing hunt so a revival at ANY
point lands the on-chip numbers. Work items are fine-grained — each MFU
sweep variant is its own item — so a second wedge never forfeits what a
brief serving window already measured.

Usage: python scripts/tpu_watch.py [hours] [out.json]
State: writes {"phase": "waiting"|"capturing"|"done", ...} to
scripts/tpu_watch_state.json after every transition so the build loop can
see where it is without attaching.
"""
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
os.chdir(_REPO)

import bench  # noqa: E402

# the watcher and a concurrently-running `python bench.py` must not fight
# over the same crash-recovery snapshot file
bench._PARTIAL_PATH = os.path.join(_REPO, "scripts",
                                   "tpu_watch_partial.json")

_STATE_PATH = os.path.join(_REPO, "scripts", "tpu_watch_state.json")
_PROBE_SECS = 90
_PROBE_INTERVAL = 150
_MAX_ATTEMPTS = 2


def _state(phase, **kw):
    kw.update({"phase": phase, "ts": time.time()})
    tmp = _STATE_PATH + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(kw, fh)
    os.replace(tmp, _STATE_PATH)


def _items():
    # headline first: MFU is the round's missing number, cheapest/most
    # likely-to-win variants leading; agg re-captures cheaply after; the
    # product-loop round (e2e) and decode/flash/train follow; the
    # 1.2B-param lora compile is the likeliest wedge trigger so it goes
    # LAST — a wedge there forfeits nothing already banked
    items = [f"mfu:{label}" for label, _ in bench._MFU_VARIANTS]
    items += ["agg", "e2e", "flash", "train", "decode", "lora"]
    return items


def _run_item(item, details, errors, info):
    """Run one work item; return True when it needs no further attempts."""
    if item.startswith("mfu:"):
        label = item.split(":", 1)[1]
        err_key = f"mfu.{label}"
        errors.pop(err_key, None)  # stale error from a prior attempt
        errors.pop(err_key + "_tunnel", None)
        out = bench._run_section(
            "mfu", False, bench._MFU_VARIANT_TIMEOUT, errors, info,
            variant=label, err_key=err_key)
        for key, value in out.items():
            details["mfu_backend" if key == "backend" else key] = value
        # measured, or failed with a real in-child error (a retry through
        # the same code will fail the same way)
        return (f"lm_{label}_ms_per_step" in details
                or f"lm_{label}_error" in details)
    # _run_and_record owns stale-error clearing, backend attribution, and
    # the keep-partials-on-failure merge
    bench._run_and_record(item, False, details, errors, info,
                          keep_existing_on_error=True)
    return details.get(f"{item}_backend") == "tpu"


def main():
    hours = float(sys.argv[1]) if len(sys.argv) > 1 else 10.0
    out_path = sys.argv[2] if len(sys.argv) > 2 else os.path.join(
        _REPO, "bench_results", "tpu_v5e_round5_watch.json")
    deadline = time.time() + hours * 3600
    info = {"orig_platforms": os.environ.get("JAX_PLATFORMS") or "axon",
            "degraded_to_cpu": True, "last_dead_ts": 0.0}
    details = bench._PARTIAL["details"]
    errors = bench._PARTIAL["errors"]
    pending = _items()
    attempts = {}
    probes = 0
    while pending and time.time() < deadline:
        # --- probe until the tunnel serves -----------------------------
        while info.get("degraded_to_cpu") and time.time() < deadline:
            probes += 1
            _state("waiting", probes=probes, pending=pending)
            if bench.try_recover_backend(info, timeout=_PROBE_SECS):
                break
            time.sleep(_PROBE_INTERVAL)
        if info.get("degraded_to_cpu"):
            break  # deadline hit while waiting
        # --- capture until done or wedged again ------------------------
        while pending and not info.get("degraded_to_cpu") \
                and time.time() < deadline:
            item = pending[0]
            _state("capturing", item=item, probes=probes, pending=pending)
            done = _run_item(item, details, errors, info)
            err = errors.get(_err_key(item))
            timed_out = err is not None and \
                err.startswith("section timed out")
            if not _measured(item, details) \
                    and not info.get("degraded_to_cpu") and not timed_out:
                # a failure with no measurement can be the tunnel dying
                # FAST (raising instead of hanging): confirm it is alive
                # before charging an attempt, else a dead tunnel drains
                # the whole pending list in minutes and the hunt ends
                # with hours left. Timeouts skip this — _run_section's
                # kill path already probed.
                if not bench._probe_backend_alive():
                    info["degraded_to_cpu"] = True
                    info["last_dead_ts"] = time.time()
            if info.get("degraded_to_cpu") and not _measured(item, details):
                if item.startswith("mfu:"):
                    # an UNAVAILABLE recorded as a terminal variant error
                    # is outage noise, not a code error — retry on revival
                    details.pop(f"lm_{item.split(':', 1)[1]}_error", None)
                # attempt uncharged — but ROTATE to the back: if this
                # item's own compile is what wedges the tunnel, keeping it
                # at the front would burn every future serving window on
                # it and never reach the rest of the list
                pending.remove(item)
                pending.append(item)
            else:
                attempts[item] = attempts.get(item, 0) + 1
                if done or attempts[item] >= _MAX_ATTEMPTS:
                    pending.remove(item)
                else:
                    # failed while the tunnel is confirmed alive: rotate
                    # to the back
                    pending.remove(item)
                    pending.append(item)
            _finalize(details)
            _dump(out_path, details, errors, probes)
    _state("done", pending=pending, probes=probes)
    _finalize(details)
    _dump(out_path, details, errors, probes)
    print(json.dumps({"pending": pending, "probes": probes}))
    return 0 if not pending else 1


def _err_key(item):
    return f"mfu.{item.split(':', 1)[1]}" if item.startswith("mfu:") \
        else item


def _measured(item, details):
    """True when the item has banked an on-chip number."""
    if item.startswith("mfu:"):
        return f"lm_{item.split(':', 1)[1]}_ms_per_step" in details
    return details.get(f"{item}_backend") == "tpu"


def _finalize(details):
    bench._mfu_finalize(details)


def _dump(out_path, details, errors, probes):
    tmp = out_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump({"details": details, "errors": errors,
                   "watch_probes": probes, "ts": time.time()}, fh)
    os.replace(tmp, out_path)


if __name__ == "__main__":
    sys.exit(main())
