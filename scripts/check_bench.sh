#!/usr/bin/env bash
# CI bench-regression gate (ISSUE 7 satellite; docs/SCALE.md).
#
# Compares the newest bench capture against the previous one with the
# direction-aware relative thresholds in `python -m metisfl_tpu.perf`
# and FAILS the build on a regression — the ingest-throughput keys
# (cohort_*_insert_s, cohort_*_insert_models_per_sec, round_10k_wall_s)
# are lower/higher-better classified there, so a slowdown past the
# threshold exits 1.
#
# Usage:
#   scripts/check_bench.sh PREV.json CURR.json [THRESHOLD]
#   scripts/check_bench.sh DIR [THRESHOLD]     # DIR holds BENCH_*.json;
#                                              # compares the last two
#
# Exit codes: 0 clean / improved, 1 regression (build must fail),
# 2 unparseable capture (fails the build too — a capture that cannot be
# judged must not pass silently).
set -u -o pipefail

usage() { sed -n '2,15p' "$0"; exit 2; }

PYTHON="${PYTHON:-python}"
THRESHOLD=""

case "$#" in
  1) TARGET_DIR="$1" ;;
  2) if [ -d "$1" ]; then TARGET_DIR="$1"; THRESHOLD="$2";
     else PREV="$1"; CURR="$2"; fi ;;
  3) PREV="$1"; CURR="$2"; THRESHOLD="$3" ;;
  *) usage ;;
esac

if [ -n "${TARGET_DIR:-}" ]; then
  # newest two captures by name order (BENCH_r01.json < BENCH_r02.json ...)
  mapfile -t CAPTURES < <(ls "$TARGET_DIR"/BENCH_*.json 2>/dev/null | sort)
  if [ "${#CAPTURES[@]}" -lt 2 ]; then
    echo "check_bench: need >= 2 BENCH_*.json captures in $TARGET_DIR," \
         "found ${#CAPTURES[@]} — nothing to compare (pass)" >&2
    exit 0
  fi
  PREV="${CAPTURES[-2]}"
  CURR="${CAPTURES[-1]}"
fi

echo "check_bench: $PREV -> $CURR (threshold ${THRESHOLD:-default})"
if [ -n "$THRESHOLD" ]; then
  "$PYTHON" -m metisfl_tpu.perf --compare "$PREV" "$CURR" \
    --threshold "$THRESHOLD"
else
  "$PYTHON" -m metisfl_tpu.perf --compare "$PREV" "$CURR"
fi
rc=$?
case "$rc" in
  0) echo "check_bench: PASS (no regression past threshold)" ;;
  1) echo "check_bench: FAIL — bench regression (see rows above)" >&2 ;;
  2) echo "check_bench: FAIL — unparseable capture (a result that" \
          "cannot be judged must not pass)" >&2 ;;
  *) echo "check_bench: FAIL — perf CLI exited $rc" >&2 ;;
esac
exit "$rc"
