#!/usr/bin/env bash
# CI churn-tolerance gate (ISSUE 9 satellite; docs/RESILIENCE.md
# "Cross-device churn").
#
# Runs the seeded cross-device churn scenario — 1024 virtual clients,
# per-round sampling at quorum, 30% per-round dropout plus one flapping
# and one partitioned learner — AND the no-churn same-seed control, then
# fails the build when any round fails to complete or the final accuracy
# drifts past the tolerance from the control run. Deterministic fault
# schedule (fixed seed), finishes in well under 60 s on one CPU core:
# churn tolerance is gated exactly like bench regressions are by
# scripts/check_bench.sh.
#
# ISSUE 10 additions, gated in the same run:
#  - SLO alert lifecycle (--alert-smoke): the partition fault must trip
#    the dispatch_retries_total rate rule AND the drained run must
#    resolve it (firing -> resolved, end to end), while the same-seed
#    no-churn control stays silent — alerting that cannot fire, or
#    cannot resolve, fails the build;
#  - cardinality budget (--budget 256): the run's per-learner metric
#    families serve sketches past the budget, proving the exposition
#    stays bounded under churn.
#
# Usage:
#   scripts/chaos_smoke.sh                  # the pinned CI scenario
#   scripts/chaos_smoke.sh --clients 256    # any crossdevice CLI override
#
# Exit codes: 0 all rounds completed within tolerance and the alert
# lifecycle proved out, 1 a round failed / halted / accuracy drifted /
# alert did not fire+resolve (or fired in the control), 2 harness
# crashed (fails the build too).
set -u -o pipefail

PYTHON="${PYTHON:-python}"

# CPU-pinned and time-bounded: the harness measures scheduling, not
# accelerator math, and a wedged run must fail, not hang the build.
JAX_PLATFORMS=cpu timeout -k 10 120 "$PYTHON" -m metisfl_tpu.driver.crossdevice \
  --clients 1024 --rounds 5 --quorum 12 --dropout 0.3 --seed 7 \
  --tolerance 0.2 --budget 256 --alert-smoke "$@"
rc=$?
case "$rc" in
  0) echo "chaos_smoke: PASS (all rounds completed at quorum, accuracy" \
          "within tolerance of the no-churn control, alert fired and" \
          "resolved under churn and stayed silent in the control)" ;;
  1) echo "chaos_smoke: FAIL — a round failed/halted, accuracy drifted" \
          "past tolerance, or the alert lifecycle did not prove out" \
          "(see JSON above)" >&2 ;;
  *) echo "chaos_smoke: FAIL — harness crashed or timed out (rc=$rc)" >&2
     rc=2 ;;
esac
[ "$rc" -eq 0 ] || exit "$rc"

# ISSUE 12 slice-kill gate (docs/RESILIENCE.md "Distributed slice
# aggregators"): three real slice-aggregator subprocesses over gRPC, one
# SIGKILLed mid-round. The build fails unless the round completes
# without operator action, slice_rehomed fires (and stays silent in the
# control), and the community model is BIT-IDENTICAL to the same-seed
# undisturbed run.
JAX_PLATFORMS=cpu timeout -k 10 180 "$PYTHON" -m metisfl_tpu.driver.crossdevice \
  --slice-smoke --slices 3 --seed 7
rc=$?
case "$rc" in
  0) echo "chaos_smoke: slice-kill PASS (aggregator killed mid-round," \
          "slice re-homed, round completed, community model bit-identical" \
          "to the no-kill control)" ;;
  1) echo "chaos_smoke: slice-kill FAIL — re-homing did not complete the" \
          "round or the community model diverged from the control (see" \
          "JSON above)" >&2 ;;
  *) echo "chaos_smoke: slice-kill FAIL — smoke crashed or timed out" \
          "(rc=$rc)" >&2
     rc=2 ;;
esac
[ "$rc" -eq 0 ] || exit "$rc"

# ISSUE 20 secure-aggregation gate (docs/SECURITY.md "Secure
# aggregation at scale"): a real-gRPC federation under scheme=masking
# composed with distributed slice aggregators AND streaming
# fold-on-arrival, one learner SIGKILLed with its masked uplink in the
# air. The build fails unless every round completes via dropout
# settlement (seed-share disclosure from a survivor), the masks cancel
# (each round-pinned community equals the same-seed PLAIN control run
# within the fixed-point tolerance), and the control emits zero
# secure_* events.
JAX_PLATFORMS=cpu timeout -k 10 420 "$PYTHON" -m metisfl_tpu.driver.crossdevice \
  --secure-smoke --seed 7 --timeout 150
rc=$?
case "$rc" in
  0) echo "chaos_smoke: secure-agg PASS (learner SIGKILLed mid-uplink," \
          "round settled via mask recovery, community equals the plain" \
          "control within fixed-point tolerance, control secure-silent)" ;;
  1) echo "chaos_smoke: secure-agg FAIL — a round did not settle, masks" \
          "failed to cancel against the plain control, or the control" \
          "emitted secure events (see JSON above)" >&2 ;;
  *) echo "chaos_smoke: secure-agg FAIL — smoke crashed or timed out" \
          "(rc=$rc)" >&2
     rc=2 ;;
esac
[ "$rc" -eq 0 ] || exit "$rc"

# ISSUE 11 fleet-tail gate (docs/OBSERVABILITY.md "Fleet fabric"): a
# three-peer real-gRPC fleet with one flapping learner — the collector
# must keep assembling the merged view while the peer is down (stale
# marked, collection never raises, the peer recovers on relaunch) and
# the mean incremental poll must stay under the pinned 400 ms bound.
JAX_PLATFORMS=cpu timeout -k 10 60 "$PYTHON" -m metisfl_tpu.telemetry \
  --fabric-smoke --budget-ms 400
rc=$?
case "$rc" in
  0) echo "chaos_smoke: fleet-tail PASS (stale marked + recovered under" \
          "flap, merged view never dropped, poll overhead within bound)" ;;
  1) echo "chaos_smoke: fleet-tail FAIL — the collector dropped the" \
          "merged view under flap or blew the poll budget (see JSON" \
          "above)" >&2 ;;
  *) echo "chaos_smoke: fleet-tail FAIL — smoke crashed or timed out" \
          "(rc=$rc)" >&2
     rc=2 ;;
esac
[ "$rc" -eq 0 ] || exit "$rc"

# ISSUE 15 serving-fleet replica-kill gate (docs/DEPLOYMENT.md "Serving
# fleet"): three real gateway-replica subprocesses over gRPC behind the
# consistent-hash router, live canary traffic, one replica SIGKILLed
# mid-canary. The build fails unless ZERO requests drop (the router
# drains around the corpse with bounded retry to the next hash owner),
# the router marks the replica dead, every key's replies stay on one
# canary channel, the surviving replicas roll to the mid-run promotion,
# and the relaunched replica re-pins to the promoted version.
JAX_PLATFORMS=cpu timeout -k 10 180 "$PYTHON" -m metisfl_tpu.serving \
  --fleet-smoke --smoke-replicas 3
rc=$?
case "$rc" in
  0) echo "chaos_smoke: replica-kill PASS (replica SIGKILLed mid-canary," \
          "zero requests dropped, router drained around it, channels" \
          "stayed coherent, relaunch re-pinned to the promoted version)" ;;
  1) echo "chaos_smoke: replica-kill FAIL — requests dropped, channels" \
          "mixed, or the relaunch did not re-pin (see JSON above)" >&2 ;;
  *) echo "chaos_smoke: replica-kill FAIL — smoke crashed or timed out" \
          "(rc=$rc)" >&2
     rc=2 ;;
esac
[ "$rc" -eq 0 ] || exit "$rc"

# ISSUE 13 continuous-profiling overhead gate (docs/OBSERVABILITY.md
# "Continuous profiling"): the bench round loop with the sampler (67 Hz
# default) + instrumented locks ON vs OFF, interleaved trials, minima
# judged. The build fails when profiling costs more than the pinned 3%
# bound, when the sampler collects nothing, or when the fold kernel's
# frame never appears in the profile (a blind profiler gates nothing).
JAX_PLATFORMS=cpu timeout -k 10 120 "$PYTHON" -m metisfl_tpu.telemetry \
  --prof-smoke --bound-pct 3
rc=$?
case "$rc" in
  0) echo "chaos_smoke: prof-overhead PASS (sampler + lock telemetry" \
          "within the 3% bound, hot frames visible in the profile)" ;;
  1) echo "chaos_smoke: prof-overhead FAIL — profiling overhead past the" \
          "bound or the sampler ran blind (see JSON above)" >&2 ;;
  *) echo "chaos_smoke: prof-overhead FAIL — smoke crashed or timed out" \
          "(rc=$rc)" >&2
     rc=2 ;;
esac
[ "$rc" -eq 0 ] || exit "$rc"

# ISSUE 16 causal-tracing gate (docs/OBSERVABILITY.md "Causal tracing"):
# two same-seed synthetic rounds — one with a slowed learner, one
# control — walked by the critical-path analyzer. The build fails when
# the slow run's dominant edge is not the slowed learner's train span,
# when the control attributes a dominant learner at all, when chain
# coverage drops under 90% of round wall-clock, when the orphan lint
# trips outside the spans_lost budget, or when per-RPC context
# propagation costs more than the pinned 50 µs.
JAX_PLATFORMS=cpu timeout -k 10 60 "$PYTHON" -m metisfl_tpu.telemetry \
  --causal-smoke --overhead-budget-ns 50000
rc=$?
case "$rc" in
  0) echo "chaos_smoke: causal-trace PASS (slowed learner named dominant" \
          "edge, control unattributed, chain coverage >= 90%, no orphan" \
          "spans, propagation overhead within budget)" ;;
  1) echo "chaos_smoke: causal-trace FAIL — wrong/missing dominant edge," \
          "coverage or orphan lint failed, or propagation overhead past" \
          "budget (see JSON above)" >&2 ;;
  *) echo "chaos_smoke: causal-trace FAIL — smoke crashed or timed out" \
          "(rc=$rc)" >&2
     rc=2 ;;
esac
[ "$rc" -eq 0 ] || exit "$rc"

# ISSUE 19 accelerator-runtime gate (docs/OBSERVABILITY.md "Runtime
# observability"): the bench round loop plus a continuous-batching
# decode burst under the XLA compile listener. The build fails when any
# steady-state (post-warmup) compile fires on either path, when a
# deliberately shape-shifting control run does NOT trip the recompile
# detector (+ its storm event), or when the monitored_jit wrapper costs
# more than the pinned 50 µs per steady-state call.
JAX_PLATFORMS=cpu timeout -k 10 240 "$PYTHON" -m metisfl_tpu.telemetry \
  --runtime-smoke --overhead-budget-ns 50000
rc=$?
case "$rc" in
  0) echo "chaos_smoke: runtime PASS (zero steady-state compiles on the" \
          "round + decode paths, the recompile detector provably fires," \
          "wrapper overhead within budget)" ;;
  1) echo "chaos_smoke: runtime FAIL — a steady-state recompile, a blind" \
          "detector, or wrapper overhead past budget (see JSON above)" >&2 ;;
  *) echo "chaos_smoke: runtime FAIL — smoke crashed or timed out" \
          "(rc=$rc)" >&2
     rc=2 ;;
esac
[ "$rc" -eq 0 ] || exit "$rc"

# ISSUE 17 controller-kill gate (docs/RESILIENCE.md "Controller
# hot-standby"): a real-gRPC federation with a warm --standby tailing
# the round-state WAL; the seeded injector SIGKILLs the controller on
# its first MarkTaskCompleted — mid-round, with uplinks in the air. The
# build fails unless the standby promotes itself (controller_failover
# fired from BOTH the promoted process and the driver's handoff), every
# round completes without operator action, the same-seed undisturbed
# control run stays failover-silent, and each round's community model
# is bit-identical between the two runs.
JAX_PLATFORMS=cpu timeout -k 10 420 "$PYTHON" -m metisfl_tpu.driver.crossdevice \
  --controller-smoke --rounds 3 --seed 7 --timeout 240
rc=$?
case "$rc" in
  0) echo "chaos_smoke: controller-kill PASS (standby promoted, failover" \
          "events from both roles, all rounds completed, community model" \
          "bit-identical to the undisturbed control)" ;;
  1) echo "chaos_smoke: controller-kill FAIL — no promotion, missing" \
          "failover events, a noisy control run, or a bit-level model" \
          "divergence (see JSON above)" >&2 ;;
  *) echo "chaos_smoke: controller-kill FAIL — smoke crashed or timed" \
          "out (rc=$rc)" >&2
     rc=2 ;;
esac
exit "$rc"
